#!/usr/bin/env python
"""Render request-trace reports from a flight-recorder dump or a run
ledger: per-request critical-path breakdown, top-k slow requests, and
per-replica flush timelines.

Inputs (auto-detected):

- a **recorder dump** — the JSON ``GET /tracez?full=1`` returns
  (``{"traces": [...], "batches": [...], "ops": [...]}``; save it with
  ``curl .../tracez?full=1 > dump.json``).  Richest mode: every trace
  carries its event offsets, so the report decomposes each request's
  latency into **queue wait** (enqueue → flush start) vs **apply**
  (device time, from the batch record) vs **fan-out** (apply end →
  terminal), plus the padding waste (``bucket - rows``).  When the
  fleet telemetry stitched worker-shipped spans into a batch record,
  the report also shows the cross-process chain: which
  ``worker@host`` applied the flush, the exchange's wire RTT, and the
  worker-clock ``worker.apply`` span aligned to the router timeline.
- a **ledger file** — a ``run_<id>.jsonl`` written with the JSONL
  ledger active (``KEYSTONE_OBS_DIR``): ``serve.request`` events carry
  each request's outcome/latency/queue-wait and ``serve.batch``
  span_end lines carry per-flush rows/bucket/replica/seconds with the
  rider request ids as span links.

Usage::

    python tools/trace_report.py dump.json [--top 10] [--json]
    python tools/trace_report.py obs/run_abc.jsonl [--top 10] [--json]

The incident-debugging loop this closes (docs/guide.md): a client
quotes the ``request_id`` echoed in its response → ``GET
/requestz/<id>`` shows the causal chain → this tool says where the
fleet as a whole spends its tail latency.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


# ---------------------------------------------------------------- loading


def load_dump(path: str) -> dict:
    """Normalize a recorder dump into ``{"requests": [...],
    "batches": {id: rec}, "ops": [...]}`` with per-request critical-path
    components."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    batches = {b["batch"]: b for b in data.get("batches", []) if "batch" in b}
    requests = [
        _breakdown_from_trace(tr, batches) for tr in data.get("traces", [])
    ]
    return {
        "source": "recorder",
        "requests": [r for r in requests if r is not None],
        "batches": batches,
        "ops": data.get("ops", []),
    }


def _first_event(trace: dict, name: str) -> Optional[dict]:
    for e in trace.get("events", []):
        if e.get("name") == name:
            return e
    return None


def _fleet_from_batch(b: Optional[dict]) -> dict:
    """Pull the worker-shipped stitching (``FleetTelemetry._ingest``'s
    ``batch_update``) out of a batch record: who applied it, the wire
    accounting around the exchange, and the router-aligned worker
    spans.  Absent for local-replica flushes and pre-fleet dumps — every
    field degrades to ``None`` so old dumps render unchanged."""
    wire = (b or {}).get("wire") or {}
    spans = (b or {}).get("worker_spans") or []
    worker_apply = None
    for sp in spans:
        if isinstance(sp, dict) and sp.get("name") == "worker.apply":
            worker_apply = sp.get("seconds")
            break
    return {
        "worker": (b or {}).get("worker"),
        "host": (b or {}).get("host"),
        "wire_rtt_s": wire.get("rtt_s"),
        "wire_send_s": wire.get("send_s"),
        "wire_recv_s": wire.get("recv_s"),
        "worker_apply_s": worker_apply,
        "worker_spans": [
            {
                "name": sp.get("name"),
                "t_off": sp.get("t_off"),
                "seconds": sp.get("seconds"),
            }
            for sp in spans
            if isinstance(sp, dict)
        ],
    }


def _breakdown_from_trace(trace: dict, batches: Dict[str, dict]) -> Optional[dict]:
    rid = trace.get("request_id")
    if rid is None:
        return None
    total = trace.get("seconds")
    rep = _first_event(trace, "serve.batch")
    attrs = (rep or {}).get("attrs") or {}
    queue_wait = attrs.get("queue_wait_seconds")
    bid = attrs.get("batch")
    b = batches.get(bid) if bid is not None else None
    apply_s = (b or {}).get("seconds")
    fanout = None
    if total is not None and rep is not None and apply_s is not None:
        fanout = max(0.0, total - rep["t"] - apply_s)
    pad_rows = None
    if b and b.get("bucket") is not None and b.get("rows") is not None:
        pad_rows = int(b["bucket"]) - int(b["rows"])
    return {
        "request_id": rid,
        "ts": trace.get("ts"),
        "outcome": trace.get("outcome"),
        "slow": trace.get("slow", False),
        "seconds": total,
        "queue_wait_s": queue_wait,
        "apply_s": apply_s,
        "fanout_s": fanout,
        "replica": attrs.get("replica"),
        "batch": bid,
        "pad_rows": pad_rows,
        **_fleet_from_batch(b),
        "events": [e.get("name") for e in trace.get("events", [])],
    }


def load_ledger(path: str) -> dict:
    """Reconstruct the same report shape from a run ledger:
    ``serve.request`` events + ``serve.batch`` span_end lines."""
    requests: List[dict] = []
    batches: Dict[str, dict] = {}
    ops: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn final line must not hide the run
            attrs = e.get("attrs") or {}
            kind, name = e.get("kind"), e.get("name")
            if kind == "event" and name == "serve.request":
                requests.append(
                    {
                        "request_id": attrs.get("request_id"),
                        "ts": e.get("ts"),
                        "outcome": attrs.get("outcome"),
                        "slow": False,
                        "seconds": attrs.get("seconds"),
                        "queue_wait_s": attrs.get("queue_wait_seconds"),
                        "apply_s": None,  # joined below via the batch
                        "fanout_s": None,
                        "replica": attrs.get("replica"),
                        "batch": attrs.get("batch"),
                        "pad_rows": None,
                        **_fleet_from_batch(None),
                        "events": [],
                        "error": attrs.get("error"),
                    }
                )
            elif kind == "span_end" and name == "serve.batch":
                bid = attrs.get("batch")
                if bid is not None:
                    batches[bid] = {
                        "batch": bid,
                        "ts": e.get("ts"),
                        "seconds": e.get("seconds"),
                        "rows": attrs.get("rows"),
                        "bucket": attrs.get("bucket"),
                        "replica": attrs.get("replica"),
                        "request_ids": attrs.get("request_ids") or [],
                    }
            elif kind == "span_end" and name == "serve.swap":
                ops.append(
                    {"ts": e.get("ts"), "name": name, **attrs}
                )
    for r in requests:
        b = batches.get(r["batch"]) if r["batch"] is not None else None
        if b is not None:
            r["apply_s"] = b.get("seconds")
            if b.get("bucket") is not None and b.get("rows") is not None:
                r["pad_rows"] = int(b["bucket"]) - int(b["rows"])
            if (
                r["seconds"] is not None
                and r["queue_wait_s"] is not None
                and r["apply_s"] is not None
            ):
                r["fanout_s"] = max(
                    0.0, r["seconds"] - r["queue_wait_s"] - r["apply_s"]
                )
    return {
        "source": "ledger",
        "requests": requests,
        "batches": batches,
        "ops": ops,
    }


def load(path: str) -> dict:
    """Auto-detect the input: ledger mode for anything named ``.jsonl``
    INCLUDING rotated segments (``run_<id>.jsonl.000001`` — the
    size-cap rotation this tool ships alongside), recorder-dump mode
    otherwise."""
    if ".jsonl" in os.path.basename(path):
        return load_ledger(path)
    return load_dump(path)


# -------------------------------------------------------------- summarize


def _mean(vals: List[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def summarize(data: dict, top: int = 10, timeline: int = 25) -> dict:
    """The report dict: outcome counts, critical-path aggregates, top-k
    slow requests, and per-replica flush timelines."""
    reqs = data["requests"]
    outcomes: Dict[str, int] = {}
    for r in reqs:
        outcomes[r["outcome"] or "open"] = outcomes.get(r["outcome"] or "open", 0) + 1
    finished = [r for r in reqs if r["seconds"] is not None]
    top_slow = sorted(finished, key=lambda r: -r["seconds"])[: max(1, top)]
    critical = {
        "queue_wait_s": _mean([r["queue_wait_s"] for r in finished]),
        "apply_s": _mean([r["apply_s"] for r in finished]),
        "worker_apply_s": _mean([r.get("worker_apply_s") for r in finished]),
        "wire_rtt_s": _mean([r.get("wire_rtt_s") for r in finished]),
        "fanout_s": _mean([r["fanout_s"] for r in finished]),
        "pad_rows": _mean(
            [r["pad_rows"] for r in finished if r["pad_rows"] is not None]
        ),
        "seconds": _mean([r["seconds"] for r in finished]),
    }
    # per-worker rollup of the stitched exchanges: batch records carry
    # the shipping, so aggregate over batches (one entry per flush) to
    # avoid multiply-counting a flush once per rider
    workers: Dict[str, dict] = {}
    for b in data["batches"].values():
        w = b.get("worker")
        if w is None:
            continue
        f = _fleet_from_batch(b)
        agg = workers.setdefault(
            str(w),
            {"host": f["host"], "flushes": 0, "apply_s": [], "rtt_s": []},
        )
        agg["flushes"] += 1
        if f["worker_apply_s"] is not None:
            agg["apply_s"].append(f["worker_apply_s"])
        if f["wire_rtt_s"] is not None:
            agg["rtt_s"].append(f["wire_rtt_s"])
    fleet = {
        w: {
            "host": agg["host"],
            "flushes": agg["flushes"],
            "apply_s_mean": _mean(agg["apply_s"]),
            "wire_rtt_s_mean": _mean(agg["rtt_s"]),
        }
        for w, agg in sorted(workers.items())
    }
    timelines: Dict[str, List[dict]] = {}
    for b in sorted(data["batches"].values(), key=lambda b: b.get("ts") or 0):
        rep = str(b.get("replica"))
        timelines.setdefault(rep, []).append(
            {
                "batch": b["batch"],
                "ts": b.get("ts"),
                "rows": b.get("rows"),
                "bucket": b.get("bucket"),
                "seconds": b.get("seconds"),
                "riders": len(b.get("request_ids") or []),
            }
        )
    for rep in timelines:
        timelines[rep] = timelines[rep][-max(1, timeline):]
    return {
        "source": data["source"],
        "requests": len(reqs),
        "outcomes": outcomes,
        "critical_path_mean": critical,
        "top_slow": [
            {k: v for k, v in r.items() if k != "events"} for r in top_slow
        ],
        "fleet": fleet,
        "replica_timelines": timelines,
        "ops": data["ops"][-max(1, top):],
    }


def render(summary: dict) -> str:
    ms = lambda v: "-" if v is None else f"{1000.0 * v:8.2f}ms"  # noqa: E731
    lines = [
        f"trace report ({summary['source']}): "
        f"{summary['requests']} requests",
        "outcomes: "
        + ", ".join(f"{k}={v}" for k, v in sorted(summary["outcomes"].items())),
        "critical path (mean): "
        + " | ".join(
            f"{k.replace('_s', '')} {ms(v) if k != 'pad_rows' else v}"
            for k, v in summary["critical_path_mean"].items()
        ),
        "",
        f"top {len(summary['top_slow'])} slow requests:",
    ]
    for r in summary["top_slow"]:
        line = (
            f"  {r['request_id']}: {ms(r['seconds'])} "
            f"[{r['outcome']}] queue {ms(r['queue_wait_s'])} "
            f"apply {ms(r['apply_s'])} fanout {ms(r['fanout_s'])} "
            f"replica {r['replica']} batch {r['batch']}"
        )
        if r.get("worker") is not None:
            line += (
                f" | worker {r['worker']}@{r.get('host')}"
                f" wire {ms(r.get('wire_rtt_s'))}"
                f" worker-apply {ms(r.get('worker_apply_s'))}"
            )
        lines.append(line)
    lines.append("")
    if summary.get("fleet"):
        lines.append("fleet (worker-shipped spans, stitched per flush):")
        for w, agg in summary["fleet"].items():
            lines.append(
                f"  {w}@{agg['host']}: flushes {agg['flushes']} "
                f"apply {ms(agg['apply_s_mean'])} "
                f"wire rtt {ms(agg['wire_rtt_s_mean'])}"
            )
        lines.append("")
    for rep, tl in sorted(summary["replica_timelines"].items()):
        lines.append(f"replica {rep} timeline (last {len(tl)} flushes):")
        for b in tl:
            lines.append(
                f"  {b['batch']}: rows {b['rows']} / bucket {b['bucket']} "
                f"apply {ms(b['seconds'])} riders {b['riders']}"
            )
    if summary["ops"]:
        lines.append("")
        lines.append("control-plane spans:")
        for o in summary["ops"]:
            extra = {
                k: v for k, v in o.items() if k not in ("ts", "name")
            }
            lines.append(f"  {o.get('name')}: {extra}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-request critical-path report from a flight-"
        "recorder dump (/tracez?full=1) or a run ledger (run_*.jsonl)"
    )
    ap.add_argument("path", help="dump.json or run_<id>.jsonl")
    ap.add_argument("--top", type=int, default=10, help="top-k slow requests")
    ap.add_argument(
        "--timeline", type=int, default=25, help="flushes per replica timeline"
    )
    ap.add_argument("--json", action="store_true", help="emit the report as JSON")
    args = ap.parse_args(argv)
    summary = summarize(load(args.path), top=args.top, timeline=args.timeline)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
