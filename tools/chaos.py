"""Run a pipeline under a fault plan and report per-site outcomes.

The driver half of the chaos contract (keystone_tpu/faults.py injects,
utils/durable.py survives): execute a workload with a KEYSTONE_FAULTS-
grammar plan active and report, per site, how many calls passed through,
how many faults were injected, and whether the workload survived.

Usage (CPU-safe; any laptop)::

    JAX_PLATFORMS=cpu python tools/chaos.py \
        --plan "blockstore.read:every=3:raise;ckpt.save:after=1:times=1:corrupt" \
        --workload bcd --restarts 1

    # or drive your own entry point: any module:function() that runs a fit
    JAX_PLATFORMS=cpu python tools/chaos.py --plan "..." \
        --workload my_pkg.my_module:main

Built-in workloads (synthetic, seconds-scale): ``bcd`` (checkpointed
block coordinate descent), ``ooc`` (out-of-core streamed BCD — spills a
FeatureBlockStore, exercising blockstore.*), ``lbfgs`` (chunk-
checkpointed dense L-BFGS), ``stream`` (a resilient StreamDataset
sweep), ``kernel`` (checkpointed out-of-core kernel BCD — spills a
RowBlockStore and sweeps gram blocks, exercising blockstore.* +
kernel.sweep + ckpt.*), ``nethost`` (a live 2-worker CROSS-HOST TCP
fleet — ``serve/net.py`` — severed by a seeded network partition
mid-wave and required to heal with zero lost futures), ``rollout``
(a guarded canary rollout — ``serve/rollout.py`` — of a bad model
version under the seeded ``poison_flood`` zoo workload from
``tools/workloads.py``: the canary generation must concentrate the
failures, the judge must roll back and quarantine the version in the
registry, the watcher must refuse to redeploy it, and zero futures
may hang across the abandoned staged generation).

Network plans: the ``serve.net.connect``/``serve.net.send``/
``serve.net.recv`` sites take ``drop`` (the frame vanishes — silence,
not an error; ``partition`` is a grammar alias for it, so
``serve.net.send:ctx.link=NAME:partition`` reads as what it does),
``delay``, ``hang``, and ``corrupt`` (a flipped byte the far side's
CRC condemns).  Context-match on ``ctx.link=<worker>`` to sever one
worker's link; both directions (send + recv) make a full partition.

Latency plans (``delay=SECONDS`` / ``hang`` actions) are first-class:
pair them with ``--stage-deadline`` / ``--stream-timeout`` (and
``--stage-retries``) so the deadline/watchdog/breaker layer
(``utils/guard.py``) converts injected stalls into retried or degraded
operations, and the report's ``guard`` section shows deadline hits,
breaker opens, and degraded nodes alongside the per-site fault counts.

Exit code 0 = workload completed under the plan (all injected faults
survived); 1 = the workload failed — the report's ``error`` names the
escaping fault/exception; 2 = the workload completed but a site named
in the plan never injected (``not-exercised`` — a typo'd trigger or a
workload that never reaches the site must not read as a green chaos
run).

**Soak mode** (``--soak SECONDS [--seed N] [--soak-replicas R |
--soak-workers W]``) stands up a live replica fleet (supervisor +
hedging on; ``--soak-workers`` promotes it to a PROCESS fleet and adds
seeded mid-wave worker SIGKILLs to the menu) and loops
seeded randomized multi-site plans over the ``serve.*`` sites — worker
crashes, flush failures, injected delays — submitting a request wave
under each plan and requiring EVERY future to resolve (result or typed
error).  Exit 1 on any hung/lost request, or on a fleet that cannot
serve a clean wave once the soak ends.  The plan sequence is
deterministic in the seed, so a failing soak replays exactly.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from concurrent.futures import TimeoutError as _FutTimeout

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bcd(tmp, restarts):
    import numpy as np

    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset, fit_with_recovery

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 48)).astype(np.float32)
    y = rng.normal(size=(256, 4)).astype(np.float32)
    ckpt = os.path.join(tmp, "bcd-ckpt")

    class CheckpointedBLS(BlockLeastSquaresEstimator):
        def fit_dataset(self, data, labels=None):
            return self.fit_checkpointed(data, labels, checkpoint_dir=ckpt)

    est = CheckpointedBLS(block_size=16, num_iter=4, lam=1e-3)
    fit_with_recovery(
        lambda: est.with_data(Dataset(x), Dataset(y)),
        state_dir=tmp,
        max_restarts=restarts,
    )


def _ooc(tmp, restarts):
    import numpy as np

    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset, StreamDataset, fit_with_recovery

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 48)).astype(np.float32)
    y = rng.normal(size=(256, 4)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-3)
    fit_with_recovery(
        lambda: est.with_data(
            StreamDataset(batched(x, 64), n=x.shape[0]), Dataset(y)
        ),
        max_restarts=restarts,
    )


def _kernel(tmp, restarts):
    """Out-of-core kernel BCD under fault: the row-block spill rides
    blockstore.read/write, each diag step fires kernel.sweep, and the
    per-epoch (α, F) checkpoint rides ckpt.save/load — so a plan over
    any of those proves the sweep resumes from the last completed epoch
    instead of restarting (or worse, trusting torn state)."""
    import numpy as np

    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.models import KernelRidgeRegressionEstimator
    from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator
    from keystone_tpu.workflow import Dataset, StreamDataset, fit_with_recovery

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 16)).astype(np.float32)
    y = rng.normal(size=(128, 2)).astype(np.float32)
    ckpt = os.path.join(tmp, "krr-ckpt")

    class CheckpointedKRR(KernelRidgeRegressionEstimator):
        def fit_dataset(self, data, labels=None):
            return self.fit_stream_dataset(
                data,
                labels,
                spill_dir=os.path.join(tmp, "krr-store"),
                checkpoint_dir=ckpt,
            )

    est = CheckpointedKRR(
        GaussianKernelGenerator(0.05), lam=1e-3, block_size=32, num_epochs=3
    )
    fit_with_recovery(
        lambda: est.with_data(
            StreamDataset(batched(x, 64), n=x.shape[0]), Dataset(y)
        ),
        state_dir=tmp,
        max_restarts=restarts,
    )


def _lbfgs(tmp, restarts):
    import numpy as np

    from keystone_tpu.models.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.workflow import Dataset, fit_with_recovery

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 16)).astype(np.float32)
    y = rng.normal(size=(128, 2)).astype(np.float32)
    ckpt = os.path.join(tmp, "lbfgs-ckpt")

    class CheckpointedLBFGS(DenseLBFGSwithL2):
        def fit_dataset(self, data, labels=None):
            return self.fit_checkpointed(
                data, labels, checkpoint_dir=ckpt, checkpoint_every=3
            )

    est = CheckpointedLBFGS(lam=1e-3, num_iterations=9, history=4)
    fit_with_recovery(
        lambda: est.with_data(Dataset(x), Dataset(y)),
        max_restarts=restarts,
    )


def _stream(tmp, restarts):
    import numpy as np

    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.workflow.dataset import StreamDataset

    from keystone_tpu.utils.guard import env_float

    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 8)).astype(np.float32)
    ds = StreamDataset(
        batched(x, 32),
        n=512,
        retries=3,
        # env_float: "0" means disabled, same as every other guard knob
        timeout=env_float("KEYSTONE_STREAM_TIMEOUT"),
    )
    total = sum(np.asarray(b).shape[0] for b in ds.batches())
    if total != 512:
        raise RuntimeError(f"stream delivered {total}/512 rows")


def _serve_artifacts(tmp, restarts):
    """The AOT artifact ladder under fault: publish a model WITH
    pre-lowered artifacts, then deploy → predict → hot-swap → heal a
    crashed worker, all while the plan batters ``serve.artifact_load``
    (corrupt the blobs, fail the reads, stall them).  The contract
    being proven: a damaged or missing artifact degrades that
    deploy/swap/heal to recompilation — it NEVER fails it, and
    predictions keep flowing."""
    import numpy as np

    from keystone_tpu.serve import ModelRegistry, serve
    from tools.serve_bench import build_pipeline

    dim = 16
    reg = ModelRegistry(os.path.join(tmp, "registry"))
    example = np.zeros((dim,), np.float32)
    for seed in (0, 1):
        pipe = build_pipeline(dim=dim, seed=seed)
        bundle = pipe.freeze().export_artifacts(example=example, buckets=(4, 8))
        reg.publish(pipe, artifacts=bundle)
    fitted, version = reg.load("v0001")
    arts = reg.load_artifacts(version)
    svc = serve(
        fitted,
        max_batch=8,
        buckets=(4, 8),
        example=example,
        name="chaos_artifacts",
        replicas=2,
        supervise=True,
        supervise_interval_s=0.05,
        artifacts=arts,
    )
    rng = np.random.default_rng(3)
    x = rng.normal(size=(dim,)).astype(np.float32)
    try:
        y0 = np.asarray(svc.submit(x).result(timeout=30.0))
        # hot-swap to v0002, loading its artifacts under the plan
        fitted2, v2 = reg.load("v0002")
        svc.swap(fitted2, version=v2, artifacts=reg.load_artifacts(v2))
        np.asarray(svc.submit(x).result(timeout=30.0))
        # heal: crash one worker, require the supervisor to rejoin it
        from keystone_tpu import faults as _faults

        with _faults.inject("serve.worker:ctx.replica=0:raise:times=1"):
            deadline = time.time() + 30.0
            while time.time() < deadline:
                try:
                    svc.submit(x).result(timeout=10.0)
                except Exception:
                    pass
                if svc.supervisor.restarts_total >= 1:
                    break
                time.sleep(0.01)
        if svc.supervisor.restarts_total < 1:
            raise RuntimeError("supervisor never healed the crashed worker")
        y1 = np.asarray(svc.submit(x).result(timeout=30.0))
        if not np.all(np.isfinite(y1)):
            raise RuntimeError("post-heal prediction is non-finite")
        del y0
    finally:
        svc.close()


def _tenants(tmp, restarts):
    """Multi-tenant blast-radius isolation: two tenants sharing a
    featurization prefix behind one fleet, a wave of traffic per tenant
    under the active plan.  Tenant-targeted plans
    (``serve.enqueue:ctx.tenant=a:raise`` /
    ``serve.batch:ctx.tenant=a:raise``) may fail tenant ``a``'s
    requests — every failure must be TYPED (no hung future), and
    tenant ``b``'s wave must complete 100% clean: one tenant's
    poison/overload can never shed another's traffic.  Raises (chaos
    exit 1) on any cross-tenant failure or unresolved future."""
    import numpy as np

    from keystone_tpu.serve import serve_multi
    from tools.serve_bench import build_tenant_models

    dim = 16
    models = build_tenant_models(tenants=2, dim=dim, branches=3)
    # chaos plans say ctx.tenant=a / ctx.tenant=b
    models = {"a": models.pop("t0"), "b": models.pop("t1")}
    svc = serve_multi(
        models,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=64,
        example=np.zeros((dim,), np.float32),
        name="chaos_tenants",
    )
    rng = np.random.default_rng(5)
    xs = rng.normal(size=(24, dim)).astype(np.float32)
    try:
        futs = {"a": [], "b": []}
        for i in range(xs.shape[0]):
            for t in ("a", "b"):
                try:
                    futs[t].append(svc.submit(xs[i], tenant=t))
                except Exception:
                    # admission refusal IS a typed terminal (the
                    # targeted tenant's faults land here too)
                    futs[t].append(None)
        b_failures = 0
        for t, fs in futs.items():
            for f in fs:
                if f is None:
                    if t == "b":
                        b_failures += 1
                    continue
                try:
                    y = np.asarray(f.result(timeout=30.0))
                    if not np.all(np.isfinite(y)):
                        raise RuntimeError(f"tenant {t} non-finite result")
                except RuntimeError:
                    raise
                except Exception:
                    if t == "b":
                        b_failures += 1
        if b_failures:
            raise RuntimeError(
                f"cross-tenant blast radius: {b_failures} tenant-b "
                "request(s) failed under a tenant-a-targeted plan"
            )
    finally:
        svc.close()


def _kill_live_worker(svc, pick) -> bool:
    """SIGKILL one live worker process of a process-backed service —
    THE seeded kill action, shared by the ``procfleet`` workload and
    the soak loop (two drifting copies would silently test different
    behavior).  ``pick``: seeded index chooser, ``callable(n) -> int``.
    Returns whether a kill landed."""
    import signal as _signal

    pids = [
        r.get("pid")
        for r in svc.replica_statuses()
        if r.get("worker_alive") and r.get("pid")
    ]
    if not pids:
        return False
    try:
        os.kill(pids[int(pick(len(pids)))], _signal.SIGKILL)
        return True
    except OSError:
        return False


class _ChaosCheckFailed(RuntimeError):
    """A workload's OWN acceptance check failed (non-finite result,
    hung future, unhealthy exit wave) — distinct from RuntimeError-
    typed terminal failures the serve layer legitimately answers
    (FleetUnavailable, RemoteApplyError), which are acceptable
    outcomes, not chaos failures."""


def _procfleet(tmp, restarts):
    """The process fleet under seeded kill/hang chaos: a workers=2
    service takes waves of traffic while the workload SIGKILLs live
    worker processes between (and during) waves and the active plan
    batters the parent-side serve sites.  The contract being proven is
    PR-15's promotion invariant: a worker process death loses NOTHING
    — in-flight flushes requeue onto the supervisor's replacement,
    every submitted future resolves (result or typed failure; a hung
    future raises → chaos exit 1), and after the last kill a clean
    wave serves 100% with bit-finite results."""
    from concurrent.futures import TimeoutError as _FTimeout

    import numpy as np

    from tools.serve_bench import build_service

    dim = 8
    svc, item_shape = build_service(
        dim=dim,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=256,
        deadline_ms=None,
        workers=2,
        supervise_interval_s=0.1,
        heartbeat_s=5.0,
        restart_limit=10_000,
    )
    rng = np.random.default_rng(7 + int(restarts))
    xs = rng.normal(size=(32,) + tuple(item_shape)).astype(np.float32)
    hung = 0
    try:
        for wave in range(4):
            futs = []
            for i in range(xs.shape[0]):
                try:
                    futs.append(svc.submit(xs[i]))
                except Exception:
                    futs.append(None)  # typed admission refusal
                if i == 10:
                    # mid-wave: kill a seeded-random live worker
                    _kill_live_worker(svc, lambda n: int(rng.integers(n)))
            for f in futs:
                if f is None:
                    continue
                try:
                    y = np.asarray(f.result(timeout=30.0))
                    if not np.all(np.isfinite(y)):
                        raise _ChaosCheckFailed(
                            "non-finite result after a kill"
                        )
                except _FTimeout:
                    hung += 1
                except _ChaosCheckFailed:
                    raise
                except Exception:
                    pass  # typed failure (FleetUnavailable, remote
                    # errors): an acceptable terminal
        if hung:
            raise _ChaosCheckFailed(
                f"{hung} future(s) hung across worker SIGKILLs — "
                "the process fleet lost admitted work"
            )
        # exit gate: with the kills over, a clean wave must serve 100%
        deadline = time.monotonic() + 30.0
        clean = 0
        while clean < xs.shape[0] and time.monotonic() < deadline:
            clean = 0
            waiters = []
            for i in range(xs.shape[0]):
                try:
                    waiters.append(svc.submit(xs[i]))
                except Exception:
                    pass
            for f in waiters:
                try:
                    f.result(timeout=30.0)
                    clean += 1
                except Exception:
                    pass
            if clean < xs.shape[0]:
                time.sleep(0.2)
        if clean < xs.shape[0]:
            raise _ChaosCheckFailed(
                f"fleet unhealthy after kills: clean wave served "
                f"{clean}/{xs.shape[0]}"
            )
    finally:
        svc.close()


def _nethost(tmp, restarts):
    """The cross-host TCP fleet under a seeded network partition: a
    workers=2 ``hosts=`` service (serve/net.py — every replica is a
    spawned ``keystone worker --connect`` process under a heartbeat
    lease) takes waves of traffic while the workload severs one
    worker's link mid-wave — a ``serve.net.send``/``serve.net.recv``
    ``drop`` plan held for ~3 lease windows, the ``partition`` alias
    of the plan grammar.  The contract being proven is the PR's
    partition invariant: the router declares the silent worker dead at
    lease expiry and re-serves its in-flight flush on the survivor
    (zero lost futures — a hung future raises → chaos exit 1), the
    fenced worker discards its stale result and rejoins with a fresh
    lease once the partition heals, and after the heal a clean wave
    serves 100% from a 2-live fleet."""
    import threading as _threading
    from concurrent.futures import TimeoutError as _FTimeout

    import numpy as np

    from keystone_tpu import faults as _faults
    from tools.serve_bench import build_service

    dim = 8
    svc, item_shape = build_service(
        dim=dim,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=256,
        deadline_ms=None,
        workers=2,
        hosts=["local", "local"],
        supervise_interval_s=0.1,
        heartbeat_s=10.0,
        restart_limit=10_000,
        worker_opts={"lease_s": 1.0, "spawn_grace_s": 3.0},
    )
    rng = np.random.default_rng(11 + int(restarts))
    xs = rng.normal(size=(32,) + tuple(item_shape)).astype(np.float32)
    hung = 0
    severs: list = []
    try:
        links = sorted(
            r.get("link")
            for r in svc.replica_statuses()
            if r.get("link")
        )
        if len(links) < 2:
            raise _ChaosCheckFailed(
                f"net fleet came up with links {links!r}; expected 2"
            )

        def _sever(victim: str) -> None:
            # both directions of the victim's link drop on the router
            # side: its beats stop arriving (lease expiry → declared
            # dead) AND the router's frames stop reaching it (the
            # worker's own lease lapses → self-fence).  ~3 lease
            # windows is long past expiry on both sides.
            plan = (
                f"serve.net.send:ctx.link={victim}:drop;"
                f"serve.net.recv:ctx.link={victim}:drop"
            )
            with _faults.inject(plan):
                time.sleep(3.0)

        for wave in range(4):
            futs = []
            for i in range(xs.shape[0]):
                try:
                    futs.append(svc.submit(xs[i]))
                except Exception:
                    futs.append(None)  # typed admission refusal
                if wave == 1 and i == 10:
                    # mid-wave: sever a seeded-random worker's link
                    victim = links[int(rng.integers(len(links)))]
                    th = _threading.Thread(
                        target=_sever, args=(victim,), daemon=True
                    )
                    th.start()
                    severs.append(th)
            for f in futs:
                if f is None:
                    continue
                try:
                    y = np.asarray(f.result(timeout=60.0))
                    if not np.all(np.isfinite(y)):
                        raise _ChaosCheckFailed(
                            "non-finite result across a partition"
                        )
                except _FTimeout:
                    hung += 1
                except _ChaosCheckFailed:
                    raise
                except Exception:
                    pass  # typed failure: an acceptable terminal
        for th in severs:
            th.join(timeout=30.0)
        if hung:
            raise _ChaosCheckFailed(
                f"{hung} future(s) hung across the partition — "
                "the cross-host fleet lost admitted work"
            )
        # heal gate: the fenced worker must rejoin (fresh lease) —
        # 2 live workers before the clean wave is demanded
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            live = [
                r
                for r in svc.replica_statuses()
                if r.get("worker_alive")
            ]
            if len(live) >= 2:
                break
            time.sleep(0.2)
        else:
            raise _ChaosCheckFailed(
                "fleet never healed to 2 live workers after the "
                "partition lifted"
            )
        # exit gate: with the partition healed, a clean wave serves 100%
        deadline = time.monotonic() + 30.0
        clean = 0
        while clean < xs.shape[0] and time.monotonic() < deadline:
            clean = 0
            waiters = []
            for i in range(xs.shape[0]):
                try:
                    waiters.append(svc.submit(xs[i]))
                except Exception:
                    pass
            for f in waiters:
                try:
                    f.result(timeout=30.0)
                    clean += 1
                except Exception:
                    pass
            if clean < xs.shape[0]:
                time.sleep(0.2)
        if clean < xs.shape[0]:
            raise _ChaosCheckFailed(
                f"fleet unhealthy after the partition: clean wave "
                f"served {clean}/{xs.shape[0]}"
            )
    finally:
        svc.close()


def _rollout(tmp, restarts):
    """The guarded-rollout drill: a good version serves live while a
    BAD version (``tools/workloads.py`` MarkerGate — fails exactly the
    rows the seeded ``poison_flood`` scenario floods) is canaried at
    50% of traffic.  The contract being proven is PR-19's guard
    invariant: canary-hashed requests concentrate the failures on the
    staged generation while live traffic stays clean, the judge rolls
    back on the error-rate guardrail and QUARANTINES the version in
    the registry (checksummed ``BAD`` sidecar), the watcher refuses to
    redeploy the quarantined version even with ``CURRENT`` pointing at
    it, every future across the abandoned staged generation resolves
    (a hung future raises → chaos exit 1), and a clean final wave
    serves 100% from the untouched live generation."""
    import threading as _threading
    from concurrent.futures import TimeoutError as _FTimeout

    import numpy as np

    from keystone_tpu.obs import metrics as _metrics
    from keystone_tpu.serve import (
        ModelRegistry,
        RegistryWatcher,
        RolloutConfig,
        serve,
    )
    from keystone_tpu.serve.rollout import CanaryController
    from tools import workloads as zoo

    dim = 8
    reg = ModelRegistry(os.path.join(tmp, "registry"))
    good = zoo.build_zoo_pipeline(dim=dim, scale=2.0, gate=False)
    bad = zoo.build_zoo_pipeline(dim=dim, scale=3.0, gate=True)
    v1 = reg.publish(good)
    v2 = reg.publish(bad, set_current=False)
    fitted, ver = reg.load(v1)
    svc = serve(
        fitted,
        version=ver,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=512,
        example=np.zeros((dim,), np.float32),
        name="chaos_rollout",
        replicas=2,
        slo_ms=250.0,
    )
    scenario = zoo.make_scenario(
        "poison_flood", seed=int(restarts), duration_s=2.0, qps=300.0, dim=dim
    )
    flood_at = scenario.duration_s / 3.0
    futs: list = []
    futs_lock = _threading.Lock()

    def _submit(event, rows):
        try:
            fs = svc.submit_many(rows)
        except Exception:
            return None  # typed admission refusal: a scheduled outcome
        with futs_lock:
            futs.extend(fs)
        return len(fs)

    pump = _threading.Thread(
        target=lambda: zoo.play(scenario, _submit, time_scale=1.0),
        daemon=True,
    )
    try:
        pump.start()
        # judge inside the flood window: the scenario's clean warmup
        # third would otherwise commit the bad version before the first
        # marker row arrives
        time.sleep(flood_at)
        cfg = RolloutConfig(
            canary=0.5,
            seed=int(restarts),
            min_samples=16,
            decide_s=20.0,
            max_error_rate=0.1,
            insufficient="rollback",
        )
        info = CanaryController(svc, cfg, registry=reg).run(
            reg.load(v2)[0], version=v2
        )
        if info["verdict"] != "rolled_back":
            raise _ChaosCheckFailed(
                f"canary let the bad version through: {info!r}"
            )
        if svc.version != v1:
            raise _ChaosCheckFailed(
                f"service serves {svc.version!r} after rollback, not {v1!r}"
            )
        if reg.quarantined(v2) is None:
            raise _ChaosCheckFailed(
                f"rollback did not quarantine {v2} in the registry"
            )
        # the watcher must refuse the quarantined version even when an
        # operator (or a crashed deploy) points CURRENT straight at it
        reg.set_current(v2)
        RegistryWatcher(svc, reg, poll_seconds=3600.0)._poll_once()
        if svc.version != v1:
            raise _ChaosCheckFailed(
                "watcher redeployed a quarantined version"
            )
        reg.set_current(v1)
        pump.join(timeout=30.0)
        if pump.is_alive():
            raise _ChaosCheckFailed("workload pump never finished")
        hung = 0
        with futs_lock:
            pending = list(futs)
        for f in pending:
            try:
                f.result(timeout=30.0)
            except _FTimeout:
                hung += 1
            except Exception:
                pass  # typed failure (poison, shed): acceptable
        if hung:
            raise _ChaosCheckFailed(
                f"{hung} future(s) hung across the abandoned canary "
                "generation — the rollout lost admitted work"
            )
        if _metrics.REGISTRY.counter_total("serve.rollout.rollbacks") < 1:
            raise _ChaosCheckFailed("serve.rollout.rollbacks never counted")
        hist = svc.rollout_status()["history"]
        if not hist or hist[-1]["verdict"] != "rolled_back":
            raise _ChaosCheckFailed(
                f"rollout history missing the rollback: {hist!r}"
            )
        # exit gate: a clean marker-free wave serves 100% from the
        # live generation (norm fingerprints the GOOD version's scale)
        xs = np.random.default_rng(13).normal(size=(16, dim)).astype(
            np.float32
        )
        for i in range(xs.shape[0]):
            y = np.asarray(svc.submit(xs[i]).result(timeout=30.0))
            norm = float(np.linalg.norm(y))
            if abs(norm - 2.0) > 1e-3:
                raise _ChaosCheckFailed(
                    f"post-rollback result norm {norm:.4f} fingerprints "
                    "the wrong version (want 2.0, the good scale)"
                )
    finally:
        svc.close()


WORKLOADS = {
    "bcd": _bcd,
    "ooc": _ooc,
    "kernel": _kernel,
    "lbfgs": _lbfgs,
    "stream": _stream,
    "serve_artifacts": _serve_artifacts,
    "tenants": _tenants,
    "procfleet": _procfleet,
    "nethost": _nethost,
    "rollout": _rollout,
}

#: workloads that activate their own fault plan mid-run (a seeded
#: partition, a timed sever, a canaried bad version under a poison
#: flood) — runnable with no --plan at all
SELF_INJECTING = frozenset({"nethost", "rollout"})


# --------------------------------------------------------------- soak
#: the serve-path sites a soak plan draws from, with the actions each
#: may carry (worker crashes exercise the supervisor; delays exercise
#: hedging/shedding; raises exercise failure containment + bisection
#: charging).  `hang` is deliberately absent: an un-deadlined hang is
#: an hour-long stall, which is a test of the clock, not the fleet.
_SOAK_MENU = (
    ("serve.enqueue", ("raise",)),
    ("serve.batch", ("raise", "delay")),
    ("serve.replica", ("raise", "delay")),
    ("serve.worker", ("raise", "delay")),
)


def _soak_plan(rng) -> str:
    """One randomized (but seed-deterministic) multi-site plan clause
    set in the KEYSTONE_FAULTS grammar."""
    n_sites = rng.randint(1, 3)
    picks = rng.sample(range(len(_SOAK_MENU)), n_sites)
    clauses = []
    for i in picks:
        site, actions = _SOAK_MENU[i]
        action = actions[rng.randrange(len(actions))]
        times = rng.randint(1, 3)
        after = rng.randint(0, 4)
        if action == "delay":
            delay = round(rng.uniform(0.005, 0.05), 4)
            clauses.append(f"{site}:delay={delay}:after={after}:times={times}")
        else:
            clauses.append(f"{site}:raise:after={after}:times={times}")
    return ";".join(clauses)


def run_soak(
    seconds: float,
    seed: int = 0,
    replicas: int = 2,
    wave: int = 48,
    result_timeout: float = 30.0,
    workers: int = 0,
) -> dict:
    """Loop seeded randomized multi-site fault plans against a LIVE
    serving fleet; every submitted future must resolve (a completed
    result or a typed failure) — a future that never resolves is a
    LOST/HUNG request, the one outcome the self-healing layer must
    never produce.  Returns the report dict; the CLI exits non-zero on
    any hung request (or a fleet that cannot serve a clean wave at the
    end)."""
    import random as _random

    import numpy as np

    from keystone_tpu import faults
    from keystone_tpu.utils import guard as _guard

    from tools import serve_bench

    rng = _random.Random(seed)
    fleet_kw = (
        # process fleet soak (PR 15): worker PROCESSES behind the same
        # router — the plan menu still fires at the parent-side sites,
        # and the soak loop additionally SIGKILLs live workers
        dict(workers=workers)
        if workers
        else dict(replicas=replicas)
    )
    svc, item_shape = serve_bench.build_service(
        dim=8,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=256,
        deadline_ms=None,
        # soak services heal aggressively: short heartbeat, fast sweep,
        # a restart budget the whole soak cannot exhaust
        supervise_interval_s=0.1,
        heartbeat_s=5.0,
        restart_limit=10_000,
        hedge_ms=25.0,
        **fleet_kw,
    )
    payload = np.random.default_rng(seed).normal(
        size=(wave,) + tuple(item_shape)
    ).astype(np.float32)
    report = {
        "seconds": seconds,
        "seed": seed,
        "replicas": replicas,
        "workers": workers,
        "iterations": 0,
        "submitted": 0,
        "completed": 0,
        "failed_typed": 0,
        "rejected": 0,
        "hung": 0,
        "process_kills": 0,
        "plans": [],
    }

    def _maybe_kill_worker() -> None:
        """Process-fleet soak action: SIGKILL a seeded-random live
        worker child mid-wave (the failure mode threads can't even
        have) — the supervisor must heal and no future may hang."""
        if _kill_live_worker(svc, rng.randrange):
            report["process_kills"] += 1

    try:
        end = time.monotonic() + float(seconds)
        while time.monotonic() < end:
            plan = _soak_plan(rng)
            report["iterations"] += 1
            report["plans"].append(plan)
            # process fleets get killed roughly every other iteration
            kill_at = (
                rng.randrange(wave) if workers and rng.random() < 0.5 else None
            )
            futs = []
            with faults.inject(plan):
                for i in range(wave):
                    if i == kill_at:
                        _maybe_kill_worker()
                    try:
                        futs.append(svc.submit(payload[i]))
                    except Exception:
                        report["rejected"] += 1
                    report["submitted"] += 1
                # resolve INSIDE the plan window: mid-flight faults on
                # in-flight futures are the point of the soak
                for f in futs:
                    try:
                        f.result(timeout=result_timeout)
                        report["completed"] += 1
                    except _FutTimeout:
                        report["hung"] += 1
                    except Exception:
                        report["failed_typed"] += 1
        # the exit gate: after the last plan, a clean wave must serve —
        # a fleet that "survived" the soak but can no longer serve is a
        # failure (give healing a moment to finish)
        clean_ok = 0
        deadline = time.monotonic() + result_timeout
        while clean_ok < wave and time.monotonic() < deadline:
            clean_ok = 0
            futs = []
            for i in range(wave):
                try:
                    futs.append(svc.submit(payload[i]))
                except Exception:
                    pass  # still healing: retry the wave below
            for f in futs:
                try:
                    f.result(timeout=result_timeout)
                    clean_ok += 1
                except Exception:
                    pass
            if clean_ok < wave:
                _guard.interruptible_sleep(0.2)
        report["clean_wave_completed"] = clean_ok
        report["clean_wave_size"] = wave
        report["healthy_after_soak"] = clean_ok == wave
    finally:
        svc.close()
    report["ok"] = report["hung"] == 0 and report["healthy_after_soak"]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run a workload under a KEYSTONE_FAULTS plan and "
        "report per-site injected/survived counts"
    )
    ap.add_argument(
        "--plan",
        default=None,
        help="fault plan, KEYSTONE_FAULTS grammar "
        "(e.g. 'ckpt.save:after=1:corrupt;blockstore.read:p=0.1:seed=7'). "
        "Required unless --soak is given.",
    )
    ap.add_argument(
        "--soak",
        type=float,
        default=None,
        metavar="SECONDS",
        help="soak mode: loop seeded randomized multi-site plans "
        "(serve.* sites) against a live replica fleet for SECONDS; "
        "exits non-zero on any lost/hung future or a fleet that cannot "
        "serve a clean wave afterwards.  Ignores --plan/--workload.",
    )
    ap.add_argument(
        "--seed",
        type=int,
        default=0,
        help="soak plan-generator seed (deterministic plan sequence)",
    )
    ap.add_argument(
        "--soak-replicas",
        type=int,
        default=2,
        help="fleet size for the soak service (pair with "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N on CPU)",
    )
    ap.add_argument(
        "--soak-workers",
        type=int,
        default=0,
        help="run the soak over a PROCESS fleet of this many worker "
        "processes (0 = the threaded fleet): the soak loop then also "
        "SIGKILLs live workers mid-wave — every future must still "
        "resolve",
    )
    ap.add_argument(
        "--workload",
        default="bcd",
        help=f"one of {sorted(WORKLOADS)} or module.path:function",
    )
    ap.add_argument(
        "--restarts",
        type=int,
        default=1,
        help="fit_with_recovery restart budget for the built-in workloads",
    )
    ap.add_argument(
        "--tmp", default=None, help="scratch dir (default: a fresh tempdir)"
    )
    ap.add_argument(
        "--ledger",
        default=None,
        metavar="DIR",
        help="write a run ledger (JSONL spans/events) under DIR: per-"
        "restart fault stats() land there instead of being lost to "
        "reset_stats() between restart attempts, and the report reads "
        "per-site counts from the unified metrics registry "
        "(render with tools/obs_report.py)",
    )
    ap.add_argument(
        "--stage-deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-stage watchdog budget for the workload "
        "(KEYSTONE_STAGE_DEADLINE): a hang injected at executor.stage "
        "becomes a retried/degraded stage instead of a stalled run",
    )
    ap.add_argument(
        "--stage-retries",
        type=int,
        default=None,
        metavar="N",
        help="stage retry budget (KEYSTONE_STAGE_RETRIES) — the budget "
        "deadline overruns are retried from",
    )
    ap.add_argument(
        "--stream-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-batch fetch watchdog for the 'stream' workload "
        "(KEYSTONE_STREAM_TIMEOUT): a hung source counts against the "
        "retry/bad-batch quota instead of blocking the iterator",
    )
    args = ap.parse_args(argv)

    if args.soak is not None:
        report = run_soak(
            args.soak,
            seed=args.seed,
            replicas=args.soak_replicas,
            workers=args.soak_workers,
        )
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    if args.plan is None and args.workload not in SELF_INJECTING:
        ap.error(
            "--plan is required (unless --soak, or a self-injecting "
            f"workload: {sorted(SELF_INJECTING)})"
        )

    if args.stage_deadline is not None:
        os.environ["KEYSTONE_STAGE_DEADLINE"] = str(args.stage_deadline)
    if args.stage_retries is not None:
        os.environ["KEYSTONE_STAGE_RETRIES"] = str(args.stage_retries)
    if args.stream_timeout is not None:
        os.environ["KEYSTONE_STREAM_TIMEOUT"] = str(args.stream_timeout)

    import tempfile

    from keystone_tpu import faults
    from keystone_tpu.obs import ledger as obs_ledger
    from keystone_tpu.obs import metrics

    # fail fast on grammar errors; a self-injecting workload (nethost
    # activates its own seeded partition plan mid-wave) may run with
    # no outer plan at all
    plan = (
        faults.parse_plan(args.plan)
        if args.plan is not None
        else faults.FaultPlan([], source="(workload self-injected)")
    )
    tmp = args.tmp or tempfile.mkdtemp(prefix="kst_chaos_")

    if args.workload in WORKLOADS:
        run = lambda: WORKLOADS[args.workload](tmp, args.restarts)  # noqa: E731
    else:
        modname, _, fnname = args.workload.partition(":")
        fn = getattr(importlib.import_module(modname), fnname or "main")
        run = fn

    led = None
    if args.ledger:
        led = obs_ledger.start_run(args.ledger)
        led.event("chaos.start", plan=args.plan, workload=args.workload)

    faults.reset_stats()
    metrics.reset()  # the report window starts here, registry included
    error = None
    with faults.inject(plan):
        try:
            run()
        except BaseException as e:  # report, don't crash the reporter
            error = f"{type(e).__name__}: {e}"

    stats = faults.stats()
    # the unified registry accumulates across restarts (reset_stats only
    # clears the faults-module window): prefer it for per-site counts so
    # the report and the ledger agree
    snap = metrics.snapshot()
    reg_sites = {}
    for key, v in (snap.get("counters") or {}).items():
        for name in ("faults.calls", "faults.injected"):
            if key.startswith(name + "{site="):
                site = key[len(name) + 6 : -1]
                reg_sites.setdefault(site, {"calls": 0, "injected": 0})
                reg_sites[site][
                    "calls" if name.endswith("calls") else "injected"
                ] += int(v)
    if reg_sites:
        stats = {
            site: {
                "calls": c["calls"],
                "injected": c["injected"],
            }
            for site, c in reg_sites.items()
        }
    escaped_site = None
    if error is not None and "injected fault at" in error:
        for site in faults.SITES:
            if repr(site) in error:
                escaped_site = site

    # every site the plan NAMES must appear in the report, even with
    # zero calls — a typo'd trigger (after=100 on a 5-call site) or a
    # workload that never reaches the site otherwise vanishes entirely
    # and the run reads green
    planned = {s.site for s in plan.specs}
    for site in planned:
        stats.setdefault(site, {"calls": 0, "injected": 0})

    def survived(site, counts):
        # only claim survival when it is attributable: a clean run
        # survived everything it was actually GIVEN; a planned site
        # that never injected is "not-exercised", not survived; an
        # escaped FaultInjected pins one site; any other failure (e.g.
        # a downstream CorruptStateError from a corrupt action) leaves
        # per-site survival unknown -> null
        if counts["injected"] == 0:
            return None
        if error is None:
            return counts["injected"]
        if site == escaped_site:
            return counts["injected"] - 1
        return None

    def verdict(site, counts):
        if counts["injected"] == 0:
            return "not-exercised" if site in planned else "no-injections"
        if error is None:
            return "survived"
        if site == escaped_site:
            return "escaped"
        return "unknown"

    not_exercised = sorted(
        site
        for site in planned
        if stats.get(site, {}).get("injected", 0) == 0
    )

    def _labeled(name, label):
        """{label_value: total} for one counter family in the snapshot."""
        out = {}
        prefix = name + "{" + label + "="
        for key, v in (snap.get("counters") or {}).items():
            if key == name:
                out[""] = out.get("", 0) + int(v)
            elif key.startswith(prefix) and key.endswith("}"):
                out[key[len(prefix) : -1]] = int(v)
        return out

    def _gauges_labeled(snapshot, name, label):
        """{label_value: gauge} for one gauge family in a snapshot."""
        out = {}
        prefix = name + "{" + label + "="
        for key, v in (snapshot.get("gauges") or {}).items():
            if key.startswith(prefix) and key.endswith("}"):
                out[key[len(prefix) : -1]] = v
        return out

    report = {
        "plan": args.plan,
        "workload": args.workload,
        "completed": error is None,
        "error": error,
        "not_exercised": not_exercised,
        "sites": {
            site: {
                "calls": counts["calls"],
                "injected": counts["injected"],
                "survived": survived(site, counts),
                "verdict": verdict(site, counts),
            }
            for site, counts in sorted(stats.items())
        },
        # the deadline/watchdog/breaker layer's outcomes (utils/guard.py)
        # — how injected latency was absorbed, from the same registry
        # the per-site counts come from — plus the serve fleet's
        # self-healing outcomes (supervisor restarts, quarantines,
        # batch bisections) when the workload ran a service
        "guard": {
            "deadline_exceeded": _labeled("guard.deadline_exceeded", "site"),
            "breaker_opens": _labeled("breaker.opens", "key"),
            "degraded": _labeled("executor.degraded", "node"),
            "replica_restarts": _labeled("serve.replica_restarts", "replica"),
            "quarantined": _gauges_labeled(snap, "serve.quarantined", "replica"),
            "bisections": int(
                (snap.get("counters") or {}).get("serve.bisections", 0)
            ),
            "poison": int((snap.get("counters") or {}).get("serve.poison", 0)),
            "hedges": int((snap.get("counters") or {}).get("serve.hedges", 0)),
        },
    }
    if led is not None:
        led.event(
            "faults.stats",
            final=True,
            completed=error is None,
            error=error,
            stats=report["sites"],
        )
        report["ledger"] = led.path
        obs_ledger.stop_run()
    print(json.dumps(report, indent=2))
    if error is not None:
        return 1
    # completed, but a named site never fired: the plan did not test
    # what it claims to test — fail the run so CI catches the typo
    return 2 if not_exercised else 0


if __name__ == "__main__":
    sys.exit(main())
