"""Open-loop load generator for the online serving subsystem.

Drives a :class:`keystone_tpu.serve.PipelineService` with a fixed
arrival schedule — requests are submitted at the target QPS whether or
not earlier ones completed (open loop: the honest way to measure a
service, since closed-loop generators self-throttle and hide queueing
collapse) — and reports latency percentiles, achieved throughput, mean
batch occupancy, and the shed/rejected breakdown.

Usage (CPU-safe; any laptop)::

    JAX_PLATFORMS=cpu python tools/serve_bench.py \
        --qps 2000 --duration 3 --max-batch 32 --max-wait-ms 2 \
        --deadline-ms 250 --queue-bound 128

    # burst mode: arrivals in groups of N at the same mean rate
    ... --burst 16

    # emulate a heavier model: stall every flush via the serve.batch
    # fault site (the chaos machinery doubles as a load shaper)
    ... --batch-delay-ms 10

    # serve a saved model instead of the synthetic default
    ... --model fitted.pkl --dim 512

    # replica fleet: one FrozenApplier clone per local device
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python tools/serve_bench.py --replicas 4 ...

    # blue/green hot-swap halfway through the offer window; the report
    # gains the swap pause + prime time and per-replica occupancy
    ... --replicas 4 --swap-mid-run

    # inject a straggler (replica 0 stalls every flush 40 ms) and hedge
    # around it: queued flushes escape onto a healthy replica
    ... --replicas 2 --straggler-ms 40 --hedge-ms 10

The default workload is a small synthetic two-stage pipeline
(NormalizeRows → LinearMapper) so the tool measures the serving layer
itself; ``--model`` swaps in a real fitted pipeline whose input is a
``--dim``-vector.  Exit code 0; the report is one JSON object on
stdout.  ``bench.py --leg-serve`` embeds this report (overload config)
in the round artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import wait as futures_wait

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_pipeline(dim: int = 64, classes: int = 16, seed: int = 0):
    """The synthetic two-stage workload (NormalizeRows → LinearMapper)."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(dim, classes)).astype(np.float32))
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def build_service(
    dim: int = 64,
    classes: int = 16,
    max_batch: int = 32,
    max_wait_ms: float = 2.0,
    queue_bound: int = 128,
    deadline_ms: float | None = 250.0,
    model: str | None = None,
    seed: int = 0,
    replicas: int = 1,
    recorder: bool = True,
    **serve_kw,
):
    """A primed service over the synthetic two-stage pipeline (or a
    saved fitted model); returns ``(service, item_shape)``.
    ``recorder=False`` runs the PR-5 untraced path — the on/off pair is
    how the bench pins the flight recorder's overhead budget.  Extra
    keywords (``hedge_ms``, ``supervise``, ``heartbeat_s``, ...) pass
    through to :func:`keystone_tpu.serve.serve` — the hedging A/B and
    the chaos soak ride this."""
    import numpy as np

    from keystone_tpu.serve import serve

    if model:
        from keystone_tpu.workflow import FittedPipeline

        pipe = FittedPipeline.load(model)
    else:
        pipe = build_pipeline(dim=dim, classes=classes, seed=seed)
    item_shape = (int(dim),)
    svc = serve(
        pipe,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        deadline_ms=deadline_ms,
        example=np.zeros(item_shape, np.float32),
        name="serve_bench",
        replicas=replicas,
        recorder=recorder,
        **serve_kw,
    )
    return svc, item_shape


def _hist_delta(before: dict, after: dict, name: str) -> tuple:
    b = before.get(name) or {"count": 0, "sum": 0.0}
    a = after.get(name) or {"count": 0, "sum": 0.0}
    return a["count"] - b["count"], a["sum"] - b["sum"]


def run_bench(
    svc,
    item_shape,
    qps: float,
    duration: float,
    burst: int = 1,
    deadline_ms: float | None = None,
    batch_delay_ms: float = 0.0,
    swap_pipeline=None,
    straggler_ms: float = 0.0,
    straggler_replica: int = 0,
) -> dict:
    """Offer ``qps`` requests/sec for ``duration`` seconds (groups of
    ``burst`` arrivals at the same mean rate), wait for the tail to
    drain, and report.  ``batch_delay_ms`` > 0 stalls every flush via a
    ``serve.batch:delay=…`` fault plan (emulating a heavier model, so a
    laptop can exercise overload deterministically).  ``swap_pipeline``:
    blue/green hot-swap this fitted pipeline in at the midpoint of the
    offer window; the report gains the swap info (pause, prime time) so
    the round artifact records what a live rollout costs under load.
    ``straggler_ms`` > 0 makes ONE replica (``straggler_replica``) stall
    every flush apply via a context-matched ``serve.replica`` plan —
    the deterministic straggler the hedging A/B measures against."""
    import contextlib

    import numpy as np

    from keystone_tpu import faults
    from keystone_tpu.obs import metrics
    from keystone_tpu.serve import Overloaded
    from keystone_tpu.utils import guard

    burst = max(1, int(burst))
    deadline_s = None if not deadline_ms else float(deadline_ms) / 1000.0
    snap0 = metrics.snapshot()
    c0 = dict(snap0.get("counters") or {})

    lock = threading.Lock()
    latencies: list = []
    outcomes = {"completed": 0, "shed": 0, "rejected": 0, "errors": 0}

    def record(fut, t_submit):
        t_done = time.monotonic()
        exc = fut.exception()
        with lock:
            if exc is None:
                outcomes["completed"] += 1
                latencies.append(t_done - t_submit)
            elif isinstance(exc, guard.DeadlineExceeded):
                outcomes["shed"] += 1
            else:
                outcomes["errors"] += 1

    rng = np.random.default_rng(1)
    payload = rng.normal(size=(burst,) + tuple(item_shape)).astype(np.float32)
    n_arrivals = max(1, int(round(qps * duration)))
    interval = burst / qps
    futs = []

    clauses = []
    if batch_delay_ms > 0:
        clauses.append(f"serve.batch:delay={batch_delay_ms / 1000.0}")
    if straggler_ms > 0:
        # serve.worker, not serve.replica: the stall lands in the worker
        # loop BEFORE the flush is claimed, so the batch stays
        # "still-unflushed" for the whole stall — the exact failure mode
        # hedged dispatch exists to rescue (a claimed flush mid-apply is
        # beyond any hedge that avoids duplicate device work)
        clauses.append(
            f"serve.worker:ctx.replica={int(straggler_replica)}"
            f":delay={straggler_ms / 1000.0}"
        )
    plan = (
        faults.inject(";".join(clauses)) if clauses else contextlib.nullcontext()
    )
    swap_info: dict = {}
    swap_thread = None
    if swap_pipeline is not None:

        def _swap_midway():
            time.sleep(duration / 2.0)
            try:
                swap_info.update(svc.swap(swap_pipeline, version="bench-swap"))
            except Exception as e:  # report it; don't kill the offer loop
                swap_info["error"] = f"{type(e).__name__}: {e}"

        swap_thread = threading.Thread(target=_swap_midway, daemon=True)
    t_start = time.monotonic()
    if swap_thread is not None:
        swap_thread.start()
    with plan:
        next_t = t_start
        sent = 0
        while sent < n_arrivals:
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(next_t - now, 0.002))
                continue
            for b in range(burst):
                if sent >= n_arrivals:
                    break
                t_submit = time.monotonic()
                try:
                    fut = svc.submit(payload[b], deadline=deadline_s)
                except Overloaded:
                    with lock:
                        outcomes["rejected"] += 1
                else:
                    fut.add_done_callback(
                        lambda f, t0=t_submit: record(f, t0)
                    )
                    futs.append(fut)
                sent += 1
            next_t += interval
        # throughput denominator = the OFFER window: including the
        # post-offer tail-drain below would bias achieved_qps low by
        # queue_bound × batch-time per run, making round-over-round
        # movement track drain length instead of serving capacity
        offer_elapsed = time.monotonic() - t_start
        # drain the tail: everything admitted resolves (completed or
        # shed) — the report must account for every offered request
        futures_wait(futs, timeout=duration + 30.0)
    wall_elapsed = time.monotonic() - t_start
    if swap_thread is not None:
        swap_thread.join(timeout=duration + 60.0)
    replica_stats = svc.replica_statuses()

    snap1 = metrics.snapshot()
    c1 = dict(snap1.get("counters") or {})
    rows_n, rows_sum = _hist_delta(
        snap0.get("histograms") or {}, snap1.get("histograms") or {}, "serve.batch_rows"
    )
    lat_ms = sorted(x * 1000.0 for x in latencies)

    def pct(p):
        if not lat_ms:
            return None
        return round(float(np.percentile(lat_ms, p)), 2)

    completed = outcomes["completed"]
    report = {
        "offered_qps": qps,
        "duration_s": duration,
        "burst": burst,
        "deadline_ms": deadline_ms,
        "batch_delay_ms": batch_delay_ms,
        "straggler_ms": straggler_ms,
        "hedges": int(
            c1.get("serve.hedges", 0.0) - c0.get("serve.hedges", 0.0)
        ),
        "hedge_wins": int(
            c1.get("serve.hedge_wins", 0.0) - c0.get("serve.hedge_wins", 0.0)
        ),
        "n_requests": n_arrivals,
        "completed": completed,
        "shed": outcomes["shed"],
        "rejected": outcomes["rejected"],
        "errors": outcomes["errors"],
        "achieved_qps": (
            round(completed / offer_elapsed, 1) if offer_elapsed > 0 else None
        ),
        "drain_s": round(wall_elapsed - offer_elapsed, 3),
        "p50_ms": pct(50),
        "p95_ms": pct(95),
        "p99_ms": pct(99),
        "max_ms": round(lat_ms[-1], 2) if lat_ms else None,
        "batches": rows_n,
        "mean_batch_occupancy": round(rows_sum / rows_n, 2) if rows_n else None,
        "shed_rate": round(
            (outcomes["shed"] + outcomes["rejected"]) / n_arrivals, 4
        ),
        "deadline_miss": int(
            c1.get("serve.deadline_miss", 0.0) - c0.get("serve.deadline_miss", 0.0)
        ),
        "replicas": len(replica_stats),
        "recorder": svc.recorder is not None,
        # flush share per replica: a healthy least-outstanding router
        # keeps these near-uniform; a skew marks a slow/broken replica.
        # Counter deltas, not replica statuses — statuses reset at a
        # swap (a fresh generation), counters span the whole run
        "replica_occupancy": _occupancy(replica_stats, c0, c1),
    }
    if swap_pipeline is not None:
        report["swap"] = {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in swap_info.items()
        }
    return report


def _occupancy(replica_stats: list, c0: dict, c1: dict) -> list:
    def flushes(i: int) -> int:
        key = f"serve.replica_flushes{{replica={i}}}"
        return int(c1.get(key, 0.0) - c0.get(key, 0.0))

    counts = {r["replica"]: flushes(r["replica"]) for r in replica_stats}
    total = sum(counts.values()) or 1
    return [
        {
            "replica": r["replica"],
            "version": r["version"],
            "flushes": counts[r["replica"]],
            "share": round(counts[r["replica"]] / total, 4),
            "errors": r["errors"],
            "breaker": r["breaker"],
        }
        for r in replica_stats
    ]


def run_overhead_pair(
    qps: float = 300.0,
    duration: float = 2.0,
    rounds: int = 4,
    max_batch: int = 16,
    deadline_ms: float = 500.0,
    batch_delay_ms: float = 2.0,
    dim: int = 64,
) -> dict:
    """The flight-recorder overhead pin: the SAME workload against two
    services in ONE process — recorder on vs off — interleaved with
    alternating order across ``rounds`` and a discarded warmup round, so
    process cold-start, CPU-frequency, and scheduler noise cancel
    instead of masquerading as tracing overhead.  Runs at a steady
    operating point BELOW the collapse knee (offered < capacity):
    in overload, achieved QPS sits on the collapse cliff where tiny
    capacity shifts swing it wildly and no 5%-budget claim is
    measurable.  Reports per-mode medians and on/off ratios — the
    acceptance budget is ratios within 5% of 1.0."""
    import statistics

    services = {}
    for mode, rec in (("on", True), ("off", False)):
        svc, item_shape = build_service(
            dim=dim,
            max_batch=max_batch,
            queue_bound=128,
            deadline_ms=deadline_ms,
            recorder=rec,
        )
        services[mode] = (svc, item_shape)
    samples = {"on": [], "off": []}
    try:
        for rnd in range(max(2, int(rounds)) + 1):
            order = ("on", "off") if rnd % 2 == 0 else ("off", "on")
            for mode in order:
                svc, item_shape = services[mode]
                rep = run_bench(
                    svc,
                    item_shape,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=deadline_ms,
                    batch_delay_ms=batch_delay_ms,
                )
                if rnd > 0:  # round 0 is the discarded warmup
                    samples[mode].append(rep)
    finally:
        for svc, _ in services.values():
            svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    out = {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["on"]),
        "batch_delay_ms": batch_delay_ms,
    }
    for mode in ("on", "off"):
        out[f"recorder_{mode}"] = {
            k: med(mode, k)
            for k in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms")
        }
    ratios = {}
    for key, name in (
        ("achieved_qps", "achieved_qps_ratio"),
        ("p99_ms", "p99_ratio"),
    ):
        on, off = out["recorder_on"].get(key), out["recorder_off"].get(key)
        if on and off:
            ratios[name] = round(on / off, 3)
    out["overhead"] = ratios
    return out


def run_straggler_ab(
    qps: float = 300.0,
    duration: float = 2.0,
    rounds: int = 4,
    replicas: int = 2,
    max_batch: int = 16,
    deadline_ms: float = 2000.0,
    straggler_ms: float = 40.0,
    hedge_ms: float = 10.0,
    dim: int = 64,
) -> dict:
    """The hedging acceptance pin: the SAME workload with ONE injected
    straggler replica (every flush on replica 0 stalls ``straggler_ms``)
    against two fleets in one process — hedging ON vs OFF — order-
    alternated across ``rounds`` with a discarded warmup, exactly the
    ``run_overhead_pair`` discipline.  Hedging must cut p99 (queued
    flushes escape the straggler's queue onto a healthy replica) at
    ≤ 5% achieved-QPS cost — hedge losers are claim-skips, not
    duplicated device work.  Reports per-mode medians plus
    ``p99_ratio`` (hedged/unhedged, want < 1) and ``qps_cost``
    (1 − hedged/unhedged QPS, want ≤ 0.05)."""
    import statistics

    services = {}
    for mode, hedge in (("hedged", hedge_ms), ("unhedged", None)):
        svc, item_shape = build_service(
            dim=dim,
            max_batch=max_batch,
            queue_bound=256,
            deadline_ms=deadline_ms,
            replicas=replicas,
            hedge_ms=hedge,
            # the straggler is an INJECTED stall, not a wedge: keep the
            # supervisor from "healing" the leg out from under the A/B
            supervise=False,
        )
        services[mode] = (svc, item_shape)
    samples = {"hedged": [], "unhedged": []}
    try:
        for rnd in range(max(2, int(rounds)) + 1):
            order = (
                ("hedged", "unhedged") if rnd % 2 == 0 else ("unhedged", "hedged")
            )
            for mode in order:
                svc, item_shape = services[mode]
                rep = run_bench(
                    svc,
                    item_shape,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=deadline_ms,
                    straggler_ms=straggler_ms,
                )
                if rnd > 0:  # round 0 is the discarded warmup
                    samples[mode].append(rep)
    finally:
        for svc, _ in services.values():
            svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    out = {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["hedged"]),
        "replicas": replicas,
        "straggler_ms": straggler_ms,
        "hedge_ms": hedge_ms,
    }
    for mode in ("hedged", "unhedged"):
        out[mode] = {
            k: med(mode, k)
            for k in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms", "max_ms")
        }
    out["hedged"]["hedges"] = sum(r["hedges"] for r in samples["hedged"])
    out["hedged"]["hedge_wins"] = sum(
        r["hedge_wins"] for r in samples["hedged"]
    )
    hedging = {}
    on_p99, off_p99 = out["hedged"].get("p99_ms"), out["unhedged"].get("p99_ms")
    if on_p99 and off_p99:
        hedging["p99_ratio"] = round(on_p99 / off_p99, 3)
    on_q, off_q = (
        out["hedged"].get("achieved_qps"),
        out["unhedged"].get("achieved_qps"),
    )
    if on_q and off_q:
        hedging["qps_cost"] = round(1.0 - on_q / off_q, 4)
    out["hedging"] = hedging
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for keystone_tpu.serve"
    )
    ap.add_argument("--qps", type=float, default=500.0, help="offered load")
    ap.add_argument("--duration", type=float, default=3.0, help="seconds")
    ap.add_argument(
        "--burst", type=int, default=1, help="arrivals per group (same mean rate)"
    )
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-bound", type=int, default=128)
    ap.add_argument("--deadline-ms", type=float, default=250.0)
    ap.add_argument(
        "--batch-delay-ms",
        type=float,
        default=0.0,
        help="stall every flush this long via the serve.batch fault site "
        "(emulates a heavier model; makes overload reproducible anywhere)",
    )
    ap.add_argument("--dim", type=int, default=64, help="request vector length")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument(
        "--model", default=None, help="serve this saved FittedPipeline instead"
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="serving fleet size (one FrozenApplier clone per local "
        "device; pair with XLA_FLAGS=--xla_force_host_platform_device_count=N "
        "on CPU)",
    )
    ap.add_argument(
        "--swap-mid-run",
        action="store_true",
        help="blue/green hot-swap a freshly-built model in at the offer "
        "window's midpoint; the report gains the swap pause/prime times",
    )
    ap.add_argument(
        "--no-recorder",
        action="store_true",
        help="disable the flight recorder (request tracing); the "
        "on-vs-off pair pins the recorder overhead budget (p99/QPS "
        "within 5%%)",
    )
    ap.add_argument(
        "--straggler-ms",
        type=float,
        default=0.0,
        help="stall ONE replica's worker loop (--straggler-replica) "
        "this long per flush via a context-matched serve.worker plan "
        "(pre-claim, so the stalled batch stays hedgeable) — the "
        "deterministic straggler for hedging A/Bs",
    )
    ap.add_argument(
        "--straggler-replica",
        type=int,
        default=0,
        help="which replica index the straggler plan targets",
    )
    ap.add_argument(
        "--hedge-ms",
        type=float,
        default=None,
        help="enable hedged dispatch with this floor delay (needs "
        "--replicas >= 2); pair with --straggler-ms to see the p99 win",
    )
    args = ap.parse_args(argv)

    svc, item_shape = build_service(
        dim=args.dim,
        classes=args.classes,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_bound=args.queue_bound,
        deadline_ms=args.deadline_ms,
        model=args.model,
        replicas=args.replicas,
        recorder=not args.no_recorder,
        hedge_ms=args.hedge_ms,
    )
    swap_pipeline = None
    if args.swap_mid_run:
        if args.model:
            from keystone_tpu.workflow import FittedPipeline

            swap_pipeline = FittedPipeline.load(args.model)
        else:
            swap_pipeline = build_pipeline(
                dim=args.dim, classes=args.classes, seed=1
            )
    try:
        report = run_bench(
            svc,
            item_shape,
            qps=args.qps,
            duration=args.duration,
            burst=args.burst,
            deadline_ms=args.deadline_ms,
            batch_delay_ms=args.batch_delay_ms,
            swap_pipeline=swap_pipeline,
            straggler_ms=args.straggler_ms,
            straggler_replica=args.straggler_replica,
        )
    finally:
        svc.close()
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
