"""Open-loop load generator for the online serving subsystem.

Drives a :class:`keystone_tpu.serve.PipelineService` with a fixed
arrival schedule — requests are submitted at the target QPS whether or
not earlier ones completed (open loop: the honest way to measure a
service, since closed-loop generators self-throttle and hide queueing
collapse) — and reports latency percentiles, achieved throughput, mean
batch occupancy, and the shed/rejected breakdown.

Usage (CPU-safe; any laptop)::

    JAX_PLATFORMS=cpu python tools/serve_bench.py \
        --qps 2000 --duration 3 --max-batch 32 --max-wait-ms 2 \
        --deadline-ms 250 --queue-bound 128

    # burst mode: arrivals in groups of N at the same mean rate
    ... --burst 16

    # emulate a heavier model: stall every flush via the serve.batch
    # fault site (the chaos machinery doubles as a load shaper)
    ... --batch-delay-ms 10

    # serve a saved model instead of the synthetic default
    ... --model fitted.pkl --dim 512

    # replica fleet: one FrozenApplier clone per local device
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python tools/serve_bench.py --replicas 4 ...

    # blue/green hot-swap halfway through the offer window; the report
    # gains the swap pause + prime time and per-replica occupancy
    ... --replicas 4 --swap-mid-run

    # inject a straggler (replica 0 stalls every flush 40 ms) and hedge
    # around it: queued flushes escape onto a healthy replica
    ... --replicas 2 --straggler-ms 40 --hedge-ms 10

    # multi-tenant sharing A/B (ISSUE 14): N pipelines sharing a
    # featurization prefix, shared stage pool vs sharing disabled —
    # per-tenant QPS/p99, fairness ratio, pool counters, bit-identity
    python tools/serve_bench.py --tenants 3

The default workload is a small synthetic two-stage pipeline
(NormalizeRows → LinearMapper) so the tool measures the serving layer
itself; ``--model`` swaps in a real fitted pipeline whose input is a
``--dim``-vector.  Exit code 0; the report is one JSON object on
stdout.  ``bench.py --leg-serve`` embeds this report (overload config)
in the round artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import wait as futures_wait

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_pipeline(dim: int = 64, classes: int = 16, seed: int = 0):
    """The synthetic two-stage workload (NormalizeRows → LinearMapper)."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(dim, classes)).astype(np.float32))
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _fft_gather_feat(dim: int, branches: int, seed: int = 0):
    """A ``branches``-way gather of RandomSignNode → PaddedFFT →
    LinearRectifier chains — the MnistRandomFFT shape; returns
    ``(featurizer pipeline, feature dim)``.  Each branch's rectifier
    carries a DISTINCT constant: identical-structure branches lower to
    identical HLO that the persistent compile cache dedupes across
    programs (hiding trace costs in A/Bs), which real heterogeneous
    pipelines don't enjoy.  Shared by the AOT-artifact workload and the
    multi-tenant one — one definition, so the benches cannot silently
    drift apart."""
    from keystone_tpu.ops.stats import (
        LinearRectifier,
        PaddedFFT,
        RandomSignNode,
    )
    from keystone_tpu.workflow import Pipeline

    feat = Pipeline.gather(
        [
            RandomSignNode.init(dim, seed * 1000 + i)
            | PaddedFFT()
            | LinearRectifier(0.0, alpha=0.001 * (i + 1))
            for i in range(int(branches))
        ]
    )
    padded = 1 << (dim - 1).bit_length()
    return feat, branches * (padded // 2 + 1) * 2


def build_aot_pipeline(
    dim: int = 64, classes: int = 16, seed: int = 0, branches: int = 8
):
    """The cold-start/restart A/B workload: an ``_fft_gather_feat``
    featurizer feeding a normalized linear head.  The gather is the
    point: a plain two-stage chain fuses into ONE tiny program whose
    Python trace costs nothing, so an A/B over it measures only XLA
    backend time (which both arms pay); a real pipeline is N fused
    branch programs, each traced+lowered per padding bucket per
    replica clone — exactly the repeated host-side work the AOT
    artifact (one whole-graph program per bucket) removes."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows

    feat, feat_dim = _fft_gather_feat(dim, branches, seed)
    rng = np.random.default_rng(seed)
    w = jnp.asarray(
        rng.normal(size=(feat_dim, classes)).astype(np.float32)
    )
    return feat | NormalizeRows() | LinearMapper(w)


def build_service(
    dim: int = 64,
    classes: int = 16,
    max_batch: int = 32,
    max_wait_ms: float = 2.0,
    queue_bound: int = 128,
    deadline_ms: float | None = 250.0,
    model: str | None = None,
    seed: int = 0,
    replicas: int = 1,
    recorder: bool = True,
    **serve_kw,
):
    """A primed service over the synthetic two-stage pipeline (or a
    saved fitted model); returns ``(service, item_shape)``.
    ``recorder=False`` runs the PR-5 untraced path — the on/off pair is
    how the bench pins the flight recorder's overhead budget.  Extra
    keywords (``hedge_ms``, ``supervise``, ``heartbeat_s``, ...) pass
    through to :func:`keystone_tpu.serve.serve` — the hedging A/B and
    the chaos soak ride this."""
    import numpy as np

    from keystone_tpu.serve import serve

    if model:
        from keystone_tpu.workflow import FittedPipeline

        pipe = FittedPipeline.load(model)
    else:
        pipe = build_pipeline(dim=dim, classes=classes, seed=seed)
    item_shape = (int(dim),)
    svc = serve(
        pipe,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        deadline_ms=deadline_ms,
        example=np.zeros(item_shape, np.float32),
        name="serve_bench",
        replicas=replicas,
        recorder=recorder,
        **serve_kw,
    )
    return svc, item_shape


def _hist_delta(before: dict, after: dict, name: str) -> tuple:
    b = before.get(name) or {"count": 0, "sum": 0.0}
    a = after.get(name) or {"count": 0, "sum": 0.0}
    return a["count"] - b["count"], a["sum"] - b["sum"]


def run_bench(
    svc,
    item_shape,
    qps: float,
    duration: float,
    burst: int = 1,
    deadline_ms: float | None = None,
    batch_delay_ms: float = 0.0,
    swap_pipeline=None,
    straggler_ms: float = 0.0,
    straggler_replica: int = 0,
) -> dict:
    """Offer ``qps`` requests/sec for ``duration`` seconds (groups of
    ``burst`` arrivals at the same mean rate), wait for the tail to
    drain, and report.  Each burst group is admitted with ONE
    ``submit_many`` call (client-side batched submit, atomic
    all-or-none; the report's ``submit_mode`` field records it) —
    the client stops paying a lock round-trip per datum and a
    rejected group is counted as the unit it arrived as.  ``batch_delay_ms`` > 0 stalls every flush via a
    ``serve.batch:delay=…`` fault plan (emulating a heavier model, so a
    laptop can exercise overload deterministically).  ``swap_pipeline``:
    blue/green hot-swap this fitted pipeline in at the midpoint of the
    offer window; the report gains the swap info (pause, prime time) so
    the round artifact records what a live rollout costs under load.
    ``straggler_ms`` > 0 makes ONE replica (``straggler_replica``) stall
    every flush apply via a context-matched ``serve.replica`` plan —
    the deterministic straggler the hedging A/B measures against."""
    import contextlib

    import numpy as np

    from keystone_tpu import faults
    from keystone_tpu.obs import metrics
    from keystone_tpu.serve import Overloaded
    from keystone_tpu.utils import guard

    burst = max(1, int(burst))
    deadline_s = None if not deadline_ms else float(deadline_ms) / 1000.0
    snap0 = metrics.snapshot()
    c0 = dict(snap0.get("counters") or {})

    lock = threading.Lock()
    latencies: list = []
    outcomes = {"completed": 0, "shed": 0, "rejected": 0, "errors": 0}

    def record(fut, t_submit):
        t_done = time.monotonic()
        exc = fut.exception()
        with lock:
            if exc is None:
                outcomes["completed"] += 1
                latencies.append(t_done - t_submit)
            elif isinstance(exc, guard.DeadlineExceeded):
                outcomes["shed"] += 1
            else:
                outcomes["errors"] += 1

    rng = np.random.default_rng(1)
    payload = rng.normal(size=(burst,) + tuple(item_shape)).astype(np.float32)
    n_arrivals = max(1, int(round(qps * duration)))
    interval = burst / qps
    futs = []

    clauses = []
    if batch_delay_ms > 0:
        clauses.append(f"serve.batch:delay={batch_delay_ms / 1000.0}")
    if straggler_ms > 0:
        # serve.worker, not serve.replica: the stall lands in the worker
        # loop BEFORE the flush is claimed, so the batch stays
        # "still-unflushed" for the whole stall — the exact failure mode
        # hedged dispatch exists to rescue (a claimed flush mid-apply is
        # beyond any hedge that avoids duplicate device work)
        clauses.append(
            f"serve.worker:ctx.replica={int(straggler_replica)}"
            f":delay={straggler_ms / 1000.0}"
        )
    plan = (
        faults.inject(";".join(clauses)) if clauses else contextlib.nullcontext()
    )
    swap_info: dict = {}
    swap_thread = None
    if swap_pipeline is not None:

        def _swap_midway():
            time.sleep(duration / 2.0)
            try:
                swap_info.update(svc.swap(swap_pipeline, version="bench-swap"))
            except Exception as e:  # report it; don't kill the offer loop
                swap_info["error"] = f"{type(e).__name__}: {e}"

        swap_thread = threading.Thread(target=_swap_midway, daemon=True)
    t_start = time.monotonic()
    if swap_thread is not None:
        swap_thread.start()
    with plan:
        next_t = t_start
        sent = 0
        while sent < n_arrivals:
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(next_t - now, 0.002))
                continue
            # client-side batched submit: the whole burst group rides
            # ONE admission call (submit_many — atomic all-or-none)
            # instead of a per-datum submit loop, so the bench client
            # stops paying lock/condition round-trips per datum and an
            # overloaded group is rejected as the unit it arrived as
            group = payload[: min(burst, n_arrivals - sent)]
            t_submit = time.monotonic()
            try:
                batch_futs = svc.submit_many(group, deadline=deadline_s)
            except Overloaded:
                with lock:
                    outcomes["rejected"] += len(group)
            else:
                for fut in batch_futs:
                    fut.add_done_callback(
                        lambda f, t0=t_submit: record(f, t0)
                    )
                futs.extend(batch_futs)
            sent += len(group)
            next_t += interval
        # throughput denominator = the OFFER window: including the
        # post-offer tail-drain below would bias achieved_qps low by
        # queue_bound × batch-time per run, making round-over-round
        # movement track drain length instead of serving capacity
        offer_elapsed = time.monotonic() - t_start
        # drain the tail: everything admitted resolves (completed or
        # shed) — the report must account for every offered request
        futures_wait(futs, timeout=duration + 30.0)
    wall_elapsed = time.monotonic() - t_start
    if swap_thread is not None:
        swap_thread.join(timeout=duration + 60.0)
    replica_stats = svc.replica_statuses()

    snap1 = metrics.snapshot()
    c1 = dict(snap1.get("counters") or {})
    rows_n, rows_sum = _hist_delta(
        snap0.get("histograms") or {}, snap1.get("histograms") or {}, "serve.batch_rows"
    )
    lat_ms = sorted(x * 1000.0 for x in latencies)

    def pct(p):
        if not lat_ms:
            return None
        return round(float(np.percentile(lat_ms, p)), 2)

    completed = outcomes["completed"]
    report = {
        "offered_qps": qps,
        "duration_s": duration,
        "burst": burst,
        "submit_mode": "batched",
        "deadline_ms": deadline_ms,
        "batch_delay_ms": batch_delay_ms,
        "straggler_ms": straggler_ms,
        "hedges": int(
            c1.get("serve.hedges", 0.0) - c0.get("serve.hedges", 0.0)
        ),
        "hedge_wins": int(
            c1.get("serve.hedge_wins", 0.0) - c0.get("serve.hedge_wins", 0.0)
        ),
        "n_requests": n_arrivals,
        "completed": completed,
        "shed": outcomes["shed"],
        "rejected": outcomes["rejected"],
        "errors": outcomes["errors"],
        "achieved_qps": (
            round(completed / offer_elapsed, 1) if offer_elapsed > 0 else None
        ),
        # BOTH denominators, every leg: offered-window QPS (capacity —
        # the A/B comparand) AND drain-inclusive wall QPS.  One number
        # alone biases A/Bs: offered-window flatters a run that banked a
        # deep queue during the window and drained it after; wall-clock
        # punishes a run for its own queue bound.  Reporting the pair
        # (plus drain_s) makes the bias visible instead of implicit.
        "achieved_qps_wall": (
            round(completed / wall_elapsed, 1) if wall_elapsed > 0 else None
        ),
        "drain_s": round(wall_elapsed - offer_elapsed, 3),
        "p50_ms": pct(50),
        "p95_ms": pct(95),
        "p99_ms": pct(99),
        "max_ms": round(lat_ms[-1], 2) if lat_ms else None,
        "batches": rows_n,
        "mean_batch_occupancy": round(rows_sum / rows_n, 2) if rows_n else None,
        "shed_rate": round(
            (outcomes["shed"] + outcomes["rejected"]) / n_arrivals, 4
        ),
        "deadline_miss": int(
            c1.get("serve.deadline_miss", 0.0) - c0.get("serve.deadline_miss", 0.0)
        ),
        "replicas": len(replica_stats),
        "recorder": svc.recorder is not None,
        # flush share per replica: a healthy least-outstanding router
        # keeps these near-uniform; a skew marks a slow/broken replica.
        # Counter deltas, not replica statuses — statuses reset at a
        # swap (a fresh generation), counters span the whole run
        "replica_occupancy": _occupancy(replica_stats, c0, c1),
    }
    if swap_pipeline is not None:
        report["swap"] = {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in swap_info.items()
        }
    return report


def _occupancy(replica_stats: list, c0: dict, c1: dict) -> list:
    def flushes(i: int) -> int:
        key = f"serve.replica_flushes{{replica={i}}}"
        return int(c1.get(key, 0.0) - c0.get(key, 0.0))

    counts = {r["replica"]: flushes(r["replica"]) for r in replica_stats}
    total = sum(counts.values()) or 1
    return [
        {
            "replica": r["replica"],
            "version": r["version"],
            "flushes": counts[r["replica"]],
            "share": round(counts[r["replica"]] / total, 4),
            "errors": r["errors"],
            "breaker": r["breaker"],
        }
        for r in replica_stats
    ]


def build_tenant_models(
    tenants: int = 3,
    dim: int = 64,
    classes: int = 16,
    branches: int = 6,
    seed: int = 0,
):
    """N tenant pipelines SHARING a featurization prefix: every tenant
    gathers the SAME RandomSignNode → PaddedFFT → LinearRectifier
    branches (identical seeds/constants, so the prefix signatures are
    equal and the cross-pipeline planner shares them) feeding a
    per-tenant linear head (distinct weights — never shared, and with
    ``params() = None`` never collision-prone either)."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows

    models = {}
    for t in range(int(tenants)):
        # the SAME seed for every tenant's featurizer: equal prefix
        # signatures are what the cross-pipeline planner shares
        feat, feat_dim = _fft_gather_feat(dim, branches, seed)
        rng = np.random.default_rng(seed + 100 + t)
        w = jnp.asarray(
            rng.normal(size=(feat_dim, classes)).astype(np.float32)
        )
        models[f"t{t}"] = feat | NormalizeRows() | LinearMapper(w)
    return models


def build_tenant_service(
    tenants: int = 3,
    share: bool = True,
    dim: int = 64,
    classes: int = 16,
    branches: int = 6,
    max_batch: int = 32,
    max_wait_ms: float = 2.0,
    queue_bound: int = 256,
    deadline_ms: float | None = 1000.0,
    seed: int = 0,
    **serve_kw,
):
    """A primed multi-tenant service over :func:`build_tenant_models`;
    returns ``(service, item_shape, tenant_names)``.  ``share=False``
    is the A/B control arm: identical DRR batching and combined
    flushes, shared stage pool OFF — every tenant's walk recomputes the
    prefix."""
    import numpy as np

    from keystone_tpu.serve import serve_multi

    models = build_tenant_models(
        tenants=tenants, dim=dim, classes=classes, branches=branches, seed=seed
    )
    item_shape = (int(dim),)
    svc = serve_multi(
        models,
        share=share,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        deadline_ms=deadline_ms,
        example=np.zeros(item_shape, np.float32),
        name="serve_bench_mt",
        **serve_kw,
    )
    return svc, item_shape, list(models)


def run_tenants_bench(
    svc,
    item_shape,
    names,
    qps: float,
    duration: float,
    deadline_ms: float | None = None,
    burst: int = 8,
) -> dict:
    """Open-loop offered load split EQUALLY across tenants: each tick
    submits one ``burst``-sized ``submit_many`` group for one tenant,
    rotating the tenant list, at the aggregate mean rate (bursting
    keeps the GENERATOR's per-request Python off the measurement — a
    per-datum submit loop caps out near 3k QPS on a small host and
    would measure itself, not the service).  Waits for the tail and
    reports aggregate + per-tenant achieved QPS / p50 / p99 / outcome
    counts, plus the fairness ratio (max per-tenant p99 over min —
    1.0 = perfectly even service under equal offered load)."""
    import numpy as np

    from keystone_tpu.serve import Overloaded

    deadline_s = None if not deadline_ms else float(deadline_ms) / 1000.0
    burst = max(1, int(burst))
    lock = threading.Lock()
    lat: dict = {t: [] for t in names}
    outcomes: dict = {
        t: {"completed": 0, "shed": 0, "rejected": 0, "errors": 0}
        for t in names
    }

    def record(fut, t_submit, tenant):
        from keystone_tpu.utils import guard

        t_done = time.monotonic()
        exc = fut.exception()
        with lock:
            o = outcomes[tenant]
            if exc is None:
                o["completed"] += 1
                lat[tenant].append(t_done - t_submit)
            elif isinstance(exc, guard.DeadlineExceeded):
                o["shed"] += 1
            else:
                o["errors"] += 1

    rng = np.random.default_rng(1)
    payload = rng.normal(size=(burst,) + tuple(item_shape)).astype(np.float32)
    n_arrivals = max(len(names) * burst, int(round(qps * duration)))
    interval = burst / qps
    futs = []
    t_start = time.monotonic()
    next_t = t_start
    sent = 0
    tick = 0
    while sent < n_arrivals:
        now = time.monotonic()
        if now < next_t:
            time.sleep(min(next_t - now, 0.002))
            continue
        tenant = names[tick % len(names)]
        tick += 1
        k = min(burst, n_arrivals - sent)
        t_submit = time.monotonic()
        try:
            group = svc.submit_many(
                payload[:k], deadline=deadline_s, tenant=tenant
            )
        except Overloaded:
            with lock:
                outcomes[tenant]["rejected"] += k
        else:
            for fut in group:
                fut.add_done_callback(
                    lambda f, t0=t_submit, tn=tenant: record(f, t0, tn)
                )
            futs.extend(group)
        sent += k
        next_t += interval
    offer_elapsed = time.monotonic() - t_start
    futures_wait(futs, timeout=duration + 30.0)
    wall_elapsed = time.monotonic() - t_start

    def pct(vals, p):
        if not vals:
            return None
        return round(float(np.percentile([v * 1000.0 for v in vals], p)), 2)

    per_tenant = {}
    for t in names:
        per_tenant[t] = {
            **outcomes[t],
            "achieved_qps": (
                round(outcomes[t]["completed"] / offer_elapsed, 1)
                if offer_elapsed > 0
                else None
            ),
            "p50_ms": pct(lat[t], 50),
            "p99_ms": pct(lat[t], 99),
        }
    completed = sum(o["completed"] for o in outcomes.values())
    p99s = [v["p99_ms"] for v in per_tenant.values() if v["p99_ms"]]
    pool = (
        svc.status().get("stage_pool", {}) if hasattr(svc, "status") else {}
    )
    return {
        "offered_qps": qps,
        "duration_s": duration,
        "tenants": len(names),
        "n_requests": n_arrivals,
        "aggregate_completed": completed,
        "aggregate_qps": (
            round(completed / offer_elapsed, 1) if offer_elapsed > 0 else None
        ),
        # the same offered-window vs drain-inclusive pair run_bench
        # reports — every leg carries both denominators
        "aggregate_qps_wall": (
            round(completed / wall_elapsed, 1) if wall_elapsed > 0 else None
        ),
        "drain_s": round(wall_elapsed - offer_elapsed, 3),
        # per-tenant p99 spread under EQUAL offered load: the fairness
        # claim is max/min ≤ 1.25 (acceptance criterion)
        "fairness_p99_ratio": (
            round(max(p99s) / min(p99s), 3) if p99s and min(p99s) > 0 else None
        ),
        "per_tenant": per_tenant,
        "pool": {
            k: pool.get(k)
            for k in (
                "hits",
                "misses",
                "evictions",
                "shared_stages",
                "collision_refusals",
                "sharing",
            )
        },
    }


def run_tenants_ab(
    qps: float = 12000.0,
    duration: float = 2.0,
    rounds: int = 3,
    tenants: int = 3,
    branches: int = 12,
    max_batch: int = 64,
    deadline_ms: float = 8000.0,
    dim: int = 512,
) -> dict:
    """The multi-tenant sharing A/B: the IDENTICAL workload against a
    shared-pool service and a sharing-disabled twin in one process,
    order-alternating rounds with a discarded warmup (the
    run_overhead_pair discipline).  Also pins bit-identity: one probe
    batch per tenant must predict EXACTLY the same bytes shared vs
    unshared — sharing is an execution strategy, never a numerics
    change.

    Defaults sit the workload where the claim lives: offered load well
    past capacity (achieved QPS then measures capacity), a wide/deep
    featurization prefix (the shared compute), and the flight recorder
    OFF in both arms — per-request tracing Python is identical in both
    and at thousands of QPS on a small host it floors the measurable
    ratio toward 1 (the recorder's own budget is pinned by its own
    leg)."""
    import statistics

    import numpy as np

    services = {}
    for mode, share in (("shared", True), ("unshared", False)):
        svc, item_shape, names = build_tenant_service(
            tenants=tenants,
            share=share,
            dim=dim,
            branches=branches,
            max_batch=max_batch,
            queue_bound=max(256, max_batch * 8),
            deadline_ms=deadline_ms,
            recorder=False,
        )
        services[mode] = (svc, item_shape, names)

    # bit-identity probe BEFORE the load rounds (quiet services)
    rng = np.random.default_rng(7)
    probe = rng.normal(size=(dim,)).astype(np.float32)
    identical = True
    for t in services["shared"][2]:
        a = services["shared"][0].submit(probe, tenant=t).result(30.0)
        b = services["unshared"][0].submit(probe, tenant=t).result(30.0)
        identical = identical and np.array_equal(a, b)

    samples: dict = {"shared": [], "unshared": []}
    try:
        for rnd in range(max(2, int(rounds)) + 1):
            order = (
                ("shared", "unshared")
                if rnd % 2 == 0
                else ("unshared", "shared")
            )
            for mode in order:
                svc, item_shape, names = services[mode]
                rep = run_tenants_bench(
                    svc,
                    item_shape,
                    names,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=deadline_ms,
                )
                if rnd > 0:
                    samples[mode].append(rep)
    finally:
        for svc, _, _ in services.values():
            svc.close()

    def med(mode, key):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 3) if vals else None

    shared_qps = med("shared", "aggregate_qps")
    unshared_qps = med("unshared", "aggregate_qps")
    out = {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["shared"]),
        "tenants": tenants,
        "aggregate_qps_shared": shared_qps,
        "aggregate_qps_unshared": unshared_qps,
        # the acceptance claim: shared sustains ≥ 1.5× unshared
        "speedup": (
            round(shared_qps / unshared_qps, 3)
            if shared_qps and unshared_qps
            else None
        ),
        "fairness_p99_ratio": med("shared", "fairness_p99_ratio"),
        "predictions_identical": bool(identical),
        "pool": samples["shared"][-1]["pool"] if samples["shared"] else {},
        "per_tenant_shared": (
            samples["shared"][-1]["per_tenant"] if samples["shared"] else {}
        ),
    }
    return out


def run_overhead_pair(
    qps: float = 300.0,
    duration: float = 2.0,
    rounds: int = 4,
    max_batch: int = 16,
    deadline_ms: float = 500.0,
    batch_delay_ms: float = 2.0,
    dim: int = 64,
) -> dict:
    """The flight-recorder overhead pin: the SAME workload against two
    services in ONE process — recorder on vs off — interleaved with
    alternating order across ``rounds`` and a discarded warmup round, so
    process cold-start, CPU-frequency, and scheduler noise cancel
    instead of masquerading as tracing overhead.  Runs at a steady
    operating point BELOW the collapse knee (offered < capacity):
    in overload, achieved QPS sits on the collapse cliff where tiny
    capacity shifts swing it wildly and no 5%-budget claim is
    measurable.  Reports per-mode medians and on/off ratios — the
    acceptance budget is ratios within 5% of 1.0."""
    import statistics

    services = {}
    for mode, rec in (("on", True), ("off", False)):
        svc, item_shape = build_service(
            dim=dim,
            max_batch=max_batch,
            queue_bound=128,
            deadline_ms=deadline_ms,
            recorder=rec,
        )
        services[mode] = (svc, item_shape)
    samples = {"on": [], "off": []}
    try:
        for rnd in range(max(2, int(rounds)) + 1):
            order = ("on", "off") if rnd % 2 == 0 else ("off", "on")
            for mode in order:
                svc, item_shape = services[mode]
                rep = run_bench(
                    svc,
                    item_shape,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=deadline_ms,
                    batch_delay_ms=batch_delay_ms,
                )
                if rnd > 0:  # round 0 is the discarded warmup
                    samples[mode].append(rep)
    finally:
        for svc, _ in services.values():
            svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    out = {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["on"]),
        "batch_delay_ms": batch_delay_ms,
    }
    for mode in ("on", "off"):
        out[f"recorder_{mode}"] = {
            k: med(mode, k)
            for k in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms")
        }
    ratios = {}
    for key, name in (
        ("achieved_qps", "achieved_qps_ratio"),
        ("p99_ms", "p99_ratio"),
    ):
        on, off = out["recorder_on"].get(key), out["recorder_off"].get(key)
        if on and off:
            ratios[name] = round(on / off, 3)
    out["overhead"] = ratios
    return out


def run_straggler_ab(
    qps: float = 300.0,
    duration: float = 2.0,
    rounds: int = 4,
    replicas: int = 2,
    max_batch: int = 16,
    deadline_ms: float = 2000.0,
    straggler_ms: float = 40.0,
    hedge_ms: float = 10.0,
    dim: int = 64,
) -> dict:
    """The hedging acceptance pin: the SAME workload with ONE injected
    straggler replica (every flush on replica 0 stalls ``straggler_ms``)
    against two fleets in one process — hedging ON vs OFF — order-
    alternated across ``rounds`` with a discarded warmup, exactly the
    ``run_overhead_pair`` discipline.  Hedging must cut p99 (queued
    flushes escape the straggler's queue onto a healthy replica) at
    ≤ 5% achieved-QPS cost — hedge losers are claim-skips, not
    duplicated device work.  Reports per-mode medians plus
    ``p99_ratio`` (hedged/unhedged, want < 1) and ``qps_cost``
    (1 − hedged/unhedged QPS, want ≤ 0.05)."""
    import statistics

    services = {}
    for mode, hedge in (("hedged", hedge_ms), ("unhedged", None)):
        svc, item_shape = build_service(
            dim=dim,
            max_batch=max_batch,
            queue_bound=256,
            deadline_ms=deadline_ms,
            replicas=replicas,
            hedge_ms=hedge,
            # the straggler is an INJECTED stall, not a wedge: keep the
            # supervisor from "healing" the leg out from under the A/B
            supervise=False,
        )
        services[mode] = (svc, item_shape)
    samples = {"hedged": [], "unhedged": []}
    try:
        for rnd in range(max(2, int(rounds)) + 1):
            order = (
                ("hedged", "unhedged") if rnd % 2 == 0 else ("unhedged", "hedged")
            )
            for mode in order:
                svc, item_shape = services[mode]
                rep = run_bench(
                    svc,
                    item_shape,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=deadline_ms,
                    straggler_ms=straggler_ms,
                )
                if rnd > 0:  # round 0 is the discarded warmup
                    samples[mode].append(rep)
    finally:
        for svc, _ in services.values():
            svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    out = {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["hedged"]),
        "replicas": replicas,
        "straggler_ms": straggler_ms,
        "hedge_ms": hedge_ms,
    }
    for mode in ("hedged", "unhedged"):
        out[mode] = {
            k: med(mode, k)
            for k in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms", "max_ms")
        }
    out["hedged"]["hedges"] = sum(r["hedges"] for r in samples["hedged"])
    out["hedged"]["hedge_wins"] = sum(
        r["hedge_wins"] for r in samples["hedged"]
    )
    hedging = {}
    on_p99, off_p99 = out["hedged"].get("p99_ms"), out["unhedged"].get("p99_ms")
    if on_p99 and off_p99:
        hedging["p99_ratio"] = round(on_p99 / off_p99, 3)
    on_q, off_q = (
        out["hedged"].get("achieved_qps"),
        out["unhedged"].get("achieved_qps"),
    )
    if on_q and off_q:
        hedging["qps_cost"] = round(1.0 - on_q / off_q, 4)
    out["hedging"] = hedging
    return out


# ----------------------------------------------------- AOT artifact A/Bs
def build_gil_pipeline(
    dim: int = 64,
    classes: int = 16,
    burn_rounds: int = 300,
    seed: int = 0,
):
    """The COMPUTE-BOUND (not stall-emulated) workload for the
    thread-vs-process A/B: a deterministic pure-Python featurizer
    (iterated CRC mixing per row — interpreter-loop work that HOLDS the
    GIL, like real tokenize/ngram featurization stages) feeding the
    normalize→linear head.  On a multi-core host, N worker THREADS
    serialize on the GIL through this stage while N worker PROCESSES
    compute in parallel — which is exactly the claim
    ``bench.py --leg-serve-procs`` measures.  Bit-deterministic: the
    burn factor is integer CRC math on the row's exact bytes, so
    thread and process fleets must produce identical output bytes."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    from tools.gilburn import GilBurnFeature

    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(dim, classes)).astype(np.float32))
    return (
        Pipeline.of(GilBurnFeature(rounds=burn_rounds))
        | NormalizeRows()
        | LinearMapper(w)
    )


def build_gil_service(
    mode: str,
    workers: int = 2,
    dim: int = 64,
    burn_rounds: int = 300,
    max_batch: int = 16,
    queue_bound: int = 512,
    max_wait_ms: float = 2.0,
    seed: int = 0,
    **serve_kw,
):
    """A primed service over the GIL-bound pipeline: ``mode="thread"``
    → ``replicas=workers`` worker threads (the PR-8 fleet),
    ``mode="process"`` → ``workers=workers`` worker processes (PR-15).
    Recorder off in both arms (identical per-request Python, pinned by
    its own leg)."""
    import numpy as np

    from keystone_tpu.serve import serve

    pipe = build_gil_pipeline(dim=dim, burn_rounds=burn_rounds, seed=seed)
    fleet_kw = (
        dict(workers=int(workers))
        if mode == "process"
        else dict(replicas=int(workers))
    )
    svc = serve(
        pipe,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        deadline_ms=None,
        example=np.zeros((dim,), np.float32),
        name=f"procs_{mode}",
        recorder=False,
        **fleet_kw,
        **serve_kw,
    )
    return svc, (int(dim),)


def run_procs_ab(
    qps: float = 2500.0,
    duration: float = 2.5,
    rounds: int = 3,
    workers: int = 2,
    dim: int = 64,
    burn_rounds: int = 2000,
    max_batch: int = 16,
) -> dict:
    """Thread-vs-process fleet A/B on the compute-bound workload:
    IDENTICAL open-loop load against ``replicas=workers`` threads and
    ``workers=workers`` processes, order-alternating rounds with a
    discarded warmup (the run_overhead_pair discipline), plus a
    bit-identity probe (one fixed batch serially through both fleets
    must produce byte-identical predictions).

    HONEST SCALING BOUND: processes can beat threads only where cores
    exist — the report carries ``cores`` (the scheduler affinity mask)
    and ``achievable_speedup = min(workers, cores)``.  On a >= 2-core
    host the acceptance claim is speedup >= 1.8×; a 1-core host cannot
    express the claim (both arms share one core) and the leg instead
    requires the process fleet to be within 30% of the threaded one
    (the wire protocol's overhead bound) while still pinning
    bit-identity.  The PR-8 fleet leg's speedup was STALL-dominated by
    construction (an injected 40 ms flush delay that releases the GIL)
    — it measured router concurrency, not multi-core compute; THIS leg
    is the compute-bound claim."""
    import os as _os
    import statistics

    import numpy as np

    cores = len(_os.sched_getaffinity(0))
    services = {}
    samples: dict = {"thread": [], "process": []}
    try:
        # build + probe INSIDE the try: a spawn failure or a hung probe
        # must still close (and reap the worker processes of) whatever
        # was already built
        for mode in ("thread", "process"):
            services[mode] = build_gil_service(
                mode,
                workers=workers,
                dim=dim,
                burn_rounds=burn_rounds,
                max_batch=max_batch,
                # offered load sits ABOVE capacity so achieved QPS
                # measures capacity; a modest bound keeps the
                # post-offer tail short
                queue_bound=512,
            )

        # bit-identity probe on quiet services (serial submits)
        rng = np.random.default_rng(11)
        probe = rng.normal(size=(24, dim)).astype(np.float32)
        digests = {}
        for mode, (svc, _shape) in services.items():
            outs = [
                np.asarray(svc.submit(probe[i]).result(timeout=60.0))
                for i in range(probe.shape[0])
            ]
            digests[mode] = _prediction_sha(np.stack(outs))
        identical = digests["thread"] == digests["process"]

        for rnd in range(max(2, int(rounds)) + 1):
            order = (
                ("thread", "process")
                if rnd % 2 == 0
                else ("process", "thread")
            )
            for mode in order:
                svc, item_shape = services[mode]
                rep = run_bench(
                    svc,
                    item_shape,
                    qps=qps,
                    duration=duration if rnd > 0 else 0.5,
                    deadline_ms=None,
                )
                if rnd > 0:
                    samples[mode].append(rep)
    finally:
        for svc, _ in services.values():
            svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    t_qps, p_qps = med("thread", "achieved_qps"), med("process", "achieved_qps")
    speedup = round(p_qps / t_qps, 3) if t_qps and p_qps else None
    achievable = min(int(workers), cores)
    ok = bool(identical) and speedup is not None and (
        speedup >= 1.8 if cores >= 2 else speedup >= 0.7
    )
    return {
        "offered_qps": qps,
        "duration_s": duration,
        "rounds": len(samples["thread"]),
        "workers": workers,
        "cores": cores,
        "burn_rounds": burn_rounds,
        "thread_qps": t_qps,
        "process_qps": p_qps,
        "thread_p99_ms": med("thread", "p99_ms"),
        "process_p99_ms": med("process", "p99_ms"),
        "speedup": speedup,
        "achievable_speedup": achievable,
        "cores_limited": cores < int(workers),
        "predictions_identical": bool(identical),
        "prediction_sha": digests,
        "ok": ok,
        "note": (
            "compute-bound (GIL-held featurizer) A/B: threads measure "
            "the GIL, processes measure cores.  The PR-8 fleet leg's "
            "~2.6x was stall-dominated by construction (injected "
            "GIL-releasing flush delay) and was never a multi-core "
            "hardware claim."
        ),
    }


# --------------------------------------------------------- ingress A/B
def _http_datum_worker(host, port, rows, stop_evt, lock, lats, counts):
    """One persistent-connection HTTP/JSON client: per-datum POSTs on a
    keep-alive HTTP/1.1 connection (the pre-ingress submit shape, minus
    the per-request TCP handshake the keep-alive satellite removed —
    measuring WITH keep-alive is the conservative comparison)."""
    import http.client

    def connect():
        return http.client.HTTPConnection(host, port, timeout=30.0)

    conn = connect()
    i = 0
    try:
        while not stop_evt.is_set():
            body = json.dumps({"instance": rows[i % len(rows)]}).encode()
            i += 1
            t0 = time.monotonic()
            try:
                conn.request(
                    "POST",
                    "/predict",
                    body,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except Exception:
                ok = False
                try:
                    conn.close()
                except Exception:
                    pass
                conn = connect()
            dt = time.monotonic() - t0
            with lock:
                if ok:
                    counts["completed"] += 1
                    lats.append(dt)
                else:
                    counts["errors"] += 1
    finally:
        try:
            conn.close()
        except Exception:
            pass


def _binary_batch_worker(host, port, batch, stop_evt, lock, lats, counts):
    """One binary batch-protocol client: whole ``(b, dim)`` batches per
    CRC-framed message on a persistent connection (the zero-copy path)."""
    from keystone_tpu.serve.ingress import BinaryClient

    b = int(batch.shape[0])
    try:
        with BinaryClient(host, port) as c:
            while not stop_evt.is_set():
                t0 = time.monotonic()
                try:
                    c.predict(batch)
                    ok = True
                except Exception:
                    ok = False
                dt = time.monotonic() - t0
                with lock:
                    if ok:
                        counts["completed"] += b
                        lats.append(dt)
                    else:
                        counts["errors"] += b
    except Exception:
        with lock:
            counts["errors"] += b


def _saturate(worker, n_clients, args_common, duration) -> dict:
    """Closed-loop saturation leg: ``n_clients`` persistent-connection
    client threads hammer the front end for ``duration`` seconds; the
    per-datum rate over the measurement window IS the ceiling (a closed
    loop self-throttles at capacity — exactly the number a ceiling
    claim wants, unlike an open loop which would measure queueing)."""
    import numpy as np

    lock = threading.Lock()
    lats: list = []
    counts = {"completed": 0, "errors": 0}
    stop_evt = threading.Event()
    threads = [
        threading.Thread(
            target=worker,
            args=args_common + (stop_evt, lock, lats, counts),
            daemon=True,
        )
        for _ in range(int(n_clients))
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(max(0.2, float(duration)))
    stop_evt.set()
    for t in threads:
        t.join(30.0)
    elapsed = time.monotonic() - t0
    lat_ms = sorted(x * 1000.0 for x in lats)

    def pct(p):
        if not lat_ms:
            return None
        return round(float(np.percentile(lat_ms, p)), 2)

    return {
        "clients": int(n_clients),
        "completed": counts["completed"],
        "errors": counts["errors"],
        "per_datum_qps": (
            round(counts["completed"] / elapsed, 1) if elapsed > 0 else None
        ),
        # closed loop: the offer window IS the wall window (no tail to
        # drain past stop), so the two denominators coincide — reported
        # under both names so every leg carries the pair
        "per_datum_qps_wall": (
            round(counts["completed"] / elapsed, 1) if elapsed > 0 else None
        ),
        "p50_ms": pct(50),
        "p99_ms": pct(99),
    }


def run_ingress_ab(
    duration: float = 2.0,
    rounds: int = 2,
    dim: int = 64,
    max_batch: int = 64,
    shards: int = 2,
    http_clients: int = 8,
    bin_clients: int = 4,
    bin_batch: int | None = None,
) -> dict:
    """The zero-copy ingress acceptance A/B: ONE service + compute
    fleet behind ONE :class:`~keystone_tpu.serve.ingress.AsyncIngress`
    port, saturated twice — per-datum HTTP/JSON on keep-alive threaded
    connections (the sniffed slow path, i.e. the old front end's submit
    shape) vs whole-batch binary frames on the event loop.  Order-
    alternating rounds with a discarded warmup (the run_overhead_pair
    discipline); per-datum QPS and p99 for both; the acceptance claim
    is binary >= 3x HTTP per-datum QPS with bit-identical predictions.

    Also reports the zero-copy counters: ``serve.preformed_flushes``
    (binary batches that skipped stack+pad) and the per-arm
    ``ingress.bytes_copied`` delta — the JSON arm charges every parsed
    payload byte, the binary arm charges none."""
    import statistics

    import numpy as np

    from keystone_tpu.obs import metrics
    from keystone_tpu.serve.ingress import BinaryClient, serve_ingress

    bin_batch = int(bin_batch or max_batch)
    svc, item_shape = build_service(
        dim=dim,
        max_batch=max_batch,
        max_wait_ms=2.0,
        queue_bound=4096,
        deadline_ms=None,
        recorder=False,
    )
    front = serve_ingress(svc, port=0, shards=shards)
    samples: dict = {"http": [], "binary": []}
    rng = np.random.default_rng(3)
    probe = rng.normal(size=(bin_batch,) + tuple(item_shape)).astype(
        np.float32
    )
    try:
        # bit-identity pin on the quiet service: the SAME batch through
        # both submit paths must predict the same bytes.  (float32 JSON
        # round-trips exactly: every float32 is representable in the
        # JSON text and comes back bit-equal through float64.)
        with BinaryClient("127.0.0.1", front.port) as c:
            bin_out = c.predict(probe)
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{front.port}/predict",
            data=json.dumps({"instances": probe.tolist()}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            http_out = np.asarray(
                json.loads(resp.read())["predictions"], dtype=np.float32
            )
        identical = bool(np.array_equal(bin_out, http_out))

        rows_json = [r.tolist() for r in probe]
        bytes_copied: dict = {}
        pre0 = metrics.REGISTRY.counter_value("serve.preformed_flushes")
        for rnd in range(max(1, int(rounds)) + 1):
            order = (
                ("http", "binary") if rnd % 2 == 0 else ("binary", "http")
            )
            for mode in order:
                b0 = metrics.REGISTRY.counter_value("ingress.bytes_copied")
                if mode == "http":
                    rep = _saturate(
                        _http_datum_worker,
                        http_clients,
                        ("127.0.0.1", front.port, rows_json),
                        duration if rnd > 0 else 0.5,
                    )
                else:
                    rep = _saturate(
                        _binary_batch_worker,
                        bin_clients,
                        ("127.0.0.1", front.port, probe),
                        duration if rnd > 0 else 0.5,
                    )
                rep["bytes_copied"] = int(
                    metrics.REGISTRY.counter_value("ingress.bytes_copied")
                    - b0
                )
                if rnd > 0:
                    samples[mode].append(rep)
                    bytes_copied[mode] = (
                        bytes_copied.get(mode, 0) + rep["bytes_copied"]
                    )
        preformed = int(
            metrics.REGISTRY.counter_value("serve.preformed_flushes") - pre0
        )
    finally:
        front.stop()
        svc.close()

    def med(mode: str, key: str):
        vals = [r[key] for r in samples[mode] if r.get(key) is not None]
        return round(float(statistics.median(vals)), 2) if vals else None

    http_qps = med("http", "per_datum_qps")
    bin_qps = med("binary", "per_datum_qps")
    speedup = (
        round(bin_qps / http_qps, 3) if http_qps and bin_qps else None
    )
    return {
        "mode": "closed-loop saturation",
        "duration_s": duration,
        "rounds": len(samples["http"]),
        "dim": dim,
        "max_batch": max_batch,
        "shards": shards,
        "bin_batch": bin_batch,
        "http": {
            "clients": http_clients,
            "per_datum_qps": http_qps,
            "per_datum_qps_wall": med("http", "per_datum_qps_wall"),
            "p50_ms": med("http", "p50_ms"),
            "p99_ms": med("http", "p99_ms"),
            "errors": sum(r["errors"] for r in samples["http"]),
        },
        "binary": {
            "clients": bin_clients,
            "per_datum_qps": bin_qps,
            "per_datum_qps_wall": med("binary", "per_datum_qps_wall"),
            "frame_p50_ms": med("binary", "p50_ms"),
            "frame_p99_ms": med("binary", "p99_ms"),
            "errors": sum(r["errors"] for r in samples["binary"]),
        },
        "speedup": speedup,
        "predictions_identical": identical,
        "preformed_flushes": preformed,
        "bytes_copied": bytes_copied,
        # the acceptance claim: binary batch path sustains >= 3x the
        # threaded HTTP/JSON per-datum ceiling, predictions bit-equal
        "ok": bool(identical) and speedup is not None and speedup >= 3.0,
    }


def run_autoscale_scenario(
    qps: float = 2000.0,
    duration: float = 4.0,
    idle_timeout: float = 60.0,
    max_workers: int = 3,
    dim: int = 64,
    burn_rounds: int = 2000,
) -> dict:
    """The autoscale acceptance scenario: a 1-worker process fleet
    under sustained open-loop load must scale up (1 → N as queue/
    occupancy pressure mounts), then — offered load gone — scale back
    down to the floor, with EVERY submitted request resolving
    successfully (zero dropped, zero hung: the queue bound is sized
    above the offered burst so nothing is sheddable)."""
    import time as _time

    import numpy as np

    from keystone_tpu.serve import serve

    pipe = build_gil_pipeline(dim=dim, burn_rounds=burn_rounds)
    svc = serve(
        pipe,
        max_batch=16,
        max_wait_ms=2.0,
        queue_bound=100_000,
        deadline_ms=None,
        example=np.zeros((dim,), np.float32),
        name="procs_autoscale",
        recorder=False,
        workers=1,
        autoscale=dict(
            min_workers=1,
            max_workers=int(max_workers),
            interval_s=0.4,
            up_queue_frac=0.002,  # queue_bound is huge; react to depth
            up_cooldown_s=1.0,
            down_ticks=4,
            down_cooldown_s=3.0,
            # scale-down keyed to an empty queue + calm burn: the 60 s
            # occupancy window decays too slowly for a seconds-scale
            # scenario to gate on it
            down_occupancy=0.95,
        ),
    )
    peak = 1
    workers_track = []
    futs = []
    rng = np.random.default_rng(5)
    payload = rng.normal(size=(64, dim)).astype(np.float32)
    t0 = _time.monotonic()
    try:
        interval = 1.0 / qps
        next_t = t0
        i = 0
        while _time.monotonic() - t0 < duration:
            now = _time.monotonic()
            if now < next_t:
                _time.sleep(min(next_t - now, 0.002))
                continue
            futs.append(svc.submit(payload[i % payload.shape[0]]))
            i += 1
            next_t += interval
            if i % 50 == 0:
                n = svc.replicas
                workers_track.append(n)
                peak = max(peak, n)
        # drain: every admitted request must complete
        from concurrent.futures import TimeoutError as _FTimeout

        completed = 0
        errors = 0
        hung = 0
        for f in futs:
            try:
                f.result(timeout=180.0)
                completed += 1
            except _FTimeout:
                hung += 1
            except Exception:
                errors += 1
        peak = max(peak, svc.replicas)
        # idle: the fleet must come back down to the floor
        deadline = _time.monotonic() + idle_timeout
        final = svc.replicas
        while final > 1 and _time.monotonic() < deadline:
            _time.sleep(0.5)
            final = svc.replicas
        scaler = svc.autoscaler.status() if svc.autoscaler else {}
    finally:
        svc.close()
    return {
        "offered_qps": qps,
        "duration_s": duration,
        "submitted": len(futs),
        "completed": completed,
        "errors": errors,
        "hung": hung,
        "peak_workers": peak,
        "final_workers": final,
        "workers_track": workers_track[-20:],
        "scaled_up": peak > 1,
        "scaled_down": final == 1,
        "autoscaler": scaler,
        "ok": (
            errors == 0 and hung == 0 and peak > 1 and final == 1
        ),
    }


def publish_bench_registry(
    root: str,
    dim: int = 64,
    classes: int = 16,
    max_batch: int = 32,
    seed: int = 0,
    builder=None,
) -> str:
    """Publish an A/B workload into a fresh registry at ``root`` WITH
    its AOT artifact bundle; returns the version id.  Both arms of
    every A/B deploy from this — identical model bytes, the only
    difference being whether the deploy loads the artifacts.
    ``builder``: the pipeline factory (default :func:`build_pipeline`;
    the restart A/B uses :func:`build_aot_pipeline`)."""
    import numpy as np

    from keystone_tpu.serve import ModelRegistry
    from keystone_tpu.serve.service import default_buckets

    pipe = (builder or build_pipeline)(dim=dim, classes=classes, seed=seed)
    bundle = pipe.freeze().export_artifacts(
        example=np.zeros((dim,), np.float32),
        buckets=default_buckets(max_batch),
    )
    return ModelRegistry(root).publish(pipe, artifacts=bundle)


def run_cold_start(
    arm: str,
    registry_root: str,
    dim: int = 64,
    max_batch: int = 32,
) -> dict:
    """ONE cold-start-to-first-prediction sample, in THIS process (the
    A/B driver runs each sample in a fresh subprocess — in-process the
    second arm would ride the first's shared jit caches and measure
    nothing).  ``arm``: ``artifact`` loads the registry's AOT bundle,
    ``compile`` ignores it (the pre-artifact deploy path).  Reports the
    registry-load → service-ready (primed) → first-prediction
    timeline."""
    import time

    import numpy as np

    from keystone_tpu.obs import metrics
    from keystone_tpu.serve import ModelRegistry, serve

    reg = ModelRegistry(registry_root)
    c0 = dict(metrics.snapshot().get("counters") or {})
    t0 = time.perf_counter()
    fitted, version = reg.load()
    t_load = time.perf_counter() - t0
    arts = reg.load_artifacts(version) if arm == "artifact" else None
    svc = serve(
        fitted,
        max_batch=max_batch,
        deadline_ms=None,
        example=np.zeros((dim,), np.float32),
        name="coldstart",
        supervise=False,
        artifacts=arts,
    )
    t_ready = time.perf_counter() - t0
    x = np.random.default_rng(7).normal(size=(dim,)).astype(np.float32)
    y = np.asarray(svc.submit(x).result())
    t_first = time.perf_counter() - t0
    snap = metrics.snapshot()
    c1 = dict(snap.get("counters") or {})
    hists = snap.get("histograms") or {}
    prime = {
        src: (hists.get(f"serve.prime_seconds{{source={src}}}") or {}).get(
            "count", 0
        )
        for src in ("artifact", "cache", "compile")
    }
    svc.close()
    return {
        "arm": arm,
        "model_load_s": round(t_load, 4),
        "ready_s": round(t_ready, 4),
        "first_prediction_s": round(t_first, 4),
        "prime_sources": prime,
        "artifact_hits": int(
            c1.get("serve.artifact_hits", 0) - c0.get("serve.artifact_hits", 0)
        ),
        "artifact_fallbacks": int(
            c1.get("serve.artifact_fallbacks", 0)
            - c0.get("serve.artifact_fallbacks", 0)
        ),
        # FULL-output digest: predictions_match is a bit-for-bit claim,
        # so it must cover every byte, not an eyeball head
        "prediction_sha": _prediction_sha(y),
        "prediction_head": [round(float(v), 6) for v in y.ravel()[:4]],
    }


def _prediction_sha(y) -> str:
    # the repo's one full-array digest (shape+dtype+bytes): the parity
    # claim must not grow a second hashing implementation to drift from
    from keystone_tpu.utils.hashing import array_fingerprint

    return array_fingerprint(y)


def run_restart(
    arm: str,
    registry_root: str,
    dim: int = 64,
    max_batch: int = 32,
    replicas: int = 2,
    timeout_s: float = 60.0,
) -> dict:
    """ONE supervisor restart-to-rejoin sample: serve the registry's
    model on a 2-replica fleet, crash replica 0's worker via an
    injected ``serve.worker`` fault under light load, and report how
    long the supervisor's heal (re-clone + re-prime + adopt) took —
    the window during which the fleet runs a replica short.  With
    ``arm="artifact"`` the replacement primes from installed AOT
    programs; ``compile`` re-traces every bucket."""
    import time

    import numpy as np

    from keystone_tpu import faults
    from keystone_tpu.serve import ModelRegistry, serve

    reg = ModelRegistry(registry_root)
    fitted, version = reg.load()
    arts = reg.load_artifacts(version) if arm == "artifact" else None
    svc = serve(
        fitted,
        max_batch=max_batch,
        deadline_ms=None,
        example=np.zeros((dim,), np.float32),
        name="restart_bench",
        replicas=replicas,
        supervise=True,
        supervise_interval_s=0.05,
        heartbeat_s=30.0,
        artifacts=arts,
    )
    rng = np.random.default_rng(11)
    payload = rng.normal(size=(dim,)).astype(np.float32)
    try:
        # warm both replicas with real traffic first
        for _ in range(4):
            svc.submit(payload).result()
        with faults.inject("serve.worker:ctx.replica=0:raise:times=1"):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    svc.submit(payload).result(timeout=10.0)
                except Exception:
                    pass  # the crashed flush's riders fail typed; fine
                if svc.supervisor.restarts_total >= 1:
                    break
                time.sleep(0.01)
        last = svc.supervisor.last_restart
        # the healed fleet must answer cleanly
        y = np.asarray(svc.submit(payload).result(timeout=30.0))
    finally:
        svc.close()
    if not last:
        raise RuntimeError("supervisor never restarted the crashed replica")
    return {
        "arm": arm,
        "restart_to_rejoin_s": last["seconds"],
        "reason": last["reason"],
        "restarts": svc.supervisor.restarts_total,
        "prediction_sha": _prediction_sha(y),
        "prediction_head": [round(float(v), 6) for v in y.ravel()[:4]],
    }


def _artifact_arm_subprocess(
    flag: str, arm: str, root: str, dim: int, max_batch: int
):
    """Run one A/B arm in a pinned-env subprocess: fresh process (cold
    jit caches, cold shared-apply cache) and a FRESH empty persistent
    compile cache per invocation — both arms start equally cold, so
    the delta is the artifact tier, not leftover warmth.  The workload
    geometry (dim/max_batch) is forwarded explicitly: the arm must
    serve exactly what the driver published."""
    import shutil
    import subprocess
    import tempfile

    env = dict(os.environ)
    cache = tempfile.mkdtemp(prefix="keystone-ab-xla-")
    env["KEYSTONE_COMPILE_CACHE"] = cache
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.abspath(__file__),
                flag,
                arm,
                "--registry",
                root,
                "--dim",
                str(int(dim)),
                "--max-batch",
                str(int(max_batch)),
            ],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{flag} {arm} arm failed: {proc.stderr[-400:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        shutil.rmtree(cache, ignore_errors=True)


def _ab_summary(samples: dict, key: str) -> dict:
    import statistics

    out = {}
    for arm in ("artifact", "compile"):
        vals = [s[key] for s in samples[arm] if s.get(key) is not None]
        out[arm] = round(float(statistics.median(vals)), 4) if vals else None
    if out.get("artifact") and out.get("compile"):
        out["speedup"] = round(out["compile"] / out["artifact"], 3)
    return out


def _run_artifact_ab(
    flag: str,
    summary_keys,
    dim: int,
    max_batch: int,
    rounds: int,
    registry_root,
) -> dict:
    """The shared A/B harness: publish ONE registry version
    (+artifacts, the heterogeneous-branch ``build_aot_pipeline``
    workload), run each arm ``rounds`` times in order-alternated fresh
    subprocesses, report per-arm medians + speedups, and pin the parity
    claim — every sample's FULL prediction digest must agree across
    arms.  Cleans up the registry it created."""
    import shutil
    import tempfile

    created = registry_root is None
    root = registry_root or tempfile.mkdtemp(prefix="keystone-artifact-ab-")
    try:
        publish_bench_registry(
            root, dim=dim, max_batch=max_batch, builder=build_aot_pipeline
        )
        samples = {"artifact": [], "compile": []}
        for rnd in range(max(1, int(rounds))):
            order = (
                ("artifact", "compile")
                if rnd % 2 == 0
                else ("compile", "artifact")
            )
            for arm in order:
                samples[arm].append(
                    _artifact_arm_subprocess(flag, arm, root, dim, max_batch)
                )
        out = {"rounds": rounds, "dim": dim, "max_batch": max_batch}
        for key in summary_keys:
            out[key] = _ab_summary(samples, key)
        shas = {
            s.get("prediction_sha")
            for arm_samples in samples.values()
            for s in arm_samples
        }
        out["predictions_match"] = len(shas) == 1
        out["samples"] = samples
        return out
    finally:
        if created:
            shutil.rmtree(root, ignore_errors=True)


def run_cold_start_ab(
    dim: int = 64, max_batch: int = 32, rounds: int = 2, registry_root=None
) -> dict:
    """The cold-start A/B: median registry-load → service-ready →
    first-prediction timeline per arm, plus the artifact speedup and
    the full-digest parity pin."""
    out = _run_artifact_ab(
        "--cold-start-arm",
        ("first_prediction_s", "ready_s"),
        dim,
        max_batch,
        rounds,
        registry_root,
    )
    out["prime_sources"] = {
        arm: out["samples"][arm][0]["prime_sources"] for arm in out["samples"]
    }
    return out


def run_restart_ab(
    dim: int = 64, max_batch: int = 32, rounds: int = 2, registry_root=None
) -> dict:
    """The supervisor heal A/B: same registry, same injected worker
    crash, restart-to-rejoin latency with artifact-primed replacements
    vs recompiled ones.  (The multi-branch workload matters: a heal
    re-builds every per-instance branch program — exactly the trace
    work this A/B exposes; a fused two-stage chain re-traces nearly
    nothing.)"""
    return _run_artifact_ab(
        "--restart-arm",
        ("restart_to_rejoin_s",),
        dim,
        max_batch,
        rounds,
        registry_root,
    )


def run_plan_ab(
    dim: int = 64,
    classes: int = 16,
    max_batch: int = 32,
    qps: float = 300.0,
    duration: float = 3.0,
    deadline_ms: float = 500.0,
    queue_bound: int = 256,
    seed: int = 0,
    drift_duration: float = 3.0,
    drift_qps: float = 250.0,
) -> dict:
    """Planned-vs-static A/B (ISSUE 20): one fitted pipeline served
    with the cost-based :class:`~keystone_tpu.planner.PhysicalPlan`
    installed (sampled winners + derived serving knobs) against the
    static defaults — on the raw forward leg and the open-loop serve
    leg — plus a live :class:`~keystone_tpu.planner.PlanTuner` retune
    under the zoo's ``drift`` scenario.  The acceptance gates:
    ``speedup`` >= 1.0 (the plan matches or beats the defaults; off-TPU
    both arms run identical physics, so ~1.0 is the honest expectation)
    and the drift sub-check either improves windowed p99 or reverts via
    the bake guard with ``lost_futures == 0``."""
    import numpy as np

    from keystone_tpu import planner
    from keystone_tpu.serve import serve
    from keystone_tpu.workflow.dataset import Dataset

    fitted = build_pipeline(dim=dim, classes=classes, seed=seed).fit()
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(max(256, 4 * max_batch), dim)).astype(np.float32)
    item_shape = (int(dim),)

    # ---- forward A/B: static defaults vs the sampled plan.  The two
    # appliers are timed in INTERLEAVED rounds (ambient CPU-clock drift
    # would otherwise dominate a back-to-back pair of µs-scale arms)
    # and each arm keeps its best round.
    planner.clear_plan()
    frozen_static = fitted.freeze()
    plan = planner.build_plan(
        fitted, example=X[: 2 * max_batch], max_batch=max_batch, seed=seed
    )
    frozen_planned = fitted.freeze(plan=plan)  # installs the plan

    rows = min(X.shape[0], 4 * max_batch)
    ds = Dataset(X[:rows], shard=False)
    best = {"static": None, "planned": None}
    arms = (("static", frozen_static), ("planned", frozen_planned))

    def _enter_arm(name):
        # mode gates (matmul) resolve at APPLY time through the
        # registry, so the static arm must run with the plan cleared
        if name == "planned":
            planner.install_plan(plan, source="serve")
        else:
            planner.clear_plan()

    for name, frozen in arms:  # warmup pays trace/compile
        _enter_arm(name)
        frozen(ds)
    for _ in range(15):
        for name, frozen in arms:
            _enter_arm(name)
            t0 = time.perf_counter()
            frozen(ds)
            dt = time.perf_counter() - t0
            if best[name] is None or dt < best[name]:
                best[name] = dt
    planner.install_plan(plan, source="serve")
    static_ips = float(rows) / best["static"] if best["static"] else 0.0
    planned_ips = float(rows) / best["planned"] if best["planned"] else 0.0
    forward = {
        "static_ips": round(static_ips, 1),
        "planned_ips": round(planned_ips, 1),
        "speedup": (
            round(planned_ips / static_ips, 2) if static_ips else None
        ),
    }

    # ---- serve A/B: identical open-loop load; the planned arm leaves
    # every knob unset so the plan tier resolves them, the static arm
    # clears the plan so the static defaults resolve
    def serve_arm(planned: bool) -> dict:
        if planned:
            planner.install_plan(plan, source="serve")
        else:
            planner.clear_plan()
        svc = serve(
            fitted,
            max_batch=max_batch,
            queue_bound=queue_bound,
            deadline_ms=deadline_ms,
            example=np.zeros(item_shape, np.float32),
            name="plan_ab",
        )
        try:
            return run_bench(
                svc,
                item_shape,
                qps=qps,
                duration=duration,
                deadline_ms=deadline_ms,
            )
        finally:
            svc.close()

    static_serve = serve_arm(False)
    planned_serve = serve_arm(True)
    serve_ab = {
        "static": {
            k: static_serve.get(k)
            for k in ("achieved_qps", "p50_ms", "p99_ms", "completed")
        },
        "planned": {
            k: planned_serve.get(k)
            for k in ("achieved_qps", "p50_ms", "p99_ms", "completed")
        },
        "speedup": (
            round(
                float(planned_serve["achieved_qps"])
                / float(static_serve["achieved_qps"]),
                2,
            )
            if static_serve.get("achieved_qps")
            and planned_serve.get("achieved_qps")
            else None
        ),
    }

    # ---- drift retune: a live PlanTuner against the zoo's drift
    # scenario — every retune is bake-guarded, so the sub-check is
    # "p99 improved OR the retune reverted", with zero lost futures
    from keystone_tpu.planner import PlanTuner
    from keystone_tpu.utils import guard
    from tools import workloads as zoo

    planner.install_plan(plan, source="serve")
    svc = serve(
        fitted,
        max_batch=max_batch,
        queue_bound=queue_bound,
        deadline_ms=deadline_ms,
        example=np.zeros(item_shape, np.float32),
        name="plan_drift",
    )
    tuner = PlanTuner(
        svc, plan=plan, interval_s=0.2, bake_s=0.6, cooldown_s=0.5
    )
    scenario = zoo.make_scenario(
        "drift", seed=seed, duration_s=drift_duration, qps=drift_qps,
        dim=dim,
    )
    lock = threading.Lock()
    lat: list = []
    counts = {"completed": 0, "lost": 0, "shed": 0, "rejected": 0}
    deadline_s = float(deadline_ms) / 1000.0

    def record(fut, t0):
        t1 = time.monotonic()
        exc = fut.exception()
        with lock:
            if exc is None:
                counts["completed"] += 1
                lat.append((t0, t1 - t0))
            elif isinstance(exc, guard.DeadlineExceeded):
                counts["shed"] += 1
            else:
                counts["lost"] += 1

    def _submit(event, rows):
        t0 = time.monotonic()
        try:
            fs = svc.submit_many(rows, deadline=deadline_s)
        except Exception:
            with lock:
                counts["rejected"] += int(rows.shape[0])
            return 0
        for f in fs:
            f.add_done_callback(lambda fut, t0=t0: record(fut, t0))
        return len(fs)

    tuner.start()
    t_start = time.monotonic()
    try:
        zoo.play(scenario, _submit, time_scale=1.0)
        time.sleep(max(0.5, 2 * tuner.bake_s))  # let a pending bake land
    finally:
        tuner.stop()
        svc.close()

    def _p99(samples):
        if not samples:
            return None
        vals = sorted(s for _, s in samples)
        return round(
            vals[min(len(vals) - 1, int(0.99 * len(vals)))] * 1000.0, 3
        )

    mid = t_start + (time.monotonic() - t_start) / 2.0
    first = [s for s in lat if s[0] < mid]
    second = [s for s in lat if s[0] >= mid]
    tstat = tuner.status()
    drift = {
        "outcomes": counts,
        "lost_futures": counts["lost"],
        "p99_ms_first_half": _p99(first),
        "p99_ms_second_half": _p99(second),
        "retunes": tstat.get("retunes"),
        "last_action": tstat.get("last_action"),
    }

    planner.clear_plan()
    return {
        "plan": {
            "fingerprint": plan.fingerprint(),
            "backend": plan.backend,
            "stages": {s.gate: s.winner for s in plan.stages},
            "knobs": plan.knobs,
        },
        "forward": forward,
        "serve": serve_ab,
        "drift_retune": drift,
        # the headline acceptance number: the planned configuration
        # matches or beats static on both legs (forward is the
        # low-noise leg; serve rides open-loop achieved QPS)
        "speedup": forward["speedup"],
        "serve_speedup": serve_ab["speedup"],
    }


def run_scenario(
    name: str,
    seed: int = 0,
    duration: float = 3.0,
    qps: float = 200.0,
    dim: int = 64,
    max_batch: int = 32,
    max_wait_ms: float = 2.0,
    queue_bound: int = 128,
    deadline_ms: float | None = 250.0,
    replicas: int = 1,
    time_scale: float = 1.0,
) -> dict:
    """Replay one seeded zoo scenario (``tools/workloads.py``) against
    a live service and report outcomes + latency percentiles.  The
    report carries the scenario's ``trace_digest`` so a regression
    found here replays bit-exactly (same name + seed = same traffic)."""
    import numpy as np

    from keystone_tpu.serve import Overloaded
    from keystone_tpu.utils import guard
    from tools import workloads as zoo

    scenario = zoo.make_scenario(
        name, seed=seed, duration_s=duration, qps=qps, dim=dim
    )
    svc, _item_shape = build_service(
        dim=dim,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        deadline_ms=deadline_ms,
        replicas=replicas,
    )
    deadline_s = None if not deadline_ms else float(deadline_ms) / 1000.0
    lock = threading.Lock()
    latencies: list = []
    outcomes = {"completed": 0, "shed": 0, "rejected": 0, "errors": 0}
    futs: list = []

    def record(fut, t_submit):
        t_done = time.monotonic()
        exc = fut.exception()
        with lock:
            if exc is None:
                outcomes["completed"] += 1
                latencies.append(t_done - t_submit)
            elif isinstance(exc, guard.DeadlineExceeded):
                outcomes["shed"] += 1
            else:
                outcomes["errors"] += 1

    def _submit(event, rows):
        t_submit = time.monotonic()
        try:
            fs = svc.submit_many(rows, deadline=deadline_s)
        except Overloaded:
            with lock:
                outcomes["rejected"] += rows.shape[0]
            return 0
        for f in fs:
            f.add_done_callback(lambda fut, t0=t_submit: record(fut, t0))
        with lock:
            futs.extend(fs)
        return len(fs)

    t0 = time.monotonic()
    try:
        zoo.play(scenario, _submit, time_scale=time_scale)
        for f in list(futs):
            try:
                f.result(timeout=30.0)
            except Exception:
                pass
    finally:
        svc.close()
    wall = time.monotonic() - t0
    lat = sorted(latencies)

    def _pct(p):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1000.0, 3)

    return {
        "scenario": scenario.summary(),
        "wall_seconds": round(wall, 3),
        "outcomes": outcomes,
        "submitted_rows": scenario.total_rows(),
        "qps_achieved": (
            round(outcomes["completed"] / wall, 1) if wall > 0 else None
        ),
        "p50_ms": _pct(0.50),
        "p99_ms": _pct(0.99),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # single-arm entries the A/B driver spawns (fresh process per
    # sample); also usable by hand for debugging one arm
    if argv and argv[0] in ("--cold-start-arm", "--restart-arm"):
        sub = argparse.ArgumentParser(prog=f"serve_bench {argv[0]}")
        sub.add_argument("arm", choices=("artifact", "compile"))
        sub.add_argument("--registry", required=True)
        sub.add_argument("--dim", type=int, default=64)
        sub.add_argument("--max-batch", type=int, default=32)
        a = sub.parse_args(argv[1:])
        fn = run_cold_start if argv[0] == "--cold-start-arm" else run_restart
        print(
            json.dumps(
                fn(a.arm, a.registry, dim=a.dim, max_batch=a.max_batch)
            )
        )
        return 0
    ap = argparse.ArgumentParser(
        description="open-loop load generator for keystone_tpu.serve"
    )
    ap.add_argument("--qps", type=float, default=500.0, help="offered load")
    ap.add_argument("--duration", type=float, default=3.0, help="seconds")
    ap.add_argument(
        "--burst", type=int, default=1, help="arrivals per group (same mean rate)"
    )
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-bound", type=int, default=128)
    ap.add_argument("--deadline-ms", type=float, default=250.0)
    ap.add_argument(
        "--batch-delay-ms",
        type=float,
        default=0.0,
        help="stall every flush this long via the serve.batch fault site "
        "(emulates a heavier model; makes overload reproducible anywhere)",
    )
    ap.add_argument("--dim", type=int, default=64, help="request vector length")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument(
        "--model", default=None, help="serve this saved FittedPipeline instead"
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="serving fleet size (one FrozenApplier clone per local "
        "device; pair with XLA_FLAGS=--xla_force_host_platform_device_count=N "
        "on CPU)",
    )
    ap.add_argument(
        "--swap-mid-run",
        action="store_true",
        help="blue/green hot-swap a freshly-built model in at the offer "
        "window's midpoint; the report gains the swap pause/prime times",
    )
    ap.add_argument(
        "--no-recorder",
        action="store_true",
        help="disable the flight recorder (request tracing); the "
        "on-vs-off pair pins the recorder overhead budget (p99/QPS "
        "within 5%%)",
    )
    ap.add_argument(
        "--straggler-ms",
        type=float,
        default=0.0,
        help="stall ONE replica's worker loop (--straggler-replica) "
        "this long per flush via a context-matched serve.worker plan "
        "(pre-claim, so the stalled batch stays hedgeable) — the "
        "deterministic straggler for hedging A/Bs",
    )
    ap.add_argument(
        "--straggler-replica",
        type=int,
        default=0,
        help="which replica index the straggler plan targets",
    )
    ap.add_argument(
        "--hedge-ms",
        type=float,
        default=None,
        help="enable hedged dispatch with this floor delay (needs "
        "--replicas >= 2); pair with --straggler-ms to see the p99 win",
    )
    ap.add_argument(
        "--cold-start",
        action="store_true",
        help="run the AOT-artifact A/Bs instead of the load generator: "
        "cold-start-to-first-prediction and supervisor "
        "restart-to-rejoin, each artifact-vs-compile in fresh "
        "subprocesses with fresh compile caches",
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=None,
        metavar="N",
        help="multi-tenant mode: co-serve N pipelines sharing a "
        "featurization prefix (serve/tenants.py) and run the "
        "shared-vs-unshared A/B — per-tenant QPS/p99, the fairness "
        "ratio, the pool hit/eviction counts, the aggregate-QPS "
        "speedup, and a bit-identity pin",
    )
    ap.add_argument(
        "--tenant-branches",
        type=int,
        default=6,
        help="gather width of the shared featurization prefix "
        "(heavier prefix = bigger sharing win)",
    )
    ap.add_argument(
        "--ab-rounds",
        type=int,
        default=2,
        help="samples per arm for --cold-start (order-alternated)",
    )
    ap.add_argument(
        "--workers",
        type=int,
        default=0,
        help="PROCESS fleet: serve with N worker processes instead of "
        "worker threads (0 = threaded).  With --procs-ab, the fleet "
        "size of BOTH arms of the thread-vs-process A/B",
    )
    ap.add_argument(
        "--procs-ab",
        action="store_true",
        help="run the thread-vs-process A/B on the compute-bound "
        "(GIL-held featurizer) workload instead of the load generator: "
        "achieved-QPS per arm, speedup vs the core-count-aware bound, "
        "and a bit-identity pin",
    )
    ap.add_argument(
        "--autoscale-scenario",
        action="store_true",
        help="run the autoscale acceptance scenario: a 1-worker "
        "process fleet scales 1->N under open-loop load and back down "
        "when idle, with zero dropped or hung requests",
    )
    ap.add_argument(
        "--burn-rounds",
        type=int,
        default=2000,
        help="CRC passes per row for the GIL-bound workload "
        "(--procs-ab / --autoscale-scenario)",
    )
    ap.add_argument(
        "--ingress-ab",
        action="store_true",
        help="run the zero-copy ingress A/B instead of the load "
        "generator: per-datum HTTP/JSON keep-alive clients vs binary "
        "batch frames against ONE AsyncIngress port (same service, "
        "same fleet) — per-datum QPS + p99 both arms, the >= 3x "
        "acceptance claim, and a bit-identity pin",
    )
    ap.add_argument(
        "--ingress-shards",
        type=int,
        default=2,
        help="AsyncIngress shard count for --ingress-ab (SO_REUSEPORT "
        "listener loops)",
    )
    ap.add_argument(
        "--http-clients",
        type=int,
        default=8,
        help="concurrent keep-alive HTTP clients in the --ingress-ab "
        "slow-path arm",
    )
    ap.add_argument(
        "--bin-clients",
        type=int,
        default=4,
        help="concurrent binary batch clients in the --ingress-ab "
        "fast-path arm",
    )
    ap.add_argument(
        "--scenario",
        default=None,
        metavar="NAME",
        help="replay a seeded adversarial zoo scenario "
        "(tools/workloads.py: bursty, diurnal, heavy_tailed, "
        "poison_flood, tenant_skewed, drift) instead of the open-loop "
        "generator; the report carries the replay digest",
    )
    ap.add_argument(
        "--scenario-seed",
        type=int,
        default=0,
        help="zoo scenario seed (same name + seed = same traffic)",
    )
    args = ap.parse_args(argv)

    if args.scenario:
        report = run_scenario(
            args.scenario,
            seed=args.scenario_seed,
            duration=args.duration,
            qps=args.qps,
            dim=args.dim,
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_bound=args.queue_bound,
            deadline_ms=args.deadline_ms,
            replicas=args.replicas,
        )
        print(json.dumps(report, indent=2))
        return 0

    if args.ingress_ab:
        report = run_ingress_ab(
            duration=args.duration,
            rounds=args.ab_rounds,
            dim=args.dim,
            max_batch=args.max_batch,
            shards=args.ingress_shards,
            http_clients=args.http_clients,
            bin_clients=args.bin_clients,
        )
        print(json.dumps(report, indent=2))
        return 0 if report.get("ok") else 1

    if args.procs_ab:
        report = run_procs_ab(
            qps=args.qps,
            duration=args.duration,
            rounds=args.ab_rounds,
            workers=args.workers or 2,
            dim=args.dim,
            burn_rounds=args.burn_rounds,
        )
        print(json.dumps(report, indent=2))
        return 0 if report.get("ok") else 1

    if args.autoscale_scenario:
        report = run_autoscale_scenario(
            qps=args.qps,
            duration=args.duration,
            burn_rounds=args.burn_rounds,
        )
        print(json.dumps(report, indent=2))
        return 0 if report.get("ok") else 1

    if args.cold_start:
        report = {
            "cold_start": run_cold_start_ab(
                dim=args.dim, max_batch=args.max_batch, rounds=args.ab_rounds
            ),
            "restart": run_restart_ab(
                dim=args.dim, max_batch=args.max_batch, rounds=args.ab_rounds
            ),
        }
        print(json.dumps(report, indent=2))
        return 0

    if args.tenants:
        report = run_tenants_ab(
            qps=args.qps,
            duration=args.duration,
            rounds=args.ab_rounds,
            tenants=args.tenants,
            branches=args.tenant_branches,
            max_batch=args.max_batch,
            deadline_ms=args.deadline_ms,
            dim=args.dim,
        )
        print(json.dumps(report, indent=2))
        return 0

    fleet_kw = (
        dict(workers=args.workers)
        if args.workers
        else dict(replicas=args.replicas)
    )
    svc, item_shape = build_service(
        dim=args.dim,
        classes=args.classes,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_bound=args.queue_bound,
        deadline_ms=args.deadline_ms,
        model=args.model,
        recorder=not args.no_recorder,
        hedge_ms=args.hedge_ms,
        **fleet_kw,
    )
    swap_pipeline = None
    if args.swap_mid_run:
        if args.model:
            from keystone_tpu.workflow import FittedPipeline

            swap_pipeline = FittedPipeline.load(args.model)
        else:
            swap_pipeline = build_pipeline(
                dim=args.dim, classes=args.classes, seed=1
            )
    try:
        report = run_bench(
            svc,
            item_shape,
            qps=args.qps,
            duration=args.duration,
            burst=args.burst,
            deadline_ms=args.deadline_ms,
            batch_delay_ms=args.batch_delay_ms,
            swap_pipeline=swap_pipeline,
            straggler_ms=args.straggler_ms,
            straggler_replica=args.straggler_replica,
        )
    finally:
        svc.close()
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
