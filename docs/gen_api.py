"""Regenerate docs/api.md from the package's public exports.

Usage (from the repo root):

    JAX_PLATFORMS=cpu python docs/gen_api.py > docs/api.md
"""

import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

MODULES = [
    ("keystone_tpu.workflow", "Workflow core"),
    ("keystone_tpu.parallel", "Distribution"),
    ("keystone_tpu.models", "Solvers"),
    ("keystone_tpu.ops", "Feature ops"),
    ("keystone_tpu.loaders", "Loaders"),
    ("keystone_tpu.evaluation", "Evaluation"),
    ("keystone_tpu.utils", "Utils"),
    ("keystone_tpu.obs", "Observability"),
    ("keystone_tpu.serve", "Serving"),
    ("keystone_tpu.planner", "Physical planning"),
    ("keystone_tpu.analysis", "Static analysis"),
]


def main() -> None:
    print("# API reference\n")
    print(
        "One line per public symbol of each package namespace (regenerate "
        "with `python docs/gen_api.py > docs/api.md`).  Usage: "
        "docs/guide.md; design rationale: docs/architecture.md; reference "
        "mapping: PARITY.md.\n"
    )
    for modname, title in MODULES:
        m = importlib.import_module(modname)
        names = getattr(m, "__all__", None) or sorted(
            n for n in vars(m) if not n.startswith("_")
        )
        print(f"## {title} — `{modname}`\n")
        for n in names:
            obj = getattr(m, n, None)
            if obj is None or inspect.ismodule(obj):
                continue
            raw = (
                obj.__dict__.get("__doc__")
                if isinstance(obj, type)
                else obj.__doc__
            )
            first = ""
            if raw:
                line = inspect.cleandoc(raw).split("\n\n")[0].replace("\n", " ")
                first = line if len(line) <= 160 else line[:157] + "…"
            if inspect.isclass(obj):
                kind = "class"
            elif callable(obj):
                try:
                    kind = f"def{inspect.signature(obj)}"
                    if len(kind) > 80:
                        kind = "def(…)"
                except (TypeError, ValueError):
                    kind = "def"
            else:
                continue
            sep = " — " if first else ""
            print(f"- **`{n}`** `{kind}`{sep}{first}")
        print()


if __name__ == "__main__":
    main()
