#!/usr/bin/env bash
# Pipeline launcher — the reference's bin/run-pipeline.sh (spark-submit
# wrapper with KEYSTONE_MEM) re-imagined for the TPU runtime.
#
#   bin/run-pipeline.sh <PipelineName> [pipeline flags...]
#   bin/run-pipeline.sh --list
#
# Environment knobs (all optional):
#   KEYSTONE_PLATFORM   jax platform to force (e.g. "cpu" for the virtual
#                       device path; default: whatever the env provides)
#   KEYSTONE_NUM_CPU_DEVICES
#                       with KEYSTONE_PLATFORM=cpu, number of virtual host
#                       devices to expose (the LocalSparkContext analogue)
#   KEYSTONE_MEM        fraction of HBM jax may preallocate, e.g. "0.8".
#                       NOTE: plays the role of the reference's
#                       executor-memory knob but takes a fraction in
#                       (0,1], NOT a JVM size like "4g"
#   KEYSTONE_COMPILE_CACHE
#                       persistent XLA compile-cache dir (default
#                       ~/.cache/keystone_tpu/xla; "off" disables) —
#                       repeat runs of a pipeline skip compilation
#   KEYSTONE_STATE_DIR  saved-pipeline-state dir: materialized prefixes
#                       persisted by save_pipeline_state are reloaded
#                       instead of recomputed (SavedStateLoadRule)
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"

if [[ -n "${KEYSTONE_PLATFORM:-}" ]]; then
  export JAX_PLATFORMS="${KEYSTONE_PLATFORM}"
fi
if [[ -n "${KEYSTONE_NUM_CPU_DEVICES:-}" ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${KEYSTONE_NUM_CPU_DEVICES}"
fi
if [[ -n "${KEYSTONE_MEM:-}" ]]; then
  # a fraction in (0,1]: either has a nonzero digit after the point, or is 1
  if ! [[ "${KEYSTONE_MEM}" =~ ^0?\.[0-9]*[1-9][0-9]*$|^1(\.0+)?$ ]]; then
    echo "KEYSTONE_MEM must be a fraction in (0,1], e.g. 0.8 (got '${KEYSTONE_MEM}')" >&2
    exit 2
  fi
  export XLA_PYTHON_CLIENT_MEM_FRACTION="${KEYSTONE_MEM}"
fi

exec python -m keystone_tpu.cli "$@"
