"""Headline benchmark — ImageNet FV pipeline throughput (images/sec/chip).

Measures the north-star path (BASELINE.md): dense SIFT → PCA → GMM Fisher
vector → power/L2 normalization → block-linear scoring, end to end on
device, steady-state, on one TPU chip.  ``vs_baseline`` is the speedup
against the same JAX program on one host CPU (the closest stand-in for
the reference's BLAS-on-CPU executors; the reference repo publishes no
numbers — BASELINE.json "published": {}).

Methodology: throughput is the *marginal* per-batch time of a pipelined
dispatch stream.  Total time of an n-iteration run is
t(n) = fixed_sync + n·per_iter; per_iter is fitted as the Theil–Sen
slope (median of pairwise slopes) over runs of several lengths
(RUN_LENGTHS × REPS).  This measures sustained streaming throughput
(batches continuously in flight, as in production inference) and cancels
the fixed host↔device round-trip of the final synchronization, which in
this environment is a ~60 ms network tunnel hop that would otherwise
dominate and massively understate the chip; the pairwise-median fit is
robust to individual jittered runs.  Both the TPU leg and the CPU
baseline leg use the same estimator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py            # TPU (or default backend) + cached CPU baseline
       python bench.py --cpu     # run the CPU-baseline leg only (prints ips)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 512  # device-optimal: VMEM-friendly working set (see BASELINE.md)
IMAGE_HW = 64
GMM_K = 64
PCA_DIMS = 64
NUM_CLASSES = 1000
WARMUP = 3
# run lengths for the slope fit: spread wide so the fitted line rests on
# ~150 ms of device work end-to-end, with repeats so single jittered
# points (the host↔device sync rides a network tunnel here) are outvoted
RUN_LENGTHS = (10, 35, 60, 110, 160, 210)
REPS = 2
_BASELINE_CACHE = os.path.join(os.path.dirname(__file__), ".bench_cpu_baseline.json")
# bump whenever the measurement methodology or CPU-leg parameters change,
# so stale cached baselines from older estimators are discarded
_BASELINE_VERSION = 3


def build_forward():
    import jax.numpy as jnp

    from keystone_tpu.models.block_ls import BlockLinearMapper
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops import (
        GrayScaler,
        NormalizeRows,
        SIFTExtractor,
        SignedHellingerMapper,
    )
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(0)
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    pca = PCATransformer(
        jnp.asarray(np.linalg.qr(rng.normal(size=(128, PCA_DIMS)))[0], jnp.float32),
        mean=jnp.zeros((128,), jnp.float32),
    )
    gmm = GaussianMixtureModel(
        jnp.full((GMM_K,), 1.0 / GMM_K, jnp.float32),
        jnp.asarray(rng.normal(size=(GMM_K, PCA_DIMS)), jnp.float32),
        jnp.ones((GMM_K, PCA_DIMS), jnp.float32),
    )
    fv_dim = 2 * GMM_K * PCA_DIMS
    block = 4096
    nb = -(-fv_dim // block)
    blm = BlockLinearMapper(
        jnp.asarray(
            0.01 * rng.normal(size=(nb, block, NUM_CLASSES)), jnp.float32
        ),
        block,
    )
    gray, hell, norm = GrayScaler(), SignedHellingerMapper(), NormalizeRows()
    fv = FisherVector(gmm)

    def forward(images):
        g = gray.apply_batch(images)
        desc, mask = sift.apply_batch(g)
        desc, mask = pca.apply_batch(desc, mask=mask)
        feats = fv.apply_batch(desc, mask=mask)
        feats = norm.apply_batch(hell.apply_batch(feats))
        return blm.apply_batch(feats)

    return forward


def measure_ips(
    batch: int,
    run_lengths=RUN_LENGTHS,
    reps: int = REPS,
    warmup: int = WARMUP,
) -> float:
    import jax

    forward = jax.jit(build_forward())
    images = np.random.default_rng(1).uniform(
        0, 1, (batch, IMAGE_HW, IMAGE_HW, 3)
    ).astype(np.float32)
    import jax.numpy as jnp

    images = jnp.asarray(images)
    for _ in range(warmup):
        forward(images).block_until_ready()

    def run(iters: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = forward(images)
        out.block_until_ready()
        return time.perf_counter() - t0

    # t(n) = fixed_sync + n·per_iter.  Fit per_iter by Theil–Sen (median of
    # pairwise slopes): a single two-point slope can collapse to ~0 when
    # jitter inflates the short run, which once reported a 50× bogus
    # throughput; the pairwise median is immune to any minority of bad
    # points.  Interleave lengths across reps so drift hits all lengths.
    points = []
    for _ in range(reps):
        for n in run_lengths:
            points.append((n, run(n)))
    slopes = [
        (tj - ti) / (nj - ni)
        for i, (ni, ti) in enumerate(points)
        for nj, tj in points[i + 1:]
        if nj != ni
    ]
    per_iter = float(np.median(slopes)) if slopes else float("nan")
    if not per_iter > 0:  # catches non-positive AND NaN (empty/degenerate)
        # pathological timing environment; fall back to the sync-dominated
        # mean and say so — this measures a different quantity (includes
        # the final host<->device round-trip)
        n_max = max(run_lengths)
        per_iter = float(
            np.median([t / n for n, t in points if n == n_max])
        )
        sys.stderr.write(
            "bench: slope estimator degenerate; reporting sync-dominated mean\n"
        )
    return batch / per_iter


def cpu_baseline_ips() -> float:
    if os.path.exists(_BASELINE_CACHE):
        try:
            with open(_BASELINE_CACHE) as f:
                cached = json.load(f)
            if cached.get("v") == _BASELINE_VERSION:
                return float(cached["ips"])
        except Exception:
            pass
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu"],
        capture_output=True,
        text=True,
        timeout=3600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        ips = float(json.loads(line)["cpu_ips"])
    except Exception:
        sys.stderr.write(f"cpu baseline failed: {proc.stderr[-500:]}\n")
        return 0.0
    with open(_BASELINE_CACHE, "w") as f:
        json.dump({"ips": ips, "v": _BASELINE_VERSION}, f)
    return ips


def main():
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        # same per-image program + same marginal-time estimator, scaled down
        # (the CPU leg is ~1000× slower; a handful of iterations suffices)
        ips = measure_ips(
            batch=64, run_lengths=(1, 2, 4, 6), reps=2, warmup=1
        )
        print(json.dumps({"cpu_ips": ips}))
        return

    import jax

    ips = measure_ips(BATCH)
    cpu_ips = cpu_baseline_ips()
    vs = ips / cpu_ips if cpu_ips > 0 else None
    print(
        json.dumps(
            {
                "metric": "imagenet_fv_pipeline_throughput",
                "value": round(ips, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(vs, 2) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
