"""Headline benchmark — ImageNet-scale FV pipeline throughput + MFU.

Measures the north-star path (BASELINE.md): dense SIFT → PCA(64) → GMM
Fisher vector (K=256, T=784 descriptors/image — the regime the reference's
ImageNetSiftLcsFV pipeline ran, SURVEY.md §2.3) → power/L2 normalization →
1000-class block-linear scoring, end to end on device, steady state, on
one TPU chip.  This config engages the Pallas FV kernel (γ = T·K = 200k
elements ≫ the 32k crossover).

Prints ONE JSON line with:
  value / unit     — sustained images/sec/chip (marginal per-batch time)
  tflops           — analytic FLOPs/image × ips (FLOP accounting below)
  mfu_f32          — tflops / 49 Tf/s (TPU v5 lite f32 peak; XLA runs
                     default-precision f32 matmuls as bf16-grade MXU
                     passes, so >1.0 is possible for matmul-dense configs)
  vs_baseline      — speedup over the SAME JAX program on one host CPU
                     (stand-in: the reference publishes no numbers and its
                     mount is empty — see BASELINE.md "Baseline caveat")

Methodology: throughput is the *marginal* per-batch time of a pipelined
dispatch stream: t(n) = fixed_sync + n·per_iter, fitted by Theil–Sen
(median of pairwise slopes) over interleaved runs of several lengths.
The run-end synchronization is a REAL device→host read (np.asarray of a
small output slice).  ``block_until_ready`` returns without draining the
execution stream on the axon backend — round-1's 746k ips headline and
its apparent 2.6× large-batch decay were partly artifacts of that; see
BASELINE.md "Round-2 re-measurement".

Since r4 the one JSON line also carries the two first-class companion
metrics the reference's published story is about (VERDICT r3 item 1):
``fit``        — end-to-end north-star FIT (two-branch featurize →
                 weighted BCD; synthetic ImageNet config n=2048@128px,
                 K=64, 64 classes): fit_seconds / fit_images_per_sec
                 with bands, plus the solver-phase TFLOP/s measured
                 standalone at the post-featurize shape.
``multiscale`` — forward throughput at the densest config the
                 reference ran (vl_phow bins (4,6,8,10) + per-scale
                 smoothing, T=2520 descriptors/image).

Since r5 it also carries the at-scale artifacts (VERDICT r4 item 5):
``solver_at_scale`` — weighted-BCD at n=65536×d=16384×k=64 (solver-grade
true-f32 TF/s with band); ``fit_at_scale`` — the full two-branch fit at
n=8192 (the shape-stable chunked-apply regime).

Since r6 the line also carries ``precision_sweep`` — the headline
forward re-measured under each matmul policy (f32 / auto / bf16_apply,
one pinned-env subprocess leg per mode; BENCH_PRECISION_LEGS legs each,
0 disables) so the bf16 apply-path win (and any regression) lands in
BENCH_*.json as first-class ips / mfu_bf16_eff numbers next to the
headline.

Usage: python bench.py           # TPU (or default backend) + cached CPU leg
       python bench.py --cpu     # CPU-baseline leg only
       python bench.py --sweep   # batch sweep (prints one line per batch)
       python bench.py --leg-fit # one fit+solver leg (one JSON line)
       python bench.py --leg-ms  # one multi-scale forward leg
       python bench.py --leg-solver-scale   # one at-scale solver leg
       python bench.py --leg-fit-scale      # one n=8192 fit leg
       python bench.py --leg-kernel         # kernel tier in-core-vs-OC A/B
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 128  # measured optimum on v5 lite (BASELINE.md batch sweep)
IMAGE_HW = 128
SIFT_STEP = 4  # -> 28x28 = 784 descriptors/image
GMM_K = 256
PCA_DIMS = 64
NUM_CLASSES = 1000
WARMUP = 3
RUN_LENGTHS = (10, 25, 40, 60, 80)
REPS = 3

# --- multi-scale leg: the densest config the reference's ImageNet
# pipeline ran (vl_phow bins + per-scale smoothing; SURVEY §2.3,
# BASELINE.md "Multi-scale reference config") — T=2520 descriptors/image
MS_BATCH = 64
MS_BIN_SIZES = (4, 6, 8, 10)
MS_SMOOTHING = 6.0

# --- fit leg: the end-to-end north-star FIT (two-branch featurize →
# weighted BCD) on the synthetic ImageNet config BASELINE.md has tracked
# since r1 (n=2048 at 128px, K=64, 64 classes)
FIT_N = 2048
FIT_CLASSES = 64
FIT_GMM_K = 64
FIT_EPOCHS = 2
FIT_SOLVER_BLOCK = 4096

# --- at-scale legs (VERDICT r4 item 5: the numbers that prove the
# framework trains at reference scale must be per-round artifacts, not
# BASELINE.md prose).  Solver: the n=65536×d=16384 weighted-BCD shape
# BASELINE.md "solver at scale" measured at 19-23 TF/s true-f32; data is
# generated ON DEVICE (a host gen + tunnel transfer of 4.3 GB would be
# ~2 min).  Fit: the full two-branch fit at n=8192 (4× the tracked
# config — exercises the chunked-apply path whose programs stop scaling
# with n).
ATSCALE_N, ATSCALE_D, ATSCALE_K = 65536, 16384, 64
ATSCALE_EPOCHS = 1
FIT_SCALE_N = 8192
SCALE_LEGS = int(os.environ.get("BENCH_SCALE_LEGS", "2"))

# --- kernel leg (ISSUE 13): the kernel solver tier — blockwise
# Gauss–Seidel KRR, in-core vs the out-of-core streamed gram-block
# sweep on the SAME problem (the solver family arXiv:1602.05310 adds
# over upstream, and a genuinely different compute shape from the
# feature-block BCD: nb² gram gemms per epoch instead of nb Gramians).
# The A/B tracks: kernel-sweep TFLOP/s both ways, the OC feed's
# device_busy_fraction + transfer_seconds (is the stream keeping the
# device busy?), prediction r² between the two fits (must stay ≥
# 0.999), and how many times the on-disk row store exceeds the OC
# sweep's device-resident working set (2 staged row blocks + the
# (α, F, Y) carries) — the honest out-of-core claim.  The default
# geometry keeps that ratio > 4× while the whole leg stays
# minutes-scale on CPU; raise BENCH_KERNEL_N toward the million-row
# regime on real hardware.
KERNEL_LEGS = int(os.environ.get("BENCH_KERNEL_LEGS", "1"))
KERNEL_N = int(os.environ.get("BENCH_KERNEL_N", "8192"))
KERNEL_D = int(os.environ.get("BENCH_KERNEL_D", "256"))
KERNEL_K = int(os.environ.get("BENCH_KERNEL_K", "8"))
KERNEL_BLOCK = int(os.environ.get("BENCH_KERNEL_BLOCK", "512"))
KERNEL_EPOCHS = int(os.environ.get("BENCH_KERNEL_EPOCHS", "2"))
KERNEL_GAMMA = float(os.environ.get("BENCH_KERNEL_GAMMA", "0.002"))

# --- precision-mode sweep (ISSUE 2): the headline forward under each
# matmul policy, one subprocess leg per (mode, leg) with KEYSTONE_MATMUL
# pinned in the child env — so policy resolution, trace caches, and the
# persistent compile cache are per-mode clean.  "f32" = full-precision
# featurize policy, "auto" = the default (bf16 featurize on TPU),
# "bf16_apply" = the opt-in apply path (utils/precision.py) whose
# mfu_bf16_eff delta vs "auto" is the r6 headline claim.  On CPU hosts
# all three resolve inert and the sweep just measures noise — it still
# runs so the artifact shape is identical everywhere.
PRECISION_MODES = ("f32", "auto", "bf16_apply")
PRECISION_LEGS = int(os.environ.get("BENCH_PRECISION_LEGS", "1"))

# --- serve leg (ISSUE 5): the online-serving subsystem under overload
# (tools/serve_bench.py open-loop generator, offered QPS > capacity via
# a serve.batch delay plan emulating a heavier model).  The numbers the
# round artifact tracks: achieved QPS, p50/p99 latency, mean batch
# occupancy (>1 = micro-batching is amortizing program launches), shed
# rate (excess load counted, not queued unboundedly), deadline misses
# (0 = every completed request beat its deadline).
SERVE_LEGS = int(os.environ.get("BENCH_SERVE_LEGS", "1"))
SERVE_QPS = 1500.0
SERVE_DURATION_S = 2.0
SERVE_MAX_BATCH = 16
SERVE_QUEUE_BOUND = 64
SERVE_DEADLINE_MS = 250.0
SERVE_BATCH_DELAY_MS = 10.0

# --- recorder-overhead pair (ISSUE 9): the flight-recorder tax, pinned.
# An in-process A/B — the identical steady-state workload against a
# recorder-on and a recorder-off service, order-alternating rounds with
# a discarded warmup — because the overload leg above sits ON the
# collapse cliff, where achieved QPS swings tens of percent run-to-run
# and no 5%-budget claim is measurable.  The artifact records per-mode
# medians and the on/off ratios (budget: within 5% of 1.0).
SERVE_OVERHEAD_QPS = float(os.environ.get("BENCH_SERVE_OVERHEAD_QPS", "300"))
SERVE_OVERHEAD_ROUNDS = int(os.environ.get("BENCH_SERVE_OVERHEAD_ROUNDS", "4"))

# --- fleet leg (ISSUE 8): the replica fleet + live blue/green hot-swap
# under the same open-loop generator.  Offered load sits ABOVE one
# replica's capacity (max_batch rows per 40 ms-delayed flush ≈ 0.7k QPS)
# and below the fleet's, so achieved QPS is the scaling claim: the
# N-replica leg must sustain more than the 1-replica leg run with the
# IDENTICAL config (recorded side by side).  The emulated model is
# deliberately HEAVY (40 ms per flush): flush time must dominate the
# per-request host work (submit path, future resolution — all GIL-bound
# Python) or a 2-core CI host measures the GIL, not the fleet.  A swap
# fires at the offer window's midpoint; the artifact tracks per-replica
# occupancy (router balance) and the swap pause p99 across legs (must
# stay far under one flush interval — commit is a pointer swap, priming
# is off-path).
FLEET_LEGS = int(os.environ.get("BENCH_FLEET_LEGS", "1"))
FLEET_REPLICAS = int(os.environ.get("BENCH_FLEET_REPLICAS", "4"))
FLEET_QPS = 2000.0
FLEET_DURATION_S = 3.0
FLEET_MAX_BATCH = 32
FLEET_QUEUE_BOUND = 256
FLEET_DEADLINE_MS = 1500.0
FLEET_BATCH_DELAY_MS = 40.0

# --- hedging leg (ISSUE 10): the same open-loop workload against a
# 2-replica fleet with ONE injected straggler (replica 0 stalls every
# flush), hedging on vs off, order-alternated in one process (the
# run_overhead_pair discipline — below the collapse knee, so the A/B is
# measurable).  The acceptance claim: hedging cuts p99 (queued flushes
# escape the straggler's queue) at ≤ 5% achieved-QPS cost — losers are
# claim-skips, not duplicate device work.
HEDGE_LEGS = int(os.environ.get("BENCH_HEDGE_LEGS", "1"))
HEDGE_QPS = float(os.environ.get("BENCH_HEDGE_QPS", "250"))
HEDGE_ROUNDS = int(os.environ.get("BENCH_HEDGE_ROUNDS", "4"))
HEDGE_STRAGGLER_MS = 60.0
HEDGE_FLOOR_MS = 10.0

# --- AOT artifact legs (ISSUE 11): cold-start-to-first-prediction and
# supervisor restart-to-rejoin, each as an artifact-vs-compile A/B over
# an identical published registry version — every sample in a fresh
# subprocess with a fresh (empty) persistent compile cache, so the
# delta IS the pre-lowered executable tier, not leftover process
# warmth.  Both arms' first predictions must match bit-for-bit.
ARTIFACT_LEGS = int(os.environ.get("BENCH_ARTIFACT_LEGS", "1"))
ARTIFACT_AB_ROUNDS = int(os.environ.get("BENCH_ARTIFACT_ROUNDS", "2"))

# --- multi-tenant leg (ISSUE 14): N co-served pipelines sharing a
# featurization prefix through the cross-pipeline stage pool, vs the
# IDENTICAL service with sharing disabled — in-process A/B,
# order-alternating rounds with a discarded warmup (the
# run_overhead_pair discipline: the claim is a ratio, so both arms
# share process warmth).  The artifact tracks the aggregate-QPS
# speedup (acceptance: ≥ 1.5× with a prefix-dominated workload), the
# per-tenant p99 fairness ratio under equal offered load (acceptance:
# ≤ 1.25), pool hit/eviction counts, and a shared-vs-unshared
# bit-identity pin (sharing is an execution strategy, not a numerics
# change).
TENANT_LEGS = int(os.environ.get("BENCH_TENANT_LEGS", "1"))
TENANT_COUNT = int(os.environ.get("BENCH_TENANT_COUNT", "3"))
TENANT_QPS = float(os.environ.get("BENCH_TENANT_QPS", "12000"))
TENANT_ROUNDS = int(os.environ.get("BENCH_TENANT_ROUNDS", "3"))
TENANT_BRANCHES = int(os.environ.get("BENCH_TENANT_BRANCHES", "12"))
TENANT_MAX_BATCH = int(os.environ.get("BENCH_TENANT_MAX_BATCH", "64"))

# --- process fleet leg (ISSUE 15): thread-vs-process A/B on a
# COMPUTE-BOUND workload — a deterministic pure-Python featurizer that
# holds the GIL (like real tokenize/ngram stages), offered above
# capacity so achieved QPS measures capacity.  Worker threads serialize
# on the GIL through that stage; worker processes compute in parallel,
# so on an N-core host the process fleet's speedup approaches
# min(workers, cores) while threads stay pinned near 1 core.  The leg
# reports the scheduler-affinity core count and gates the >= 1.8x
# acceptance only where >= 2 cores exist (a 1-core host cannot express
# the claim; there the gate is process overhead <= 30%).  Thread/
# process predictions must match bit-for-bit, and the autoscale
# sub-leg must scale 1 -> N under open-loop load and back down idle
# with zero dropped or hung requests.  NOTE: the PR-8 fleet leg above
# is STALL-dominated by construction (batch_delay_ms is an injected,
# GIL-RELEASING sleep) — its fleet_speedup measures router concurrency
# over emulated device stalls and was never a multi-core hardware
# claim; THIS leg is the multi-core compute claim.
PROC_LEGS = int(os.environ.get("BENCH_PROC_LEGS", "1"))
PROC_WORKERS = int(os.environ.get("BENCH_PROC_WORKERS", "2"))
PROC_QPS = float(os.environ.get("BENCH_PROC_QPS", "2500"))
PROC_ROUNDS = int(os.environ.get("BENCH_PROC_ROUNDS", "3"))
PROC_DURATION_S = float(os.environ.get("BENCH_PROC_DURATION", "2.5"))
PROC_BURN_ROUNDS = int(os.environ.get("BENCH_PROC_BURN", "2000"))
AUTOSCALE_QPS = float(os.environ.get("BENCH_AUTOSCALE_QPS", "2000"))
AUTOSCALE_DURATION_S = float(os.environ.get("BENCH_AUTOSCALE_DURATION", "4"))
INGRESS_LEGS = int(os.environ.get("BENCH_INGRESS_LEGS", "1"))
INGRESS_DURATION_S = float(os.environ.get("BENCH_INGRESS_DURATION", "1.5"))
INGRESS_ROUNDS = int(os.environ.get("BENCH_INGRESS_ROUNDS", "2"))
INGRESS_SHARDS = int(os.environ.get("BENCH_INGRESS_SHARDS", "2"))

# --- plan leg (ISSUE 20): the cost-based physical planner's A/B — the
# same fitted pipeline with the sampled PhysicalPlan installed (stage
# winners + derived serving knobs) vs the static defaults, on the raw
# forward leg and the open-loop serve leg, plus a live PlanTuner retune
# under the workload zoo's drift scenario.  Acceptance: speedup >= 1.0
# (off-TPU both arms run identical physics, so ~1.0 is the honest
# expectation) and the drift retune improves windowed p99 or reverts
# through the bake guard with zero lost futures.
PLAN_LEGS = int(os.environ.get("BENCH_PLAN_LEGS", "1"))
PLAN_QPS = float(os.environ.get("BENCH_PLAN_QPS", "300"))
PLAN_DURATION_S = float(os.environ.get("BENCH_PLAN_DURATION", "2.5"))
PLAN_DRIFT_DURATION_S = float(os.environ.get("BENCH_PLAN_DRIFT_DURATION", "3"))


def _f32_peak() -> float:
    """TPU v5 lite f32 peak, from the repo's single roofline source."""
    from keystone_tpu.workflow.profiling import _ROOFLINE_PEAKS

    return _ROOFLINE_PEAKS["tpu"][0]


_BF16_EFFECTIVE_PEAK = 1.97e14  # TPU v5 lite bf16-grade MXU peak (~197 Tf/s);
# XLA executes default-precision f32 matmuls as bf16-grade passes, so this
# is the honest utilization denominator for the matmul-dense stages
N_LEGS = int(os.environ.get("BENCH_LEGS", "3"))  # ≥3 resynced samples
_BASELINE_CACHE = os.path.join(os.path.dirname(__file__), ".bench_cpu_baseline.json")
# bump whenever the methodology, config, or the measured PROGRAM changes
# so stale caches die (v5: SIFT windowing default moved to the matmul
# path; v6: kills any cache written in the window where r4's first cut
# accidentally benchmarked with SIFT smoothing disabled; v7: the
# per-scale Gaussian blur moved to banded-matrix einsums — the CPU leg
# runs the same program)
_BASELINE_VERSION = 7


def build_forward(bin_sizes=(4,), smoothing_magnif: float = 6.0):
    # smoothing default matches SIFTExtractor's constructor (6.0): the
    # headline program has included the per-scale smoothing since r1,
    # and r4's first cut accidentally disabled it (making the headline
    # incomparable to r2/r3 and to the cached CPU baseline)
    import jax.numpy as jnp

    from keystone_tpu.models.block_ls import BlockLinearMapper
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops import (
        GrayScaler,
        NormalizeRows,
        SIFTExtractor,
        SignedHellingerMapper,
    )
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(0)
    sift = SIFTExtractor(
        step=SIFT_STEP, bin_sizes=bin_sizes, smoothing_magnif=smoothing_magnif
    )
    pca = PCATransformer(
        jnp.asarray(np.linalg.qr(rng.normal(size=(128, PCA_DIMS)))[0], jnp.float32),
        mean=jnp.zeros((128,), jnp.float32),
    )
    gmm = GaussianMixtureModel(
        jnp.full((GMM_K,), 1.0 / GMM_K, jnp.float32),
        jnp.asarray(rng.normal(size=(GMM_K, PCA_DIMS)), jnp.float32),
        jnp.ones((GMM_K, PCA_DIMS), jnp.float32),
    )
    fv_dim = 2 * GMM_K * PCA_DIMS
    block = 4096
    nb = -(-fv_dim // block)
    blm = BlockLinearMapper(
        jnp.asarray(
            0.01 * rng.normal(size=(nb, block, NUM_CLASSES)), jnp.float32
        ),
        block,
    )
    gray, hell, norm = GrayScaler(), SignedHellingerMapper(), NormalizeRows()
    fv = FisherVector(gmm)

    def forward(images):
        g = gray.apply_batch(images)
        desc, mask = sift.apply_batch(g)
        desc, mask = pca.apply_batch(desc, mask=mask)
        feats = fv.apply_batch(desc, mask=mask)
        feats = norm.apply_batch(hell.apply_batch(feats))
        return blm.apply_batch(feats)

    return forward


def flops_per_image(bin_sizes=(4,), smoothing: bool = True) -> float:
    """Analytic FLOPs/image of the forward path (2·MACs convention).

    XLA's compiled cost analysis can't price the Pallas FV custom call,
    so the count is assembled per stage; elementwise work is ignored
    (<5% of total).  T = number of dense-SIFT descriptors per image.
    """
    from keystone_tpu.ops.sift import _window_matrix, sift_output_count

    t = sift_output_count(IMAGE_HW, IMAGE_HW, SIFT_STEP, bin_sizes)
    d_sift = 128
    # SIFT windowing (matmul path, the r3 default), per scale: two dense
    # einsums — (P, H)×(H, W·8) then (Q, W)×(W, P·8), P = Q = 4·centers
    sift = 0
    for b in bin_sizes:
        p = _window_matrix(IMAGE_HW, SIFT_STEP, b)[0].shape[0]
        sift += 2 * p * IMAGE_HW * IMAGE_HW * 8 + 2 * p * IMAGE_HW * p * 8
    if smoothing:
        # per-scale Gaussian blur as banded (extent, extent) einsums
        # (the r4 matmul strategy): one (H,H)×(H,W) + one (W,W)-side
        # pass over the single grayscale channel per scale (~2% of the
        # single-scale total; ADVICE r4 — these run on the MXU and
        # belong in the executed-FLOPs accounting)
        sift += len(bin_sizes) * (
            2 * IMAGE_HW * IMAGE_HW * IMAGE_HW
            + 2 * IMAGE_HW * IMAGE_HW * IMAGE_HW
        )
    pca = 2 * t * d_sift * PCA_DIMS
    # FV kernel: 4 MXU contractions of T×D×K (x²·inv, x·μinv, γᵀx, γᵀx²)
    fv = 4 * 2 * t * PCA_DIMS * GMM_K
    blm = 2 * (2 * GMM_K * PCA_DIMS) * NUM_CLASSES
    return float(sift + pca + fv + blm)


def measure_ips(
    batch: int,
    run_lengths=RUN_LENGTHS,
    reps: int = REPS,
    warmup: int = WARMUP,
    bin_sizes=None,
    smoothing_magnif: float | None = None,
) -> float:
    import jax

    # None → build_forward's own defaults.  Duplicating those defaults
    # here is what broke the r4 headline (a 0.0 copy silently overrode
    # the restored 6.0): forward ONLY what the caller explicitly set.
    kw = {}
    if bin_sizes is not None:
        kw["bin_sizes"] = bin_sizes
    if smoothing_magnif is not None:
        kw["smoothing_magnif"] = smoothing_magnif
    forward = jax.jit(build_forward(**kw))
    images = np.random.default_rng(1).uniform(
        0, 1, (batch, IMAGE_HW, IMAGE_HW, 3)
    ).astype(np.float32)
    import jax.numpy as jnp

    images = jnp.asarray(images)

    def sync(out):
        # REAL device→host read: block_until_ready does not drain the
        # stream on the axon backend (small fixed-cost transfer, cancelled
        # by the slope fit)
        return np.asarray(out[:1, :8])

    for _ in range(warmup):
        sync(forward(images))

    def run(iters: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = forward(images)
        sync(out)
        return time.perf_counter() - t0

    # t(n) = fixed_sync + n·per_iter.  Theil–Sen slope (median of pairwise
    # slopes) over interleaved lengths×reps: robust to the jittered
    # host↔device tunnel and to ambient device-clock drift.
    points = []
    for _ in range(reps):
        for n in run_lengths:
            points.append((n, run(n)))
    slopes = [
        (tj - ti) / (nj - ni)
        for i, (ni, ti) in enumerate(points)
        for nj, tj in points[i + 1:]
        if nj != ni
    ]
    per_iter = float(np.median(slopes)) if slopes else float("nan")
    # physical plausibility cap: a tunnel hiccup mid-measurement can
    # leave the pairwise-slope median absurdly small (observed: a
    # 1.5M-ips multi-scale leg ≈ 25× the chip's possible rate).  Any
    # reading beyond 2× the bf16 MXU peak over the program's analytic
    # FLOPs is a broken measurement, not a fast chip.
    cap_per_iter = (
        batch * flops_per_image(bin_sizes or (4,)) / (2.0 * _BF16_EFFECTIVE_PEAK)
    )
    if not per_iter > 0 or per_iter < cap_per_iter:
        n_max = max(run_lengths)
        per_iter = float(
            np.median([t / n for n, t in points if n == n_max])
        )
        sys.stderr.write(
            "bench: slope estimator degenerate/implausible; "
            "reporting sync-dominated mean\n"
        )
    return batch / per_iter


def measure_fit(n: int = FIT_N) -> dict:
    """One end-to-end north-star FIT leg: synthetic ImageNet config
    through the REAL app build (two FV branches with in-graph
    PCA/GMM vocabulary fits, CSE-merged featurize, weighted BCD solve),
    honestly blocked at the end.  Data generation happens OUTSIDE the
    timer — it is loader cost, not fit cost.

    The leg runs under a run ledger (keystone_tpu.obs) and returns its
    obs summary (stage top-k, retry totals, solver convergence points,
    memory watermarks) under ``"obs"`` so every BENCH_rNN.json carries
    the operational context of its own fit.  Ledger overhead is a
    handful of JSONL writes per stage plus one tiny host callback per
    solver epoch — noise against a minutes-scale fit."""
    import tempfile
    import time as _time

    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.obs import ledger as obs_ledger
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        Config,
        ImageNetSiftLcsFV,
    )

    cfg = Config(
        num_classes=FIT_CLASSES,
        synthetic_n=n,
        image_size=IMAGE_HW,
        gmm_k=FIT_GMM_K,
        pca_dims=PCA_DIMS,
        num_epochs=FIT_EPOCHS,
        solver_block_size=FIT_SOLVER_BLOCK,
    )
    train = ImageNetLoader.synthetic(
        n, FIT_CLASSES, size=(IMAGE_HW, IMAGE_HW), seed=1
    )
    obs_dir = tempfile.mkdtemp(prefix="kst_bench_obs_")
    obs_ledger.start_run(obs_dir)
    try:
        t0 = _time.perf_counter()
        fitted = (
            ImageNetSiftLcsFV.build(cfg, train.data, train.labels)
            .fit()
            .block_until_ready()
        )
        # REAL device→host read as the run-end sync: block_until_ready
        # does not drain the execution stream on the axon backend.
        # read_back() transfers one element of EVERY fitted array
        # (forcing each array's computation and its transitive
        # dependencies), without the 1-image probe score the first r4
        # cut used — scoring traces ~5 one-row programs per fresh
        # process, a measured 6–7 s of NON-fit work that was being
        # charged to fit_seconds (interleaved A/B, BASELINE.md).  The
        # read is UNCONDITIONAL (python -O strips asserts; only the
        # validity checks live in them).
        scalars = fitted.read_back()
        dt = _time.perf_counter() - t0
        assert scalars.size >= 1
        assert np.all(np.isfinite(scalars))
        del fitted
    finally:
        # a failed leg must not leave its ledger attached to the process
        # (the solver legs that follow would trace with obs on)
        led = obs_ledger.active()
        ledger_path = led.path if led is not None else None
        obs_ledger.stop_run()
    obs_summary = None
    dataflow = {}
    if ledger_path is not None:
        try:
            from tools.obs_report import summarize

            s = summarize(ledger_path, top_k=5)
            conv = s.get("convergence") or {}
            obs_summary = {
                "stage_top": s.get("stage_top"),
                "retries": s.get("retries"),
                "memory": s.get("memory"),
                "solver_epochs": {k: len(v) for k, v in conv.items()},
                "io": {
                    k: v
                    for k, v in (s.get("io") or {}).items()
                    if isinstance(v, (int, float)) and v
                },
            }
            dataflow = s.get("dataflow") or {}
        except Exception as e:  # the summary must never fail the leg
            obs_summary = {"error": repr(e)[:200]}
    out = {
        "fit_seconds": dt,
        "fit_images_per_sec": n / dt,
        "obs": obs_summary,
    }
    # first-class dataflow accounts (ISSUE 7): seconds the host spent
    # blocked on device results / on host→device staging during the
    # fit, and the busy share of the FIT wall clock (the obs summary's
    # own fraction is over ledger wall time, which includes the report
    # tail — the fit-relative number is the round-over-round metric)
    busy = dataflow.get("device_busy_seconds")
    if busy is not None:
        out["device_busy_seconds"] = busy
        out["transfer_seconds"] = dataflow.get("transfer_seconds", 0.0)
        out["device_busy_fraction"] = busy / dt if dt > 0 else None
    return out


def solver_flops(n: int, d: int, k: int, bs: int, epochs: int) -> float:
    """Analytic FLOPs of the weighted-BCD solve (2·MACs): per epoch and
    block — Gramian AᵀA (2·n·w²), Aᵀtarget (2·n·w·k), the target and
    residual updates (≈4·n·w·k) — summed over blocks with the LAST
    block's true width w (not bs: charging a ragged tail as a full
    block would inflate the reported TFLOP/s); the w×w Cholesky factors
    are negligible at these shapes."""
    per_epoch = 0
    for lo in range(0, d, bs):
        w = min(bs, d - lo)
        per_epoch += 2 * n * w * w + 6 * n * w * k
    return float(epochs * per_epoch)


def measure_solver() -> dict:
    """Solver-phase TFLOP/s: the weighted-BCD fit alone on synthetic
    features at exactly the north-star post-featurize shape
    (n=FIT_N, d = two branches × 2·K·D, k=FIT_CLASSES)."""
    import time as _time

    import jax

    from keystone_tpu.models.block_weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
    )

    n, k = FIT_N, FIT_CLASSES
    d = 2 * (2 * FIT_GMM_K * PCA_DIMS)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), rng.integers(0, k, size=n)] = 1.0
    est = BlockWeightedLeastSquaresEstimator(
        block_size=FIT_SOLVER_BLOCK,
        num_iter=FIT_EPOCHS,
        lam=1e-4,
        mixture_weight=0.25,
    )
    import jax.numpy as jnp

    xd, yd = jnp.asarray(x), jnp.asarray(y)
    model = est.fit_arrays(xd, yd)  # warmup leg pays the compile
    np.asarray(model.flat_weights[:1, :1])
    t0 = _time.perf_counter()
    model = est.fit_arrays(xd, yd)
    # REAL device→host read as the sync (block_until_ready does not
    # drain the stream on the axon backend)
    np.asarray(model.flat_weights[:1, :1])
    dt = _time.perf_counter() - t0
    tf = solver_flops(n, d, k, FIT_SOLVER_BLOCK, FIT_EPOCHS) / dt / 1e12
    return {"solver_seconds": dt, "solver_tflops": tf}


def measure_solver_at_scale() -> dict:
    """Weighted-BCD solver at reference scale: n=65536 × d=16384 × k=64
    (the BASELINE.md 'solver at scale' shape, ~80-93% of the
    correctness-pinned true-f32 peak when healthy).  Data is generated
    ON DEVICE — host generation + the ~38 MB/s tunnel would spend ~2
    minutes moving 4.3 GB that the measurement doesn't need."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from keystone_tpu.models.block_weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
    )

    x = jax.random.normal(
        jax.random.PRNGKey(3), (ATSCALE_N, ATSCALE_D), jnp.float32
    )
    lab = jax.random.randint(
        jax.random.PRNGKey(4), (ATSCALE_N,), 0, ATSCALE_K
    )
    y = 2.0 * jax.nn.one_hot(lab, ATSCALE_K, dtype=jnp.float32) - 1.0
    est = BlockWeightedLeastSquaresEstimator(
        block_size=FIT_SOLVER_BLOCK,
        num_iter=ATSCALE_EPOCHS,
        lam=1e-4,
        mixture_weight=0.25,
    )
    model = est.fit_arrays(x, y)  # warmup leg pays compile + data gen
    np.asarray(model.flat_weights[:1, :1])
    t0 = _time.perf_counter()
    model = est.fit_arrays(x, y)
    np.asarray(model.flat_weights[:1, :1])  # real device→host sync
    dt = _time.perf_counter() - t0
    tf = (
        solver_flops(ATSCALE_N, ATSCALE_D, ATSCALE_K, FIT_SOLVER_BLOCK, ATSCALE_EPOCHS)
        / dt
        / 1e12
    )
    return {"solver_scale_seconds": dt, "solver_scale_tflops": tf}


def kernel_flops(n_rows: int, d: int, k: int, bs: int, epochs: int) -> float:
    """Analytic FLOPs of the blockwise KRR sweep (2·MACs): per epoch
    and block — the (n × bs) kernel column gemm (2·n·bs·d), the F
    update (2·n·bs·k), the block target (2·bs²·k), and the bs³/3
    Cholesky.  Identical for the in-core and out-of-core sweeps (the
    OC form computes the same column block as nb tiles)."""
    nb = -(-n_rows // bs)
    per_epoch = nb * (
        2 * n_rows * bs * d + 2 * n_rows * bs * k + 2 * bs * bs * k + bs**3 / 3
    )
    return float(epochs * per_epoch)


def measure_kernel_at_scale() -> dict:
    """Kernel solver tier A/B: one in-core blockwise KRR fit and one
    out-of-core streamed gram-block fit of the SAME seeded problem,
    with the OC leg's dataflow accounts (device-busy fraction, transfer
    seconds) read from the metrics registry and prediction parity
    reported as r²."""
    import shutil
    import tempfile
    import time as _time

    import jax.numpy as jnp

    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        KernelRidgeRegressionEstimator,
    )
    from keystone_tpu.obs import metrics
    from keystone_tpu.workflow.blockstore import RowBlockStore
    from keystone_tpu.workflow.dataset import Dataset

    n, d, k, bs = KERNEL_N, KERNEL_D, KERNEL_K, KERNEL_BLOCK
    rng = np.random.default_rng(7)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = np.tanh(x @ w / np.sqrt(d)).astype(np.float32)
    xt = rng.normal(size=(512, d)).astype(np.float32)
    est = KernelRidgeRegressionEstimator(
        GaussianKernelGenerator(KERNEL_GAMMA),
        lam=1e-4,
        block_size=bs,
        num_epochs=KERNEL_EPOCHS,
    )
    flops = kernel_flops(n, d, k, bs, KERNEL_EPOCHS)

    # ---- in-core sweep (warmup pays the compile)
    xd, yd = jnp.asarray(x), jnp.asarray(y)
    model = est.fit_arrays(xd, yd)
    np.asarray(model.alpha[:1, :1])
    t0 = _time.perf_counter()
    model = est.fit_arrays(xd, yd)
    np.asarray(model.alpha[:1, :1])  # real device→host sync
    in_seconds = _time.perf_counter() - t0
    p_in = np.asarray(model.apply_batch(jnp.asarray(xt)))

    # ---- out-of-core sweep: spill once (timed separately), then stream
    spill_root = tempfile.mkdtemp(prefix="bench_krr_")
    try:
        t0 = _time.perf_counter()
        store = RowBlockStore.from_array(spill_root, x, bs)
        spill_seconds = _time.perf_counter() - t0
        labels = Dataset(yd, n=n)
        oc_model = est.fit_store(store, labels)  # warmup: compiles steps
        before = metrics.REGISTRY.snapshot()["histograms"]
        t0 = _time.perf_counter()
        oc_model = est.fit_store(store, labels)
        np.asarray(oc_model.alpha[:1, :1])
        oc_seconds = _time.perf_counter() - t0
        after = metrics.REGISTRY.snapshot()["histograms"]

        def _delta(name):
            hi = (after.get(name) or {}).get("sum", 0.0) or 0.0
            lo = (before.get(name) or {}).get("sum", 0.0) or 0.0
            return float(hi - lo)

        transfer_seconds = _delta("blockstore.stage_wait_seconds")
        device_busy_seconds = _delta("device.busy_seconds")
        p_oc = np.asarray(oc_model.apply_batch(jnp.asarray(xt)))
        store_bytes = store.nbytes()
    finally:
        shutil.rmtree(spill_root, ignore_errors=True)

    ss_res = float(((p_oc - p_in) ** 2).sum())
    ss_tot = float(((p_in - p_in.mean(axis=0)) ** 2).sum())
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else None
    nb = store.num_blocks
    # the OC sweep's peak device residency: two staged (bs, d) row
    # blocks (current + the window's in-flight transfer) plus the
    # (α, F, Y) per-block carries — everything else stays on disk
    resident_bytes = 2 * bs * d * 4 + 3 * nb * bs * k * 4
    return {
        "kernel_tflops": in_seconds and flops / in_seconds / 1e12,
        "kernel_seconds": in_seconds,
        "oc_kernel_tflops": oc_seconds and flops / oc_seconds / 1e12,
        "oc_kernel_seconds": oc_seconds,
        "oc_spill_seconds": spill_seconds,
        "oc_vs_incore_r2": r2,
        "device_busy_seconds": device_busy_seconds,
        "transfer_seconds": transfer_seconds,
        "device_busy_fraction": (
            device_busy_seconds / oc_seconds if oc_seconds > 0 else None
        ),
        "oc_store_bytes": int(store_bytes),
        "oc_resident_bytes": int(resident_bytes),
        "oc_over_resident_x": round(store_bytes / resident_bytes, 2),
    }


def cpu_baseline_ips() -> float:
    if os.path.exists(_BASELINE_CACHE):
        try:
            with open(_BASELINE_CACHE) as f:
                cached = json.load(f)
            if cached.get("v") == _BASELINE_VERSION:
                return float(cached["ips"])
        except Exception:
            pass
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu"],
        capture_output=True,
        text=True,
        timeout=3600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        ips = float(json.loads(line)["cpu_ips"])
    except Exception:
        sys.stderr.write(f"cpu baseline failed: {proc.stderr[-500:]}\n")
        return 0.0
    with open(_BASELINE_CACHE, "w") as f:
        json.dump({"ips": ips, "v": _BASELINE_VERSION}, f)
    return ips


def main():
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        # same per-image program + same marginal-time estimator, scaled
        # down (the CPU leg is ~3 orders slower)
        ips = measure_ips(batch=32, run_lengths=(1, 2, 3), reps=2, warmup=1)
        print(json.dumps({"cpu_ips": ips}))
        return

    if "--sweep" in sys.argv:
        for b in (32, 64, 128, 256, 512):
            try:
                ips = measure_ips(b, run_lengths=(10, 25, 40), reps=2)
            except Exception as e:
                print(json.dumps({"batch": b, "error": repr(e)[:200]}))
                continue
            tf = ips * flops_per_image() / 1e12
            print(
                json.dumps(
                    {"batch": b, "ips": round(ips, 1),
                     "tflops": round(tf, 2),
                     "mfu_f32": round(tf * 1e12 / _f32_peak(), 3)}
                )
            )
        return

    if "--leg" in sys.argv:
        # one independent sample for the band (fresh process = fresh
        # backend init, which is where the ±10–25% ambient device-clock
        # spread lives — BASELINE.md "Where the variance lives")
        print(json.dumps({"leg_ips": measure_ips(BATCH)}))
        return

    if "--leg-ms" in sys.argv:
        print(
            json.dumps(
                {
                    "leg_ips": measure_ips(
                        MS_BATCH,
                        run_lengths=(10, 25, 40),
                        reps=2,
                        bin_sizes=MS_BIN_SIZES,
                        smoothing_magnif=MS_SMOOTHING,
                    )
                }
            )
        )
        return

    if "--leg-fit" in sys.argv:
        out = measure_fit()
        out.update(measure_solver())
        print(json.dumps(out))
        return

    if "--leg-serve" in sys.argv:
        from tools import serve_bench

        svc, item_shape = serve_bench.build_service(
            max_batch=SERVE_MAX_BATCH,
            queue_bound=SERVE_QUEUE_BOUND,
            deadline_ms=SERVE_DEADLINE_MS,
            # tracing on by default (the shipping config); the
            # recorder-overhead pin is its own in-process A/B leg
            # (--leg-serve-overhead), since THIS leg sits on the
            # overload collapse cliff where ratios are unmeasurable
            recorder=os.environ.get("BENCH_SERVE_RECORDER", "1") != "0",
        )
        try:
            rep = serve_bench.run_bench(
                svc,
                item_shape,
                qps=SERVE_QPS,
                duration=SERVE_DURATION_S,
                deadline_ms=SERVE_DEADLINE_MS,
                batch_delay_ms=SERVE_BATCH_DELAY_MS,
            )
        finally:
            svc.close()
        print(json.dumps(rep))
        return

    if "--leg-serve-overhead" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                serve_bench.run_overhead_pair(
                    qps=SERVE_OVERHEAD_QPS,
                    duration=SERVE_DURATION_S,
                    rounds=SERVE_OVERHEAD_ROUNDS,
                    max_batch=SERVE_MAX_BATCH,
                    deadline_ms=500.0,
                )
            )
        )
        return

    if "--leg-serve-fleet" in sys.argv:
        from tools import serve_bench

        svc, item_shape = serve_bench.build_service(
            max_batch=FLEET_MAX_BATCH,
            queue_bound=FLEET_QUEUE_BOUND,
            deadline_ms=FLEET_DEADLINE_MS,
            replicas=FLEET_REPLICAS,
        )
        try:
            rep = serve_bench.run_bench(
                svc,
                item_shape,
                qps=FLEET_QPS,
                duration=FLEET_DURATION_S,
                deadline_ms=FLEET_DEADLINE_MS,
                batch_delay_ms=FLEET_BATCH_DELAY_MS,
                swap_pipeline=serve_bench.build_pipeline(seed=1),
            )
        finally:
            svc.close()
        print(json.dumps(rep))
        return

    if "--leg-serve-hedge" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                serve_bench.run_straggler_ab(
                    qps=HEDGE_QPS,
                    duration=SERVE_DURATION_S,
                    rounds=HEDGE_ROUNDS,
                    replicas=2,
                    max_batch=SERVE_MAX_BATCH,
                    straggler_ms=HEDGE_STRAGGLER_MS,
                    hedge_ms=HEDGE_FLOOR_MS,
                )
            )
        )
        return

    if "--leg-serve-tenants" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                serve_bench.run_tenants_ab(
                    qps=TENANT_QPS,
                    duration=SERVE_DURATION_S,
                    rounds=TENANT_ROUNDS,
                    tenants=TENANT_COUNT,
                    branches=TENANT_BRANCHES,
                    max_batch=TENANT_MAX_BATCH,
                )
            )
        )
        return

    if "--leg-serve-procs" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                {
                    "procs_ab": serve_bench.run_procs_ab(
                        qps=PROC_QPS,
                        duration=PROC_DURATION_S,
                        rounds=PROC_ROUNDS,
                        workers=PROC_WORKERS,
                        burn_rounds=PROC_BURN_ROUNDS,
                    ),
                    "autoscale": serve_bench.run_autoscale_scenario(
                        qps=AUTOSCALE_QPS,
                        duration=AUTOSCALE_DURATION_S,
                        max_workers=max(2, PROC_WORKERS),
                        burn_rounds=PROC_BURN_ROUNDS,
                    ),
                }
            )
        )
        return

    if "--leg-serve-ingress" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                serve_bench.run_ingress_ab(
                    duration=INGRESS_DURATION_S,
                    rounds=INGRESS_ROUNDS,
                    shards=INGRESS_SHARDS,
                )
            )
        )
        return

    if "--leg-serve-artifacts" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                {
                    "cold_start": serve_bench.run_cold_start_ab(
                        rounds=ARTIFACT_AB_ROUNDS
                    ),
                    "restart": serve_bench.run_restart_ab(
                        rounds=ARTIFACT_AB_ROUNDS
                    ),
                }
            )
        )
        return

    if "--leg-plan" in sys.argv:
        from tools import serve_bench

        print(
            json.dumps(
                serve_bench.run_plan_ab(
                    qps=PLAN_QPS,
                    duration=PLAN_DURATION_S,
                    drift_duration=PLAN_DRIFT_DURATION_S,
                )
            )
        )
        return

    if "--leg-solver-scale" in sys.argv:
        print(json.dumps(measure_solver_at_scale()))
        return

    if "--leg-kernel" in sys.argv:
        print(json.dumps(measure_kernel_at_scale()))
        return

    if "--leg-fit-scale" in sys.argv:
        out = measure_fit(n=FIT_SCALE_N)
        print(json.dumps(out))
        return

    # Every metric is a MEDIAN over ≥3 process-level legs, with the
    # min/max band in the JSON — a single invocation's number can sit
    # anywhere in a ±25% band (VERDICT r2 item 7).  The first leg of
    # each runs in-process (it also pays any compile); later legs ride
    # the compilation cache.
    def subprocess_leg(flag: str, required=("leg_ips",), env=None):
        try:
            # the run itself sits INSIDE the try: one hung leg (e.g. an
            # at-scale solver leg on a degraded tunnel) must skip, not
            # abort the whole multi-leg artifact
            child_env = None
            if env:
                child_env = {**os.environ, **env}
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), flag],
                capture_output=True,
                text=True,
                timeout=3600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=child_env,
            )
            leg = json.loads(proc.stdout.strip().splitlines()[-1])
            # one malformed leg (e.g. a stray JSON log line on stdout)
            # must skip, not crash the whole multi-leg run
            if not isinstance(leg, dict) or any(k not in leg for k in required):
                raise ValueError(f"leg output missing {required}: {leg!r}")
            return leg
        except Exception as e:
            # proc is unbound when the run itself timed out/raised
            err = getattr(locals().get("proc"), "stderr", "") or ""
            sys.stderr.write(f"bench leg {flag} failed ({e}): {err[-300:]}\n")
            return None

    def band(vals):
        return {
            "min": round(min(vals), 2),
            "max": round(max(vals), 2),
            "n_legs": len(vals),
        }

    def dataflow_fields(legs) -> dict:
        """Median device-busy / transfer accounts over a fit leg set —
        the first-class fields the tentpole's success metric tracks
        (device_busy_fraction must RISE round over round as the feed
        stops starving the device)."""
        out = {}
        for key, digits in (
            ("device_busy_seconds", 3),
            ("transfer_seconds", 3),
            ("device_busy_fraction", 4),
        ):
            vals = [
                float(lg[key]) for lg in legs if lg.get(key) is not None
            ]
            if vals:
                out[key] = round(float(np.median(vals)), digits)
        return out

    samples = [measure_ips(BATCH)]
    for _ in range(max(0, N_LEGS - 1)):
        leg = subprocess_leg("--leg")
        if leg:
            samples.append(float(leg["leg_ips"]))
    ips = float(np.median(samples))
    tf = ips * flops_per_image() / 1e12

    # fit + multi-scale legs, same band discipline (all subprocess legs:
    # the in-process device state is already warm from the forward
    # samples, and a fit leg wants the cold-ish process the driver sees)
    fit_legs = [
        lg
        for lg in (
            subprocess_leg(
                "--leg-fit",
                required=("fit_seconds", "fit_images_per_sec", "solver_tflops"),
            )
            for _ in range(N_LEGS)
        )
        if lg
    ]
    ms_legs = [lg for lg in (subprocess_leg("--leg-ms") for _ in range(N_LEGS)) if lg]

    # at-scale legs (VERDICT r4 item 5): the solver shape that proves
    # MXU-grade training throughput, and the n=8192 full fit that
    # exercises the shape-stable chunked-apply path — both as per-round
    # artifacts with bands (SCALE_LEGS process legs each)
    solver_scale_legs = [
        lg
        for lg in (
            subprocess_leg("--leg-solver-scale", required=("solver_scale_tflops",))
            for _ in range(SCALE_LEGS)
        )
        if lg
    ]
    fit_scale_legs = [
        lg
        for lg in (
            subprocess_leg("--leg-fit-scale", required=("fit_seconds",))
            for _ in range(SCALE_LEGS)
        )
        if lg
    ]

    # kernel leg (ISSUE 13): the kernel solver tier's in-core-vs-OC A/B
    kernel_legs = [
        lg
        for lg in (
            subprocess_leg(
                "--leg-kernel",
                required=("kernel_tflops", "oc_kernel_tflops", "oc_vs_incore_r2"),
            )
            for _ in range(KERNEL_LEGS)
        )
        if lg
    ]

    # serve leg (ISSUE 5): the online endpoint under deterministic
    # overload — one process leg (the serving layer's numbers are
    # scheduler-dominated, not device-clock-dominated)
    serve_legs = [
        lg
        for lg in (
            subprocess_leg(
                "--leg-serve", required=("achieved_qps", "p50_ms")
            )
            for _ in range(SERVE_LEGS)
        )
        if lg
    ]
    # recorder-overhead pin (ISSUE 9): in-process A/B of the identical
    # steady-state workload with the flight recorder on vs off — the
    # tracing tax must keep p99 and achieved QPS within 5%
    serve_overhead_leg = (
        subprocess_leg("--leg-serve-overhead", required=("overhead",))
        if serve_legs
        else None
    )

    # fleet leg (ISSUE 8): the N-replica fleet + mid-run hot-swap, and
    # ONE 1-replica leg with the identical config — their achieved-QPS
    # ratio is the recorded scaling claim.  On CPU hosts the child needs
    # the host platform split into N devices (appended, so a TPU host's
    # existing XLA_FLAGS survive; the flag is inert off-CPU).
    fleet_env = {
        "XLA_FLAGS": (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={FLEET_REPLICAS}"
        ).strip()
    }
    fleet_legs = [
        lg
        for lg in (
            subprocess_leg(
                "--leg-serve-fleet",
                required=("achieved_qps", "replica_occupancy"),
                env=fleet_env,
            )
            for _ in range(FLEET_LEGS)
        )
        if lg
    ] if FLEET_LEGS > 0 else []
    fleet_single_leg = (
        subprocess_leg(
            "--leg-serve-fleet",
            required=("achieved_qps",),
            env={**fleet_env, "BENCH_FLEET_REPLICAS": "1"},
        )
        if fleet_legs
        else None
    )

    # hedging leg (ISSUE 10): the straggler A/B — hedging on vs off
    # against an injected per-replica stall; needs 2 host devices
    hedge_leg = (
        subprocess_leg(
            "--leg-serve-hedge", required=("hedging",), env=fleet_env
        )
        if HEDGE_LEGS > 0
        else None
    )

    # AOT artifact legs (ISSUE 11): cold-start + restart-to-rejoin,
    # artifact vs compile (the driver leg spawns its own per-arm
    # subprocesses with fresh compile caches)
    artifact_leg = (
        subprocess_leg(
            "--leg-serve-artifacts", required=("cold_start", "restart")
        )
        if ARTIFACT_LEGS > 0
        else None
    )

    # multi-tenant leg (ISSUE 14): shared-vs-unshared A/B over N
    # co-served pipelines sharing a featurization prefix
    tenant_leg = (
        subprocess_leg(
            "--leg-serve-tenants",
            required=("aggregate_qps_shared", "predictions_identical"),
        )
        if TENANT_LEGS > 0
        else None
    )

    # process fleet leg (ISSUE 15): thread-vs-process A/B on the
    # compute-bound workload + the 1→N→1 autoscale scenario
    proc_leg = (
        subprocess_leg(
            "--leg-serve-procs", required=("procs_ab", "autoscale")
        )
        if PROC_LEGS > 0
        else None
    )

    # ingress leg (ISSUE 17): threaded HTTP/JSON vs binary-batch A/B on
    # one service — the front-end ceiling, tracked per round
    ingress_leg = (
        subprocess_leg(
            "--leg-serve-ingress",
            required=("speedup", "predictions_identical"),
        )
        if INGRESS_LEGS > 0
        else None
    )

    # plan leg (ISSUE 20): planned vs static-default A/B + the live
    # drift-retune sub-check
    plan_leg = (
        subprocess_leg("--leg-plan", required=("speedup", "drift_retune"))
        if PLAN_LEGS > 0
        else None
    )

    # precision-mode sweep: same headline program and estimator, one
    # process leg per mode (KEYSTONE_MATMUL pinned in the child).  The
    # "auto" mode IS the headline measurement when the parent env does
    # not pin a policy, so those already-collected samples are reused
    # instead of paying a redundant subprocess leg.
    precision_sweep = {}
    for mode in PRECISION_MODES if PRECISION_LEGS > 0 else ():
        if mode == "auto" and not os.environ.get("KEYSTONE_MATMUL"):
            vals = list(samples)
        else:
            vals = [
                float(lg["leg_ips"])
                for lg in (
                    subprocess_leg("--leg", env={"KEYSTONE_MATMUL": mode})
                    for _ in range(PRECISION_LEGS)
                )
                if lg
            ]
        if not vals:
            continue
        mips = float(np.median(vals))
        mtf = mips * flops_per_image() / 1e12
        precision_sweep[mode] = {
            "images_per_sec": round(mips, 1),
            "band": band(vals),
            "tflops": round(mtf, 2),
            "mfu_f32": round(mtf * 1e12 / _f32_peak(), 3),
            "mfu_bf16_eff": round(mtf * 1e12 / _BF16_EFFECTIVE_PEAK, 3),
        }

    cpu_ips = cpu_baseline_ips()
    vs = ips / cpu_ips if cpu_ips > 0 else None
    out = {
        "metric": "imagenet_fv_pipeline_throughput",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 2) if vs else None,
        "band": band(samples),
        "tflops": round(tf, 2),
        "mfu_f32": round(tf * 1e12 / _f32_peak(), 3),
        "mfu_bf16_eff": round(tf * 1e12 / _BF16_EFFECTIVE_PEAK, 3),
        "config": {
            "batch": BATCH, "image_hw": IMAGE_HW, "sift_step": SIFT_STEP,
            "gmm_k": GMM_K, "pca_dims": PCA_DIMS, "classes": NUM_CLASSES,
        },
    }
    if precision_sweep:
        out["precision_sweep"] = precision_sweep
    if fit_legs:
        fit_s = [float(lg["fit_seconds"]) for lg in fit_legs]
        out["fit"] = {
            "fit_seconds": round(float(np.median(fit_s)), 2),
            "fit_images_per_sec": round(
                float(np.median([lg["fit_images_per_sec"] for lg in fit_legs])), 1
            ),
            "band_seconds": band(fit_s),
            "solver_tflops": round(
                float(np.median([lg["solver_tflops"] for lg in fit_legs])), 2
            ),
            "solver_band_tflops": band(
                [float(lg["solver_tflops"]) for lg in fit_legs]
            ),
            "config": {
                "n": FIT_N, "image_hw": IMAGE_HW, "gmm_k": FIT_GMM_K,
                "classes": FIT_CLASSES, "epochs": FIT_EPOCHS,
                "solver_block": FIT_SOLVER_BLOCK,
            },
        }
        out["fit"].update(dataflow_fields(fit_legs))
        # operational context of the fit (stage top-k, retry totals,
        # memory watermarks) from the first leg's run ledger, so the
        # perf trajectory in BENCH_rNN.json explains itself
        obs_leg = next((lg.get("obs") for lg in fit_legs if lg.get("obs")), None)
        if obs_leg:
            out["fit"]["obs"] = obs_leg
    if ms_legs:
        ms = [float(lg["leg_ips"]) for lg in ms_legs]
        out["multiscale"] = {
            "images_per_sec": round(float(np.median(ms)), 1),
            "band": band(ms),
            "config": {
                "batch": MS_BATCH,
                "bin_sizes": list(MS_BIN_SIZES),
                "smoothing_magnif": MS_SMOOTHING,
            },
        }
    if kernel_legs:
        med = lambda key, digits=3: round(  # noqa: E731
            float(np.median([float(lg[key]) for lg in kernel_legs
                             if lg.get(key) is not None])), digits
        )
        out["kernel_at_scale"] = {
            "tflops": med("kernel_tflops"),
            "oc_tflops": med("oc_kernel_tflops"),
            "seconds": med("kernel_seconds", 2),
            "oc_seconds": med("oc_kernel_seconds", 2),
            "oc_spill_seconds": med("oc_spill_seconds", 2),
            # the acceptance gates: r² ≥ 0.999 parity and a populated
            # dataflow account for the streamed feed
            "oc_vs_incore_r2": med("oc_vs_incore_r2", 6),
            "device_busy_fraction": med("device_busy_fraction", 4),
            "transfer_seconds": med("transfer_seconds"),
            "oc_over_resident_x": med("oc_over_resident_x", 2),
            "band_tflops": band(
                [float(lg["kernel_tflops"]) for lg in kernel_legs]
            ),
            "config": {
                "n": KERNEL_N, "d": KERNEL_D, "k": KERNEL_K,
                "block": KERNEL_BLOCK, "epochs": KERNEL_EPOCHS,
                "gamma": KERNEL_GAMMA,
            },
        }
    if solver_scale_legs:
        tfs = [float(lg["solver_scale_tflops"]) for lg in solver_scale_legs]
        out["solver_at_scale"] = {
            "tflops": round(float(np.median(tfs)), 2),
            "band_tflops": band(tfs),
            "config": {
                "n": ATSCALE_N, "d": ATSCALE_D, "k": ATSCALE_K,
                "epochs": ATSCALE_EPOCHS, "block": FIT_SOLVER_BLOCK,
            },
        }
    if serve_legs:
        # one leg's full report, medians over legs for the headline keys
        sv = dict(serve_legs[0])
        if len(serve_legs) > 1:
            for key in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms"):
                vals = [
                    float(lg[key]) for lg in serve_legs if lg.get(key) is not None
                ]
                if vals:
                    sv[key] = round(float(np.median(vals)), 2)
        if serve_overhead_leg:
            # ratios near 1.0 = the recorder lives inside its overhead
            # budget (acceptance: within 5%)
            sv["recorder_overhead"] = serve_overhead_leg
        out["serve"] = sv
    if fleet_legs:
        fv = dict(fleet_legs[0])
        if len(fleet_legs) > 1:
            for key in ("achieved_qps", "p50_ms", "p95_ms", "p99_ms"):
                vals = [
                    float(lg[key]) for lg in fleet_legs if lg.get(key) is not None
                ]
                if vals:
                    fv[key] = round(float(np.median(vals)), 2)
        pauses_ms = [
            1000.0 * float(lg["swap"]["pause_seconds"])
            for lg in fleet_legs
            if lg.get("swap") and lg["swap"].get("pause_seconds") is not None
        ]
        if pauses_ms:
            fv["swap_pause_p99_ms"] = round(
                float(np.percentile(pauses_ms, 99)), 4
            )
        if fleet_single_leg and fleet_single_leg.get("achieved_qps"):
            single = float(fleet_single_leg["achieved_qps"])
            fv["single_replica_achieved_qps"] = round(single, 1)
            if single > 0 and fv.get("achieved_qps"):
                fv["fleet_speedup"] = round(
                    float(fv["achieved_qps"]) / single, 2
                )
        # the honest framing of fleet_speedup (PR-8's report implied a
        # hardware-scaling claim; it never was one): the emulated model
        # is an injected GIL-RELEASING sleep, so the ratio measures
        # router/queue concurrency over device stalls.  Multi-core
        # COMPUTE scaling is the serve_procs section's claim.
        fv["scaling_note"] = (
            "stall-dominated by construction (batch_delay_ms releases "
            "the GIL): measures router concurrency, not multi-core "
            "compute — see serve_procs for the compute-bound claim"
        )
        out["serve_fleet"] = fv
    if proc_leg:
        # the ISSUE-15 acceptance: >= 1.8x thread->process speedup on a
        # compute-bound workload where >= 2 cores exist (cores_limited
        # marks hosts that cannot express the claim), bit-identical
        # predictions, and a clean 1→N→1 autoscale scenario
        out["serve_procs"] = proc_leg
    if ingress_leg:
        # the ISSUE-17 acceptance: binary batch path >= 3x the threaded
        # HTTP/JSON per-datum QPS ceiling, p99 for both arms,
        # predictions bit-identical across JSON and binary
        out["serve_ingress"] = ingress_leg
    if plan_leg:
        # the ISSUE-20 acceptance: the planned configuration matches or
        # beats static defaults (speedup >= 1.0) and the live drift
        # retune improves p99 or reverts via the bake guard with zero
        # lost futures
        out["plan"] = plan_leg
    if hedge_leg:
        # p99_ratio < 1 = hedging rescued the straggler's queue;
        # qps_cost <= 0.05 = the acceptance budget
        out["serve_hedge"] = hedge_leg
    if tenant_leg:
        # speedup >= 1.5 = the shared stage pool pays (ISSUE 14
        # acceptance); fairness_p99_ratio <= 1.25 = DRR fair share;
        # predictions_identical pins shared-vs-unshared bit-parity
        out["serve_tenants"] = tenant_leg
    if artifact_leg:
        # speedup > 1 on both legs = the artifact tier beats fresh
        # compilation for cold start AND supervisor heal;
        # predictions_match pins artifact-vs-compile bit-parity
        for section in artifact_leg.values():
            if isinstance(section, dict):
                section.pop("samples", None)  # medians suffice in the artifact
        out["serve_artifacts"] = artifact_leg
    if fit_scale_legs:
        fss = [float(lg["fit_seconds"]) for lg in fit_scale_legs]
        out["fit_at_scale"] = {
            "fit_seconds": round(float(np.median(fss)), 2),
            "band_seconds": band(fss),
            "fit_images_per_sec": round(
                float(
                    np.median(
                        [lg["fit_images_per_sec"] for lg in fit_scale_legs]
                    )
                ),
                1,
            ),
            "config": {
                "n": FIT_SCALE_N, "image_hw": IMAGE_HW, "gmm_k": FIT_GMM_K,
                "classes": FIT_CLASSES, "epochs": FIT_EPOCHS,
            },
        }
        out["fit_at_scale"].update(dataflow_fields(fit_scale_legs))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
