"""Headline benchmark — ImageNet FV pipeline throughput (images/sec/chip).

Measures the north-star path (BASELINE.md): dense SIFT → PCA → GMM Fisher
vector → power/L2 normalization → block-linear scoring, end to end on
device, steady-state, on one TPU chip.  ``vs_baseline`` is the speedup
against the same JAX program on one host CPU (the closest stand-in for
the reference's BLAS-on-CPU executors; the reference repo publishes no
numbers — BASELINE.json "published": {}).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py            # TPU (or default backend) + cached CPU baseline
       python bench.py --cpu     # run the CPU-baseline leg only (prints ips)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 512  # large batches amortize dispatch; see BASELINE.md measurements
IMAGE_HW = 64
GMM_K = 64
PCA_DIMS = 64
NUM_CLASSES = 1000
WARMUP = 2
ITERS = 10
_BASELINE_CACHE = os.path.join(os.path.dirname(__file__), ".bench_cpu_baseline.json")


def build_forward():
    import jax.numpy as jnp

    from keystone_tpu.models.block_ls import BlockLinearMapper
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops import (
        GrayScaler,
        NormalizeRows,
        SIFTExtractor,
        SignedHellingerMapper,
    )
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(0)
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    pca = PCATransformer(
        jnp.asarray(np.linalg.qr(rng.normal(size=(128, PCA_DIMS)))[0], jnp.float32),
        mean=jnp.zeros((128,), jnp.float32),
    )
    gmm = GaussianMixtureModel(
        jnp.full((GMM_K,), 1.0 / GMM_K, jnp.float32),
        jnp.asarray(rng.normal(size=(GMM_K, PCA_DIMS)), jnp.float32),
        jnp.ones((GMM_K, PCA_DIMS), jnp.float32),
    )
    fv_dim = 2 * GMM_K * PCA_DIMS
    block = 4096
    nb = -(-fv_dim // block)
    blm = BlockLinearMapper(
        jnp.asarray(
            0.01 * rng.normal(size=(nb, block, NUM_CLASSES)), jnp.float32
        ),
        block,
    )
    gray, hell, norm = GrayScaler(), SignedHellingerMapper(), NormalizeRows()
    fv = FisherVector(gmm)

    def forward(images):
        g = gray.apply_batch(images)
        desc, mask = sift.apply_batch(g)
        desc, mask = pca.apply_batch(desc, mask=mask)
        feats = fv.apply_batch(desc, mask=mask)
        feats = norm.apply_batch(hell.apply_batch(feats))
        return blm.apply_batch(feats)

    return forward


def measure_ips(batch: int, iters: int, warmup: int) -> float:
    import jax

    forward = jax.jit(build_forward())
    images = np.random.default_rng(1).uniform(
        0, 1, (batch, IMAGE_HW, IMAGE_HW, 3)
    ).astype(np.float32)
    import jax.numpy as jnp

    images = jnp.asarray(images)
    for _ in range(warmup):
        forward(images).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = forward(images)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * iters / dt


def cpu_baseline_ips() -> float:
    if os.path.exists(_BASELINE_CACHE):
        try:
            with open(_BASELINE_CACHE) as f:
                return float(json.load(f)["ips"])
        except Exception:
            pass
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu"],
        capture_output=True,
        text=True,
        timeout=3600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        ips = float(json.loads(line)["cpu_ips"])
    except Exception:
        sys.stderr.write(f"cpu baseline failed: {proc.stderr[-500:]}\n")
        return 0.0
    with open(_BASELINE_CACHE, "w") as f:
        json.dump({"ips": ips}, f)
    return ips


def main():
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        # same per-image program; batch chosen so the CPU leg also gets
        # dispatch amortization (larger batches don't change its ips)
        ips = measure_ips(batch=64, iters=2, warmup=1)
        print(json.dumps({"cpu_ips": ips}))
        return

    import jax

    ips = measure_ips(BATCH, ITERS, WARMUP)
    cpu_ips = cpu_baseline_ips()
    vs = ips / cpu_ips if cpu_ips > 0 else None
    print(
        json.dumps(
            {
                "metric": "imagenet_fv_pipeline_throughput",
                "value": round(ips, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(vs, 2) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
