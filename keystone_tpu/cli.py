"""CLI dispatcher — the bin/run-pipeline.sh analogue.

    python -m keystone_tpu.cli <PipelineName> [pipeline flags...]
    python -m keystone_tpu.cli --list
"""

from __future__ import annotations

import importlib
import os
import sys

_PIPELINE_MODULES = {
    "MnistRandomFFT": "keystone_tpu.pipelines.mnist_random_fft",
    "LinearPixels": "keystone_tpu.pipelines.linear_pixels",
    "RandomPatchCifar": "keystone_tpu.pipelines.random_patch_cifar",
    "NewsgroupsPipeline": "keystone_tpu.pipelines.newsgroups",
    "TimitPipeline": "keystone_tpu.pipelines.timit",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
    "VOCSIFTFisher": "keystone_tpu.pipelines.voc_sift_fisher",
    "AmazonReviewsPipeline": "keystone_tpu.pipelines.amazon_reviews",
}


def _apply_platform_env() -> None:
    """Honor KEYSTONE_PLATFORM before any backend is initialized.

    Some environments force a platform programmatically at interpreter
    start (overriding JAX_PLATFORMS), so the launcher's env var must be
    re-applied through jax.config here."""
    platform = os.environ.get("KEYSTONE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("--list", "-l", "--help", "-h"):
        print("usage: python -m keystone_tpu.cli <PipelineName> [flags]")
        print("pipelines:")
        for name in _PIPELINE_MODULES:
            print(f"  {name}")
        return 0
    name, rest = argv[0], argv[1:]
    if name not in _PIPELINE_MODULES:
        print(f"unknown pipeline {name!r}; use --list", file=sys.stderr)
        return 2
    # only now touch jax: --list/--help/typos shouldn't pay the import
    _apply_platform_env()
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    state_dir = os.environ.get("KEYSTONE_STATE_DIR")
    if state_dir:
        # saved-prefix reload (workflow/state.py SavedStateLoadRule):
        # loader datasets are named, so featurized prefixes persisted by
        # save_pipeline_state in an earlier process are reused here
        from keystone_tpu.workflow import PipelineEnv

        PipelineEnv.state_dir = state_dir
    mod = importlib.import_module(_PIPELINE_MODULES[name])
    mod.main(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
