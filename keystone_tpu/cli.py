"""CLI dispatcher — the bin/run-pipeline.sh analogue.

    python -m keystone_tpu.cli <PipelineName> [pipeline flags...]
    python -m keystone_tpu.cli serve --model model.pkl [serve flags...]
    python -m keystone_tpu.cli worker --connect HOST:PORT [worker flags...]
    python -m keystone_tpu.cli check <PipelineName> [check flags...]
    python -m keystone_tpu.cli check --model model.pkl [check flags...]
    python -m keystone_tpu.cli --list
"""

from __future__ import annotations

import importlib
import os
import sys

_PIPELINE_MODULES = {
    "MnistRandomFFT": "keystone_tpu.pipelines.mnist_random_fft",
    "LinearPixels": "keystone_tpu.pipelines.linear_pixels",
    "RandomPatchCifar": "keystone_tpu.pipelines.random_patch_cifar",
    "NewsgroupsPipeline": "keystone_tpu.pipelines.newsgroups",
    "TimitPipeline": "keystone_tpu.pipelines.timit",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
    "VOCSIFTFisher": "keystone_tpu.pipelines.voc_sift_fisher",
    "AmazonReviewsPipeline": "keystone_tpu.pipelines.amazon_reviews",
    "KernelTimitPipeline": "keystone_tpu.pipelines.kernel_timit",
    "KernelCifarPipeline": "keystone_tpu.pipelines.kernel_cifar",
}


def _apply_platform_env() -> None:
    """Honor KEYSTONE_PLATFORM before any backend is initialized.

    Some environments force a platform programmatically at interpreter
    start (overriding JAX_PLATFORMS), so the launcher's env var must be
    re-applied through jax.config here."""
    platform = os.environ.get("KEYSTONE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def _serve_main(argv) -> int:
    """``serve`` subcommand: load a saved fitted pipeline (or the
    current version from a model registry) and expose it over HTTP
    (POST /predict, GET /healthz, GET /replicas, POST /swap,
    GET /metrics, plus the live ops surface GET /statusz, GET /tracez,
    GET /requestz/<id>) through the micro-batching replica fleet
    (keystone_tpu/serve) with request-scoped tracing into an always-on
    bounded flight recorder."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli serve",
        description="serve a saved fitted pipeline over HTTP with "
        "dynamic micro-batching, admission control, a multi-device "
        "replica fleet, and registry-driven live model hot-swap",
    )
    ap.add_argument(
        "--model",
        action="append",
        default=None,
        metavar="PATH | NAME=PATH",
        help="path to a FittedPipeline saved via save()/fit_or_load().  "
        "Repeatable with NAME=PATH pairs for a MULTI-TENANT deploy "
        "(serve/tenants.py): every named model is co-served behind one "
        "fleet, shared featurization prefixes computed once per flush "
        "via the cross-pipeline stage pool; requests route by the "
        "'tenant' body field.",
    )
    ap.add_argument(
        "--model-dir",
        action="append",
        default=None,
        metavar="DIR | NAME=DIR",
        help="versioned model registry root (serve/registry.py): serve "
        "the CURRENT version (falling back past corrupt ones), enable "
        "POST /swap, and (with --watch) hot-swap newly published "
        "versions live.  Repeatable with NAME=DIR pairs for a "
        "registry-backed multi-tenant deploy (each tenant serves its "
        "registry's CURRENT version; /swap and --watch need a "
        "single-tenant deploy).  At least one --model/--model-dir is "
        "required; mixing named and unnamed entries is an error.",
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="serving fleet size: one FrozenApplier clone per local "
        "device (cycling when replicas > devices); flushes are routed "
        "to the least-loaded replica whose breaker admits work",
    )
    ap.add_argument(
        "--workers",
        type=int,
        default=0,
        help="PROCESS fleet (serve/procfleet.py): serve with this many "
        "one-replica worker processes instead of worker threads — each "
        "loads the model + AOT artifacts, primes, and computes applies "
        "over a shared-memory wire, so a multi-core host's throughput "
        "is bounded by cores, not the GIL.  0 (default) = the threaded "
        "fleet.  Exclusive with --replicas > 1; single-tenant only.",
    )
    ap.add_argument(
        "--hosts",
        default=None,
        metavar="HOST[:SLOTS],...",
        help="CROSS-HOST fleet (serve/net.py; requires --workers >= 1): "
        "a host map of boxes where workers may be spawned, e.g. "
        "'local:2,gpu-a:4,gpu-b:4'.  'local' spawns on this machine; "
        "remote hosts are reached over ssh and connect back to "
        "--listen-host:--listen-port over TCP.  Each worker beats a "
        "heartbeat lease; an expired lease is treated as death (the "
        "flush re-serves on a survivor) and the worker self-fences so "
        "a healed partition cannot double-serve.  Without --hosts, "
        "--workers stays on the shared-memory transport.",
    )
    ap.add_argument(
        "--lease-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="heartbeat lease length for the cross-host fleet (default "
        "5.0): both sides beat every lease/4; this much silence fences "
        "the worker / declares it dead at the router",
    )
    ap.add_argument(
        "--listen-host",
        default=None,
        metavar="ADDR",
        help="interface the cross-host fleet's registration listener "
        "binds (default 127.0.0.1 — set 0.0.0.0 when workers connect "
        "from other boxes)",
    )
    ap.add_argument(
        "--listen-port",
        type=int,
        default=None,
        help="registration listener port (default 0 = ephemeral)",
    )
    ap.add_argument(
        "--autoscale",
        default=None,
        metavar="MIN:MAX",
        help="SLO-driven autoscaling (serve/autoscale.py): a control "
        "thread watches windowed occupancy, queue depth, SLO burn, and "
        "the shared-pool hit rate, growing the fleet to MAX under "
        "pressure, retiring idle workers down to MIN, and retuning the "
        "dispatch window live (visible in GET /statusz).  Pair with "
        "--workers (the floor spawns as processes).",
    )
    ap.add_argument(
        "--watch",
        type=float,
        default=None,
        metavar="SECONDS",
        help="poll --model-dir's CURRENT pointer this often and blue/"
        "green hot-swap new versions into the fleet (prime in the "
        "background, commit at the flush boundary; requires --model-dir)",
    )
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument(
        "--max-wait-ms",
        type=float,
        default=None,
        help="flush the micro-batch when the oldest request has waited "
        "this long (or when --max-batch requests are queued).  Default: "
        "the installed PhysicalPlan's value if the model ships one, "
        "else 5.0 — passing a value always wins (the explicit tier of "
        "the planner precedence ladder)",
    )
    ap.add_argument(
        "--queue-bound",
        type=int,
        default=128,
        help="admission control: reject (HTTP 429) past this queue depth",
    )
    ap.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="default per-request deadline; doomed requests are shed "
        "(HTTP 504) instead of executed",
    )
    ap.add_argument(
        "--slo-ms",
        type=float,
        default=None,
        help="latency objective for GET /statusz's SLO error-budget "
        "burn rate (default: --deadline-ms when set)",
    )
    ap.add_argument(
        "--slo-target",
        type=float,
        default=0.99,
        help="fraction of requests that must beat the objective "
        "(burn rate = windowed bad fraction / (1 - target))",
    )
    ap.add_argument(
        "--slo-window-s",
        type=float,
        default=None,
        help="sliding window the SLO burn rate (and rollout guardrails) "
        "measure over (default 60s): shorter windows react faster but "
        "judge canaries on fewer samples",
    )
    ap.add_argument(
        "--canary",
        type=float,
        default=None,
        metavar="FRACTION",
        help="guarded rollouts (serve/rollout.py): --watch swaps stage "
        "the new version to this fraction of traffic (seeded hash of "
        "request id — replayable), judge it against the SLO-burn/error-"
        "rate/p99 guardrails, then auto-commit or roll back and "
        "quarantine the version.  Requires --watch.",
    )
    ap.add_argument(
        "--bake-s",
        type=float,
        default=0.0,
        help="post-commit bake: watch the SLO burn this long after a "
        "canary commit and auto-revert to the prior version on "
        "sustained violation (0 = off; needs --canary)",
    )
    ap.add_argument(
        "--no-recorder",
        action="store_true",
        help="disable the in-memory flight recorder (request tracing; "
        "GET /tracez and GET /requestz/<id> answer 409).  HTTP "
        "responses still echo a request id (client log correlation); "
        "nothing records or resolves it server-side",
    )
    ap.add_argument(
        "--trace-dump",
        default=None,
        metavar="DIR",
        help="durable flight-recorder snapshots: POST /tracez/dump "
        "writes the recorder state into DIR (atomic publish), and a "
        "final snapshot is written at shutdown — the artifact "
        "tools/trace_report.py reads offline for post-incident "
        "analysis.  Needs the recorder (conflicts with --no-recorder).",
    )
    ap.add_argument(
        "--no-supervise",
        action="store_true",
        help="disable the replica supervisor (self-healing: dead/wedged "
        "worker detection, in-place restart, quarantine after repeated "
        "deaths).  On by default.",
    )
    ap.add_argument(
        "--heartbeat-s",
        type=float,
        default=30.0,
        help="wedge budget: a replica worker holding one flush longer "
        "than this is declared wedged and restarted — size it above "
        "the slowest honest apply",
    )
    ap.add_argument(
        "--restart-limit",
        type=int,
        default=3,
        help="supervisor restarts allowed per replica within "
        "--restart-window-s before the slot is quarantined",
    )
    ap.add_argument(
        "--restart-window-s",
        type=float,
        default=60.0,
        help="the sliding window the restart budget counts over",
    )
    ap.add_argument(
        "--hedge-ms",
        type=float,
        default=None,
        help="hedged dispatch (off by default): re-enqueue a batch "
        "still unflushed after max(this, 3x the EWMA batch time) on a "
        "second replica; first claim wins, the loser is cancelled "
        "without device work.  Needs --replicas >= 2.",
    )
    ap.add_argument(
        "--no-bisect",
        action="store_true",
        help="disable batch-failure bisection (poison-request "
        "isolation + content quarantine).  On by default.",
    )
    ap.add_argument(
        "--no-artifacts",
        action="store_true",
        help="skip the AOT artifact tier: ignore pre-lowered "
        "executables published next to the model (the escape hatch "
        "when a published artifact is suspected bad) — priming rides "
        "the compile-cache/fresh-compile rungs of the ladder instead",
    )
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--example-shape",
        default=None,
        metavar="D0[,D1,...]",
        help="per-datum input shape (e.g. '24' or '3,32,32'): primes "
        "every padding bucket's compiled program BEFORE serving, so no "
        "request ever pays a trace+compile against its deadline.  "
        "Without it the first request per bucket compiles in-band.",
    )
    args = ap.parse_args(argv)
    models = list(args.model or [])
    model_dirs = list(args.model_dir or [])
    if not models and not model_dirs:
        ap.error("at least one of --model / --model-dir is required")

    def _named(spec: str) -> bool:
        # NAME=PATH only when the prefix is a plain tenant name and the
        # whole spec is not itself an existing path — a single
        # --model ./runs/lr=0.1/model.pkl must stay a path
        name, sep, _ = spec.partition("=")
        return bool(sep) and bool(name) and os.sep not in name and not (
            os.path.exists(spec)
        )

    named = [m for m in models + model_dirs if _named(m)]
    multi = bool(named) or (len(models) + len(model_dirs)) > 1
    if multi and len(named) != len(models) + len(model_dirs):
        ap.error(
            "multi-tenant deploys name every entry: --model NAME=PATH / "
            "--model-dir NAME=DIR"
        )
    if not multi and models and model_dirs:
        ap.error("pass one --model OR one --model-dir, not both")
    if args.watch is not None and (multi or not model_dirs):
        ap.error("--watch requires a single-tenant --model-dir deploy")

    from keystone_tpu.serve import HttpFrontend, serve, serve_multi

    example = None
    if args.example_shape:
        import numpy as np

        shape = tuple(int(d) for d in args.example_shape.split(","))
        example = np.zeros(shape, np.float32)
    autoscale = None
    if args.autoscale:
        try:
            lo, _, hi = args.autoscale.partition(":")
            autoscale = dict(min_workers=int(lo), max_workers=int(hi))
        except ValueError:
            ap.error("--autoscale takes MIN:MAX (e.g. 1:4)")
    if args.workers and args.replicas > 1:
        ap.error("--workers (process fleet) and --replicas are exclusive")
    if args.workers and multi:
        ap.error("--workers is single-tenant only (the shared stage "
                 "pool needs in-process walks)")
    if args.hosts and not args.workers:
        ap.error("--hosts (cross-host fleet) requires --workers >= 1")
    if args.hosts and multi:
        ap.error("--hosts is single-tenant only")
    if args.trace_dump and args.no_recorder:
        ap.error("--trace-dump needs the flight recorder; drop "
                 "--no-recorder")
    if args.canary is not None and args.watch is None:
        ap.error("--canary guards --watch swaps; add --watch SECONDS")
    if args.bake_s and args.canary is None:
        ap.error("--bake-s needs --canary")
    fleet_kw = (
        dict(workers=args.workers)
        if args.workers
        else dict(replicas=args.replicas)
    )
    if args.hosts:
        fleet_kw["hosts"] = args.hosts
        net_opts = {}
        if args.lease_s is not None:
            net_opts["lease_s"] = args.lease_s
        if args.listen_host is not None:
            net_opts["listen_host"] = args.listen_host
        if args.listen_port is not None:
            net_opts["listen_port"] = args.listen_port
        if net_opts:
            fleet_kw["worker_opts"] = net_opts
    serve_kw = dict(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_bound=args.queue_bound,
        deadline_ms=args.deadline_ms,
        example=example,
        recorder=not args.no_recorder,
        **fleet_kw,
        slo_ms=args.slo_ms,
        slo_target=args.slo_target,
        supervise=not args.no_supervise,
        heartbeat_s=args.heartbeat_s,
        restart_limit=args.restart_limit,
        restart_window_s=args.restart_window_s,
        hedge_ms=args.hedge_ms,
        bisect=not args.no_bisect,
        autoscale=autoscale,
        slo_window_s=args.slo_window_s,
    )
    registry = None
    artifacts = None
    if multi:
        # registry multi-model deploy: each named entry loads a saved
        # model (NAME=PATH) or a registry's CURRENT version (NAME=DIR);
        # the fleet co-serves them with cross-pipeline prefix sharing
        from keystone_tpu.serve import ModelRegistry
        from keystone_tpu.workflow import FittedPipeline

        tenants = {}
        parts = []
        for spec in models:
            name, _, path = spec.partition("=")
            tenants[name] = FittedPipeline.load(path)
            parts.append(f"{name}={path}")
        for spec in model_dirs:
            name, _, root = spec.partition("=")
            reg = ModelRegistry(root)
            fitted, version = reg.load()
            tenants[name] = fitted
            parts.append(f"{name}={root} ({version})")
            if not args.no_artifacts:
                # the multi applier has no per-tenant bucket-program
                # install (the walk serves), but the bundle's
                # pre-seeded compile-cache entries — this PR's last
                # cold rung — apply process-wide: seed them so the
                # deploy's primes hit the cache tier
                arts = reg.load_artifacts(version)
                if arts:
                    from keystone_tpu.utils.compile_cache import (
                        seed_compile_cache,
                    )

                    seed_compile_cache(arts)
        svc = serve_multi(tenants, **serve_kw)
        version = "multi"
        source = ", ".join(parts)
    elif model_dirs:
        from keystone_tpu.serve import ModelRegistry

        registry = ModelRegistry(model_dirs[0])
        fitted, version = registry.load()
        if not args.no_artifacts:
            # best-effort AOT tier: absent/corrupt artifacts mean this
            # deploy compiles — never that it fails
            artifacts = registry.load_artifacts(version)
        source = f"{model_dirs[0]} ({version})"
        svc = serve(fitted, version=version, artifacts=artifacts, **serve_kw)
    else:
        from keystone_tpu.workflow import FittedPipeline

        fitted = FittedPipeline.load(models[0])
        version, source = "v0", models[0]
        svc = serve(fitted, version=version, **serve_kw)
    watcher = None
    if args.watch is not None:
        from keystone_tpu.serve import RegistryWatcher

        rollout_cfg = None
        if args.canary is not None:
            from keystone_tpu.serve import RolloutConfig

            rollout_cfg = RolloutConfig(
                canary=args.canary, bake_s=args.bake_s
            )
        watcher = RegistryWatcher(
            svc, registry, poll_seconds=args.watch, rollout=rollout_cfg
        ).start()
    front = HttpFrontend(
        svc,
        host=args.host,
        port=args.port,
        registry=registry,
        trace_dump_dir=args.trace_dump,
    )
    print(
        f"serving {source} on http://{args.host}:{front.port} "
        f"(replicas={svc.replicas}, max_batch={args.max_batch}, "
        f"max_wait_ms={svc.max_wait_s * 1000.0:g}, "
        f"queue_bound={args.queue_bound}"
        + (f", watching every {args.watch:g}s" if watcher else "")
        + (", tracing off" if args.no_recorder else ", tracing on")
        + (", artifacts on" if artifacts else "")
        + ")",
        flush=True,
    )
    try:
        front.serve_forever()
    except KeyboardInterrupt:
        print("shutting down (draining in-flight requests)", flush=True)
    finally:
        if watcher is not None:
            watcher.stop()
        front.server.server_close()
        if args.trace_dump:
            # the shutdown snapshot: whatever the recorder holds when
            # the process exits survives for the post-incident read
            try:
                path = svc.dump_trace(args.trace_dump)
                if path:
                    print(f"trace dump written to {path}", flush=True)
            except OSError as e:
                print(f"trace dump failed: {e}", flush=True)
        svc.close()
    return 0


def _worker_main(argv) -> int:
    """``worker`` subcommand: one remote replica of a cross-host
    serving fleet (serve/net.py).  Connects back to a router started
    with ``serve --hosts``, receives the deploy payload over the wire,
    builds + primes the applier (the same cold-start ladder the
    process fleet runs), and serves applies until the router says bye
    — reconnecting with bounded backoff through partitions, and
    self-fencing whenever its heartbeat lease lapses."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli worker",
        description="run one remote serving worker: connect to a "
        "router's registration listener, receive the model over TCP, "
        "prime, and serve under a heartbeat lease",
    )
    ap.add_argument(
        "--connect",
        required=True,
        metavar="HOST:PORT",
        help="the router's registration listener (printed by serve "
        "--hosts, or read service.listen_address)",
    )
    ap.add_argument(
        "--name",
        default=None,
        help="worker label in router logs/metrics (default "
        "<hostname>-<pid>)",
    )
    ap.add_argument(
        "--connect-attempts",
        type=int,
        default=30,
        help="bounded connect/reconnect retries (backoff+jitter) "
        "before giving up on an unreachable router",
    )
    ap.add_argument(
        "--backoff-seed",
        type=int,
        default=None,
        help="seed the reconnect jitter (reproducible drills)",
    )
    args = ap.parse_args(argv)
    from keystone_tpu.serve.net import run_worker

    return run_worker(
        args.connect,
        name=args.name,
        connect_attempts=args.connect_attempts,
        backoff_seed=args.backoff_seed,
    )


def _export_main(argv) -> int:
    """``export`` subcommand: freeze a saved fitted pipeline and write
    its AOT artifacts — the whole frozen apply lowered at every padding
    bucket and serialized with ``jax.export`` — either into a model
    registry version dir (``--model-dir``: the next ``serve``/watcher
    deploy of that version loads instead of compiling) or as a
    standalone bundle directory (``--out``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli export",
        description="freeze a saved model and publish pre-lowered AOT "
        "apply executables (jax.export) so serve cold start, hot-swap, "
        "and supervisor heals stop paying compile time",
    )
    ap.add_argument(
        "--model",
        default=None,
        help="path to a FittedPipeline saved via save()/fit_or_load(); "
        "with --model-dir the artifacts are published alongside it as "
        "a NEW registry version",
    )
    ap.add_argument(
        "--model-dir",
        default=None,
        metavar="DIR",
        help="model registry root: with --model, publish model + "
        "artifacts as a new version; without, export artifacts for the "
        "registry's CURRENT version in place",
    )
    ap.add_argument(
        "--example-shape",
        required=True,
        metavar="D0[,D1,...]",
        help="per-datum input shape (e.g. '64' or '3,32,32') the "
        "bucket programs are lowered for — must match what serve will "
        "receive",
    )
    ap.add_argument(
        "--dtype",
        default="float32",
        help="per-datum input dtype (default float32)",
    )
    ap.add_argument(
        "--max-batch",
        type=int,
        default=32,
        help="serve-side max_batch: buckets default to the same "
        "powers-of-two-up-to-max-batch the service pads with",
    )
    ap.add_argument(
        "--buckets",
        default=None,
        metavar="B0[,B1,...]",
        help="explicit padding-bucket sizes (overrides --max-batch)",
    )
    ap.add_argument(
        "--out",
        default=None,
        metavar="DIR",
        help="write the bundle to this directory instead of a registry "
        "(MANIFEST.json + one .hlo blob per bucket, BLAKE2b sidecars)",
    )
    ap.add_argument(
        "--plan",
        action="store_true",
        help="cost-based physical planning at freeze "
        "(keystone_tpu.planner): micro-profile candidate "
        "implementations on seeded sampling batches and ship the "
        "PhysicalPlan in the manifest — every install of this bundle "
        "serves the planned configuration (inspect: keystone plan)",
    )
    ap.add_argument(
        "--plan-seed",
        type=int,
        default=0,
        help="sampling seed for --plan (plan identity includes it)",
    )
    args = ap.parse_args(argv)
    if args.model is None and args.model_dir is None:
        ap.error("pass --model and/or --model-dir")
    if args.out is None and args.model_dir is None:
        ap.error("pass --out or --model-dir (somewhere to write artifacts)")

    import numpy as np

    from keystone_tpu.serve.service import default_buckets

    shape = tuple(int(d) for d in args.example_shape.split(","))
    example = np.zeros(shape, np.dtype(args.dtype))
    if args.buckets:
        buckets = tuple(int(b) for b in args.buckets.split(","))
    else:
        buckets = default_buckets(args.max_batch)

    registry = None
    version = None
    if args.model is not None:
        from keystone_tpu.workflow import FittedPipeline

        fitted = FittedPipeline.load(args.model)
    else:
        from keystone_tpu.serve import ModelRegistry

        registry = ModelRegistry(args.model_dir)
        fitted, version = registry.load()
    if args.plan:
        from keystone_tpu.planner import build_plan

        rng = np.random.default_rng(args.plan_seed)
        sample = rng.normal(size=(32,) + shape).astype(np.dtype(args.dtype))
        plan = build_plan(
            fitted, example=sample, max_batch=max(buckets),
            seed=args.plan_seed,
        )
        frozen = fitted.freeze(plan=plan)
        print(f"planned: {plan.fingerprint()} (keystone plan to inspect)")
    else:
        frozen = fitted.freeze()
    bundle = frozen.export_artifacts(example=example, buckets=buckets)
    ents = bundle["manifest"]["entries"]
    n_cache = sum(
        1 for e in ents.values() if e.get("kind") == "compile_cache"
    )
    n = len(bundle["blobs"]) - n_cache
    if n_cache:
        print(
            f"captured {n_cache} persistent-compile-cache entr"
            f"{'y' if n_cache == 1 else 'ies'} (pre-seeded backend "
            "compiles ship with the bundle)"
        )
    if args.model_dir is not None:
        from keystone_tpu.serve import ModelRegistry

        registry = registry or ModelRegistry(args.model_dir)
        if version is None:
            version = registry.publish(fitted, artifacts=bundle)
            print(
                f"published {version} (+{n} AOT bucket programs) to "
                f"{args.model_dir}"
            )
        else:
            registry.publish_artifacts(version, bundle)
            print(
                f"wrote {n} AOT bucket programs for existing version "
                f"{version} in {args.model_dir}"
            )
    if args.out is not None:
        from keystone_tpu.serve.registry import write_artifact_bundle

        write_artifact_bundle(args.out, bundle, describe="export bundle")
        print(f"wrote bundle ({n} bucket programs) to {args.out}")
    man = bundle["manifest"]
    print(
        f"buckets={man['buckets']} item_shape={tuple(man['item_shape'])} "
        f"dtype={man['dtype']} jax={man['jax_version']} "
        f"platforms={man['platforms']} signature={man['signature']}"
    )
    return 0


def _check_main(argv) -> int:
    """``check`` subcommand: run the pre-flight static analyzer
    (``keystone_tpu.analysis``) over a bundled pipeline (assembled on
    tiny synthetic data) or a saved fitted model, print findings with
    graph locations, and exit non-zero when any error-severity finding
    is present — the cheap gate to run before committing a long fit or
    bringing up a serve fleet."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli check",
        description="static pre-flight analysis: shape/dtype propagation, "
        "solver precision lint, robustness-config lint, signature audit",
    )
    ap.add_argument(
        "pipeline",
        nargs="?",
        help="bundled pipeline name (see --list); mutually exclusive "
        "with --model",
    )
    ap.add_argument(
        "--model",
        help="path to a FittedPipeline saved via save()/fit_or_load(); "
        "analyzed in apply mode (the freeze/serve contract)",
    )
    ap.add_argument(
        "--example-shape",
        default=None,
        metavar="D0[,D1,...]",
        help="per-datum input shape seeding shape propagation from the "
        "open source (with --model; bundled pipelines derive it from "
        "their synthetic training data)",
    )
    ap.add_argument(
        "--deadline",
        type=float,
        default=None,
        help="intended fit/apply deadline (seconds): enables the "
        "deadline-feasibility estimate against profiled stage costs",
    )
    ap.add_argument(
        "--dot",
        metavar="OUT",
        default=None,
        help="write a Graphviz DOT of the graph with findings overlaid "
        "(red = error, yellow = warning)",
    )
    ap.add_argument(
        "--no-solver-lint",
        action="store_true",
        help="skip the precision pass (solver jaxpr tracing)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    args = ap.parse_args(argv)
    if bool(args.pipeline) == bool(args.model):
        ap.error("pass exactly one of <PipelineName> or --model")

    from keystone_tpu.analysis import ALL_PASSES, DEFAULT_PASSES, analyze

    mode = "fit"
    if args.model:
        from keystone_tpu.workflow import FittedPipeline

        pipe = FittedPipeline.load(args.model)
        example = None
        if args.example_shape:
            example = tuple(int(d) for d in args.example_shape.split(","))
        mode = "apply"
    else:
        from keystone_tpu.analysis.bundled import build_bundled

        try:
            pipe, example = build_bundled(args.pipeline)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
    passes = (
        DEFAULT_PASSES + ("plan",) if args.no_solver_lint else ALL_PASSES
    )
    report = analyze(
        pipe,
        example=example,
        deadline=args.deadline,
        passes=passes,
        mode=mode,
    )
    if args.json:
        import json

        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.render())
    if args.dot:
        from keystone_tpu.workflow.viz import to_dot

        with open(args.dot, "w") as f:
            f.write(to_dot(pipe.graph, findings=report.findings))
        print(f"wrote findings overlay to {args.dot}")
    return 0 if report.ok else 1


def _plan_main(argv) -> int:
    """``plan`` subcommand: inspect (or build) a cost-based
    ``PhysicalPlan`` — per-stage candidates, sampled costs, the chosen
    winner and why, and the serving knobs (``keystone_tpu.planner``).
    Reads the plan a published registry version or exported bundle
    ships in its manifest, a raw ``plan.json``, or builds one fresh by
    sampling a saved fitted model."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli plan",
        description="show or build the cost-based physical plan that "
        "ships with a model: candidate implementations, sampled cost "
        "curves, winners, and serving knobs",
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--model-dir",
        metavar="DIR",
        help="model registry root: read the plan the CURRENT (or "
        "--version) version's artifact manifest ships",
    )
    src.add_argument(
        "--bundle",
        metavar="DIR",
        help="exported artifact bundle directory (MANIFEST.json)",
    )
    src.add_argument(
        "--file", metavar="PLAN.json", help="a raw serialized plan file"
    )
    src.add_argument(
        "--model",
        metavar="MODEL.pkl",
        help="build a plan NOW by sampling this saved fitted pipeline "
        "(needs --example-shape)",
    )
    ap.add_argument(
        "--version",
        default=None,
        help="registry version (with --model-dir; default CURRENT)",
    )
    ap.add_argument(
        "--example-shape",
        default=None,
        metavar="D0[,D1,...]",
        help="per-datum input shape for --model sampling batches",
    )
    ap.add_argument(
        "--dtype", default="float32", help="--model sampling dtype"
    )
    ap.add_argument(
        "--seed", type=int, default=0, help="--model sampling seed"
    )
    ap.add_argument(
        "--out",
        default=None,
        metavar="PLAN.json",
        help="write the (read or built) plan to this file",
    )
    ap.add_argument(
        "--explain",
        action="store_true",
        help="full explain: every candidate's samples, fitted curve, "
        "cost at the serving batch, and the winner's why",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the plan dict as JSON"
    )
    args = ap.parse_args(argv)

    import json

    from keystone_tpu.planner import PhysicalPlan, build_plan

    plan = None
    if args.file:
        with open(args.file) as f:
            plan = PhysicalPlan.from_dict(json.load(f))
    elif args.bundle:
        with open(os.path.join(args.bundle, "MANIFEST.json")) as f:
            manifest = json.load(f).get("manifest") or {}
        if manifest.get("plan") is None:
            print("bundle ships no plan (exported without planning)",
                  file=sys.stderr)
            return 1
        plan = PhysicalPlan.from_dict(manifest["plan"])
    elif args.model_dir:
        from keystone_tpu.serve import ModelRegistry

        reg = ModelRegistry(args.model_dir)
        version = args.version or (reg.versions() or [None])[-1]
        if version is None:
            print(f"no versions published in {args.model_dir}",
                  file=sys.stderr)
            return 1
        bundle = reg.load_artifacts(version)
        plan_dict = ((bundle or {}).get("manifest") or {}).get("plan")
        if plan_dict is None:
            print(f"version {version} ships no plan", file=sys.stderr)
            return 1
        plan = PhysicalPlan.from_dict(plan_dict)
    else:
        if not args.example_shape:
            ap.error("--model needs --example-shape for sampling batches")
        import numpy as np

        from keystone_tpu.workflow import FittedPipeline

        shape = tuple(int(d) for d in args.example_shape.split(","))
        rng = np.random.default_rng(args.seed)
        example = rng.normal(size=(32,) + shape).astype(np.dtype(args.dtype))
        fitted = FittedPipeline.load(args.model)
        plan = build_plan(fitted, example=example, seed=args.seed)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(plan.to_dict(), f, indent=2, sort_keys=True)
        print(f"wrote plan {plan.fingerprint()} to {args.out}")
    if args.json:
        print(json.dumps(plan.to_dict(), indent=2, sort_keys=True))
    elif args.explain:
        print(plan.explain())
    else:
        print(
            f"plan {plan.fingerprint()}  backend={plan.backend} "
            f"source={plan.source} stages={len(plan.stages)}"
        )
        for s in plan.stages:
            print(f"  {s.gate}: {s.winner}  ({s.why})")
        for k in sorted(plan.knobs):
            print(f"  knob {k} = {plan.knobs[k]}")
        print("(--explain for candidates, sampled costs, and fits)")
    problems = plan.validate()
    for code, msg in problems:
        print(f"WARNING [{code}] {msg}", file=sys.stderr)
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("--list", "-l", "--help", "-h"):
        print("usage: python -m keystone_tpu.cli <PipelineName> [flags]")
        print("       python -m keystone_tpu.cli serve --model model.pkl [flags]")
        print("       python -m keystone_tpu.cli worker --connect HOST:PORT [flags]")
        print("       python -m keystone_tpu.cli export --model model.pkl --example-shape D0[,D1,...] [flags]")
        print("       python -m keystone_tpu.cli check <PipelineName>|--model model.pkl [flags]")
        print("       python -m keystone_tpu.cli plan --model-dir DIR|--bundle DIR|--file plan.json|--model model.pkl [flags]")
        print("pipelines:")
        for name in _PIPELINE_MODULES:
            print(f"  {name}")
        return 0
    name, rest = argv[0], argv[1:]
    if name == "check":
        _apply_platform_env()
        return _check_main(rest)
    if name == "plan":
        _apply_platform_env()
        return _plan_main(rest)
    if name == "serve":
        _apply_platform_env()
        from keystone_tpu.utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()
        return _serve_main(rest)
    if name == "worker":
        _apply_platform_env()
        from keystone_tpu.utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()
        return _worker_main(rest)
    if name == "export":
        _apply_platform_env()
        from keystone_tpu.utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()
        return _export_main(rest)
    if name not in _PIPELINE_MODULES:
        print(f"unknown pipeline {name!r}; use --list", file=sys.stderr)
        return 2
    # only now touch jax: --list/--help/typos shouldn't pay the import
    _apply_platform_env()
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    state_dir = os.environ.get("KEYSTONE_STATE_DIR")
    if state_dir:
        # saved-prefix reload (workflow/state.py SavedStateLoadRule):
        # loader datasets are named, so featurized prefixes persisted by
        # save_pipeline_state in an earlier process are reused here
        from keystone_tpu.workflow import PipelineEnv

        PipelineEnv.state_dir = state_dir
    mod = importlib.import_module(_PIPELINE_MODULES[name])
    mod.main(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
