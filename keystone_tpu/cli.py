"""CLI dispatcher — the bin/run-pipeline.sh analogue.

    python -m keystone_tpu.cli <PipelineName> [pipeline flags...]
    python -m keystone_tpu.cli serve --model model.pkl [serve flags...]
    python -m keystone_tpu.cli --list
"""

from __future__ import annotations

import importlib
import os
import sys

_PIPELINE_MODULES = {
    "MnistRandomFFT": "keystone_tpu.pipelines.mnist_random_fft",
    "LinearPixels": "keystone_tpu.pipelines.linear_pixels",
    "RandomPatchCifar": "keystone_tpu.pipelines.random_patch_cifar",
    "NewsgroupsPipeline": "keystone_tpu.pipelines.newsgroups",
    "TimitPipeline": "keystone_tpu.pipelines.timit",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
    "VOCSIFTFisher": "keystone_tpu.pipelines.voc_sift_fisher",
    "AmazonReviewsPipeline": "keystone_tpu.pipelines.amazon_reviews",
}


def _apply_platform_env() -> None:
    """Honor KEYSTONE_PLATFORM before any backend is initialized.

    Some environments force a platform programmatically at interpreter
    start (overriding JAX_PLATFORMS), so the launcher's env var must be
    re-applied through jax.config here."""
    platform = os.environ.get("KEYSTONE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def _serve_main(argv) -> int:
    """``serve`` subcommand: load a saved fitted pipeline and expose it
    over HTTP (POST /predict, GET /healthz, GET /metrics) through the
    micro-batching service (keystone_tpu/serve)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m keystone_tpu.cli serve",
        description="serve a saved fitted pipeline over HTTP with "
        "dynamic micro-batching and admission control",
    )
    ap.add_argument(
        "--model",
        required=True,
        help="path to a FittedPipeline saved via save()/fit_or_load()",
    )
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument(
        "--max-wait-ms",
        type=float,
        default=5.0,
        help="flush the micro-batch when the oldest request has waited "
        "this long (or when --max-batch requests are queued)",
    )
    ap.add_argument(
        "--queue-bound",
        type=int,
        default=128,
        help="admission control: reject (HTTP 429) past this queue depth",
    )
    ap.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="default per-request deadline; doomed requests are shed "
        "(HTTP 504) instead of executed",
    )
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--example-shape",
        default=None,
        metavar="D0[,D1,...]",
        help="per-datum input shape (e.g. '24' or '3,32,32'): primes "
        "every padding bucket's compiled program BEFORE serving, so no "
        "request ever pays a trace+compile against its deadline.  "
        "Without it the first request per bucket compiles in-band.",
    )
    args = ap.parse_args(argv)

    from keystone_tpu.serve import HttpFrontend, serve
    from keystone_tpu.workflow import FittedPipeline

    fitted = FittedPipeline.load(args.model)
    example = None
    if args.example_shape:
        import numpy as np

        shape = tuple(int(d) for d in args.example_shape.split(","))
        example = np.zeros(shape, np.float32)
    svc = serve(
        fitted,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_bound=args.queue_bound,
        deadline_ms=args.deadline_ms,
        example=example,
    )
    front = HttpFrontend(svc, host=args.host, port=args.port)
    print(
        f"serving {args.model} on http://{args.host}:{front.port} "
        f"(max_batch={args.max_batch}, max_wait_ms={args.max_wait_ms}, "
        f"queue_bound={args.queue_bound})",
        flush=True,
    )
    try:
        front.serve_forever()
    except KeyboardInterrupt:
        print("shutting down (draining in-flight requests)", flush=True)
    finally:
        front.server.server_close()
        svc.close()
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("--list", "-l", "--help", "-h"):
        print("usage: python -m keystone_tpu.cli <PipelineName> [flags]")
        print("       python -m keystone_tpu.cli serve --model model.pkl [flags]")
        print("pipelines:")
        for name in _PIPELINE_MODULES:
            print(f"  {name}")
        return 0
    name, rest = argv[0], argv[1:]
    if name == "serve":
        _apply_platform_env()
        from keystone_tpu.utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()
        return _serve_main(rest)
    if name not in _PIPELINE_MODULES:
        print(f"unknown pipeline {name!r}; use --list", file=sys.stderr)
        return 2
    # only now touch jax: --list/--help/typos shouldn't pay the import
    _apply_platform_env()
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    state_dir = os.environ.get("KEYSTONE_STATE_DIR")
    if state_dir:
        # saved-prefix reload (workflow/state.py SavedStateLoadRule):
        # loader datasets are named, so featurized prefixes persisted by
        # save_pipeline_state in an earlier process are reused here
        from keystone_tpu.workflow import PipelineEnv

        PipelineEnv.state_dir = state_dir
    mod = importlib.import_module(_PIPELINE_MODULES[name])
    mod.main(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
