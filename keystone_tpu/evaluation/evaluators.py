"""Evaluators (reference src/main/scala/evaluation/).

Metric math runs on device as one jitted reduction over the row-sharded
prediction/label arrays (confusion matrix via a one-hot einsum — the
treeAggregate analogue), then small summaries come back to host.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.dataset import Dataset, as_dataset


@dataclasses.dataclass
class MulticlassMetrics:
    """evaluation/MulticlassClassifierEvaluator.scala § MulticlassMetrics."""

    confusion_matrix: np.ndarray  # (K, K) rows = actual, cols = predicted
    total_error: float
    per_class_error: np.ndarray
    macro_precision: float
    macro_recall: float
    macro_f1: float
    micro_f1: float

    @property
    def accuracy(self) -> float:
        return 1.0 - self.total_error

    def summary(self) -> str:
        return (
            f"accuracy: {self.accuracy:.4f}\n"
            f"total error: {self.total_error:.4f}\n"
            f"macro F1: {self.macro_f1:.4f}  micro F1: {self.micro_f1:.4f}"
        )


class MulticlassClassifierEvaluator:
    """Confusion matrix, total/per-class error, micro/macro F1
    (evaluation/MulticlassClassifierEvaluator.scala)."""

    def __init__(self, num_classes: int):
        self.num_classes = int(num_classes)

    def evaluate(self, predictions, labels) -> MulticlassMetrics:
        pred = _as_int_array(predictions)
        lab = _as_int_array(labels)
        n = min(pred.shape[0], lab.shape[0])
        cm = np.asarray(_confusion(jnp.asarray(pred[:n]), jnp.asarray(lab[:n]), self.num_classes))
        return _metrics_from_confusion(cm)


def _metrics_from_confusion(cm: np.ndarray) -> MulticlassMetrics:
    cm = np.rint(np.asarray(cm)).astype(np.int64)  # device one-hot sums are f32
    total = cm.sum()
    correct = np.trace(cm)
    class_counts = cm.sum(axis=1)  # actual
    pred_counts = cm.sum(axis=0)
    tp = np.diag(cm).astype(np.float64)
    per_class_error = np.where(
        class_counts > 0, 1.0 - tp / np.maximum(class_counts, 1), 0.0
    )
    prec = np.where(pred_counts > 0, tp / np.maximum(pred_counts, 1), 0.0)
    rec = np.where(class_counts > 0, tp / np.maximum(class_counts, 1), 0.0)
    f1 = np.where(prec + rec > 0, 2 * prec * rec / np.maximum(prec + rec, 1e-12), 0.0)
    micro_p = correct / max(total, 1)
    return MulticlassMetrics(
        confusion_matrix=cm,
        total_error=float(1.0 - correct / max(total, 1)),
        per_class_error=per_class_error,
        macro_precision=float(prec.mean()),
        macro_recall=float(rec.mean()),
        macro_f1=float(f1.mean()),
        micro_f1=float(micro_p),  # micro P=R=F1=accuracy for single-label
    )


@partial(jax.jit, static_argnames=("k",))
def _confusion(pred, lab, k):
    po = jax.nn.one_hot(pred, k)
    lo = jax.nn.one_hot(lab, k)
    return lo.T @ po


@dataclasses.dataclass
class BinaryClassificationMetrics:
    """evaluation/BinaryClassifierEvaluator.scala."""

    tp: int
    fp: int
    tn: int
    fn: int

    @property
    def accuracy(self):
        t = self.tp + self.fp + self.tn + self.fn
        return (self.tp + self.tn) / max(t, 1)

    @property
    def precision(self):
        return self.tp / max(self.tp + self.fp, 1)

    @property
    def recall(self):
        return self.tp / max(self.tp + self.fn, 1)

    @property
    def f1(self):
        p, r = self.precision, self.recall
        return 2 * p * r / max(p + r, 1e-12)


class BinaryClassifierEvaluator:
    def evaluate(self, predictions, labels) -> BinaryClassificationMetrics:
        pred = _as_int_array(predictions) > 0
        lab = _as_int_array(labels) > 0
        n = min(pred.shape[0], lab.shape[0])
        pred, lab = pred[:n], lab[:n]
        return BinaryClassificationMetrics(
            tp=int(np.sum(pred & lab)),
            fp=int(np.sum(pred & ~lab)),
            tn=int(np.sum(~pred & ~lab)),
            fn=int(np.sum(~pred & lab)),
        )


class MeanAveragePrecisionEvaluator:
    """VOC-style mean average precision over per-class rankings
    (evaluation/MeanAveragePrecisionEvaluator.scala): AP computed with the
    11-point-free 'every positive rank' averaging the reference uses."""

    def __init__(self, num_classes: int):
        self.num_classes = int(num_classes)

    def evaluate(self, scores, multilabels) -> float:
        """scores: (n, K) class scores; multilabels: (n, K) 0/1."""
        s = np.asarray(_maybe_numpy(scores), np.float64)
        y = np.asarray(_maybe_numpy(multilabels)) > 0
        n = min(s.shape[0], y.shape[0])
        s, y = s[:n], y[:n]
        aps = []
        for c in range(self.num_classes):
            order = np.argsort(-s[:, c], kind="stable")
            rel = y[order, c]
            if rel.sum() == 0:
                continue
            ranks = np.arange(1, n + 1)
            cum = np.cumsum(rel)
            precision_at = cum / ranks
            aps.append((precision_at * rel).sum() / rel.sum())
        return float(np.mean(aps)) if aps else 0.0


class AugmentedExamplesEvaluator:
    """Averages prediction scores across augmented views of each image id
    before scoring (evaluation/AugmentedExamplesEvaluator.scala — the
    ImageNet 10-view eval)."""

    def __init__(self, num_classes: int):
        self.num_classes = int(num_classes)

    @staticmethod
    def averaged_scores(scores, image_ids) -> tuple:
        """Mean score per image id.  Returns ``(agg, first_idx)`` where
        ``agg`` rows follow np.unique's sorted id order and ``first_idx``
        is each unique id's first view index (for label realignment).
        The single source of the view-aggregation logic — ``evaluate``
        and top-k consumers both derive from it."""
        s = np.asarray(_maybe_numpy(scores), np.float64)
        ids = np.asarray(_maybe_numpy(image_ids))
        uniq, first_idx, inverse = np.unique(
            ids, return_index=True, return_inverse=True
        )
        agg = np.zeros((uniq.shape[0], s.shape[1]))
        np.add.at(agg, inverse, s)
        counts = np.bincount(inverse, minlength=uniq.shape[0])[:, None]
        return agg / np.maximum(counts, 1), first_idx

    def evaluate(self, scores, image_ids, labels) -> MulticlassMetrics:
        """scores: (n_views_total, K); image_ids: (n_views_total,) group
        key per view; labels: per-image true class keyed by first
        occurrence order of image_ids."""
        labs = _as_int_array(labels)
        agg, first_idx = self.averaged_scores(scores, image_ids)
        pred = agg.argmax(axis=1)
        if labs.shape[0] == agg.shape[0]:
            # labs are per-image in FIRST-OCCURRENCE order; np.unique's uniq
            # is sorted — realign by each unique id's occurrence rank
            occ_order = np.argsort(first_idx)
            lab_per_img = np.empty_like(labs)
            lab_per_img[occ_order] = labs
        else:
            # labs are per-view: take each image's first view's label
            lab_per_img = labs[first_idx]
        cm = np.asarray(
            _confusion(jnp.asarray(pred), jnp.asarray(lab_per_img), self.num_classes)
        )
        return _metrics_from_confusion(cm)


def _maybe_numpy(x):
    if isinstance(x, Dataset):
        return x.numpy()
    if hasattr(x, "get"):
        return x.get().numpy()
    return np.asarray(x)


def _as_int_array(x) -> np.ndarray:
    arr = np.asarray(_maybe_numpy(x))
    if arr.ndim > 1:
        arr = arr.argmax(axis=-1) if arr.shape[-1] > 1 else arr.ravel()
    return arr.astype(np.int64)
