from keystone_tpu.evaluation.evaluators import (  # noqa: F401
    AugmentedExamplesEvaluator,
    BinaryClassificationMetrics,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
