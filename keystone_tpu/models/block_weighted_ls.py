"""Class-weighted block coordinate descent least squares.

Reference: nodes/learning/BlockWeightedLeastSquares.scala — the solver
behind the TIMIT and ImageNet-FV pipelines.  It rebalances skewed class
distributions by giving each example a weight blending a balanced
per-class term with a uniform term, controlled by ``mixture_weight``:

    α_i = mixture_weight · n/(K·n_c(i)) + (1 − mixture_weight)

(α has mean 1: mixture_weight=0 is plain least squares; 1 weights every
class's total contribution equally).  The fit solves the weighted ridge
normal equations blockwise, Gauss–Seidel over feature blocks, with
weighted mean-centering providing the intercept.

TPU form mirrors block_ls.py: one jitted scan-over-epochs /
fori-over-blocks program; weighted Gramians contract over the row-sharded
axis (all-reduce over ICI); the class axis shards over 'model'.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.block_ls import BlockLinearMapper, blockify
from keystone_tpu.models.common import constrain, solve_spd
from keystone_tpu.parallel.collectives import sharded_gram, sharded_matmul
from jax.sharding import PartitionSpec as P
from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator


@jax.jit
def class_weights(y: jnp.ndarray, n, mixture_weight: float):
    """Per-example weights from ±1 one-hot label matrix (n_rows, K).

    Class of row i = argmax of the one-hot; padding rows get weight 0.
    ONE jitted program: eager, this chain dispatched ~18 tiny programs
    per fit (argmax/one_hot/reduce/gather/...), each a ~0.1 s
    compile-cache RPC on the tunneled backend (r5 fit-floor call-site
    attribution).
    """
    n_rows, k = y.shape
    cls = jnp.argmax(y, axis=1)
    onehot = jax.nn.one_hot(cls, k, dtype=jnp.float32)
    counts = jnp.sum(onehot * (y.max(axis=1, keepdims=True) > 0), axis=0)
    counts = jnp.maximum(counts, 1.0)
    balanced = n / (k * counts[cls])
    alpha = mixture_weight * balanced + (1.0 - mixture_weight)
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    return alpha * row_ok


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    # class-level default for pre-spill_dtype pickles
    spill_dtype = "float32"

    def __init__(
        self,
        block_size: int = 4096,
        num_iter: int = 1,
        lam: float = 0.0,
        mixture_weight: float = 0.5,
        fit_intercept: bool = True,
        spill_dtype: str = "float32",
    ):
        self.block_size = int(block_size)
        self.num_iter = int(num_iter)
        self.lam = float(lam)
        self.mixture_weight = float(mixture_weight)
        self.fit_intercept = fit_intercept
        #: out-of-core spill precision: "bfloat16" halves disk + wire
        #: bytes per sweep (a bandwidth lever — utils/precision.py);
        #: solver math stays f32 either way
        self.spill_dtype = str(spill_dtype)

    def params(self):
        return (
            self.block_size,
            self.num_iter,
            self.lam,
            self.mixture_weight,
            self.fit_intercept,
            self.spill_dtype,
        )

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("BlockWeightedLeastSquaresEstimator requires labels")
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            if data.is_host:
                raise TypeError(
                    "host-payload stream reached a block solver; "
                    "featurize to arrays (or CSR) before the fit"
                )
            return self.fit_stream_dataset(data, labels)
        return self._fit(data.array, labels.array, data.n)

    def fit_stream_dataset(
        self, data, labels, spill_dir=None, checkpoint_dir=None, prefetch=None
    ) -> BlockLinearMapper:
        """Out-of-core weighted fit: spill streamed features to a block
        store, then sweep blocks from disk (see block_ls._oc_bcd_fit).
        ``prefetch`` — block read-ahead depth (None →
        ``KEYSTONE_OC_PREFETCH``, else 2).  The spill directory is
        deleted after a successful fit."""
        import shutil

        from keystone_tpu.models.block_ls import _spill_dir
        from keystone_tpu.workflow.blockstore import FeatureBlockStore

        store = FeatureBlockStore.from_batches(
            _spill_dir(spill_dir),
            data.batches(),
            data.n,
            self.block_size,
            dtype=self.spill_dtype,
        )
        fitted = self.fit_store(
            store, labels, checkpoint_dir=checkpoint_dir, prefetch=prefetch
        )
        shutil.rmtree(store.directory, ignore_errors=True)
        return fitted

    def fit_store(
        self, store, labels, checkpoint_dir=None, prefetch=None
    ) -> BlockLinearMapper:
        """Weighted out-of-core fit.  Rides block_ls._oc_bcd_fit, so the
        async double-buffered device feed (blockstore.iter_device_blocks)
        and the donated per-block carry (_oc_block_step donates p and
        w_b; the staged block frees by refcount) apply to the weighted
        sweep too."""
        from keystone_tpu.models.block_ls import (
            _check_store_rows,
            _oc_bcd_fit,
            finish_block_model,
        )
        from keystone_tpu.workflow.dataset import as_dataset

        labels = as_dataset(labels)
        _check_store_rows(store, labels)
        y = labels.array.astype(jnp.float32)
        alpha = class_weights(y, jnp.float32(labels.n), self.mixture_weight)
        weights, xm, ym = _oc_bcd_fit(
            store,
            y,
            alpha,
            float(labels.n),
            self.lam,
            self.num_iter,
            self.fit_intercept,
            checkpoint_dir=checkpoint_dir,
            prefetch=prefetch,
        )
        return finish_block_model(
            weights, xm, ym, store.d, self.block_size, self.fit_intercept
        )

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n) -> BlockLinearMapper:
        from keystone_tpu.obs import ledger

        x = jnp.asarray(x, jnp.float32)
        y = jnp.asarray(y, jnp.float32)
        nf = jnp.float32(n)
        alpha = class_weights(y, nf, self.mixture_weight)
        weights, xm, ym = _weighted_bcd_fit(
            x, y, alpha, nf, self.lam, self.num_iter, self.block_size,
            self.fit_intercept, obs=ledger.solver_obs(),
        )
        # obs-gated sync: charge the solve's wall wait to the ledger's
        # device-busy account (inert without an active run)
        weights = ledger.device_wait(weights)
        from keystone_tpu.models.block_ls import finish_block_model

        return finish_block_model(
            weights, xm, ym, x.shape[1], self.block_size, self.fit_intercept
        )


@partial(
    jax.jit,
    static_argnames=("num_iter", "block_size", "fit_intercept", "obs"),
)
def _weighted_bcd_fit(
    x, y, alpha, n, lam, num_iter, block_size, fit_intercept, obs=False
):
    wsum = jnp.sum(alpha)
    if fit_intercept:
        xm = (alpha @ x) / wsum
        ym = (alpha @ y) / wsum
        row_ok = (alpha > 0).astype(jnp.float32)[:, None]
        xc = (x - xm) * row_ok
        yc = (y - ym) * row_ok
    else:
        xm = jnp.zeros((x.shape[1],), jnp.float32)
        ym = jnp.zeros((y.shape[1],), jnp.float32)
        xc, yc = x, y

    xb = blockify(xc, block_size)  # (nb, n_rows, bs)
    nb, n_rows, bs = xb.shape
    k = yc.shape[1]
    xb = constrain(xb, None, DATA_AXIS, None)
    yc = constrain(yc, DATA_AXIS, MODEL_AXIS)
    sa = jnp.sqrt(alpha)

    w0 = jnp.zeros((nb, bs, k), jnp.float32)
    p0 = jnp.zeros_like(yc)

    def block_step(b, carry):
        w, p = carry
        a = xb[b] * sa[:, None]  # √α-scaled block: AᵀA = XᵀDX
        wb = w[b]
        target = (yc - p) * sa[:, None] + a @ wb
        ata = sharded_gram(a)
        atr = sharded_matmul(a, target, out_spec=P(None, MODEL_AXIS))
        wb_new = solve_spd(ata, atr, reg=lam * n)
        p_new = constrain(p + xb[b] @ (wb_new - wb), DATA_AXIS, MODEL_AXIS)
        return w.at[b].set(wb_new), p_new

    def epoch(carry, e):
        carry = lax.fori_loop(0, nb, block_step, carry)
        if obs:
            # per-epoch convergence point for the run ledger (static
            # flag: the inert program carries no callback — see
            # block_ls._bcd_fit)
            from keystone_tpu.obs import ledger

            _, p = carry
            r = yc - p
            jax.debug.callback(
                ledger.solver_callback(
                    "bcd.weighted", "epoch", "objective"
                ),
                e,
                0.5 * jnp.vdot(r, r) / n,
            )
        return carry, None

    # xs only when observing — the inert program stays byte-identical
    # to the pre-obs one (see models/kmeans.py)
    if obs:
        (w, _), _ = lax.scan(epoch, (w0, p0), jnp.arange(num_iter))
    else:
        (w, _), _ = lax.scan(epoch, (w0, p0), None, length=num_iter)
    return w, xm, ym
