"""Kernel ridge regression by block coordinate descent.

Reference [fork]: nodes/learning/KernelRidgeRegression.scala,
KernelBlockLinearMapper.scala, KernelMatrix.scala § BlockKernelMatrix and
KernelGenerator § GaussianKernelGenerator — Stephen Tu's block
Gauss–Seidel KRR (arXiv:1602.05310): kernel-matrix column blocks are
materialized (cached RDDs) and the dual coefficients are swept blockwise:

    α_b ← (K_bb + λnI)⁻¹ (Y_b − F_b + K_bb α_b),   F = K·α

TPU form: kernel blocks are computed on the fly from row-sharded X with
the ‖x−z‖² gemm expansion (never materializing the full n×n K), the block
solve runs replicated, and F updates contract over ICI.  The whole
multi-epoch sweep is one jitted program.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain, solve_spd
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import sdot


@dataclasses.dataclass(frozen=True)
class GaussianKernelGenerator:
    """K(x, z) = exp(−γ‖x−z‖²) via the gemm expansion
    (KernelGenerator.scala § GaussianKernelGenerator)."""

    gamma: float
    #: solver-grade (true f32) MXU passes for the distance gemm.  True
    #: during fits — the kernel values enter the block solves — but
    #: predict-time generators use default precision: inference has no
    #: downstream solve and the full-precision passes cost ~2×.
    solver_grade: bool = True

    def __call__(self, x: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
        xn = jnp.sum(x * x, axis=1, keepdims=True)
        zn = jnp.sum(z * z, axis=1)
        if self.solver_grade:
            cross = sdot(x, z.T)
        else:
            cross = jnp.matmul(x, z.T, preferred_element_type=jnp.float32)
        sq = jnp.maximum(xn - 2.0 * cross + zn, 0.0)
        return jnp.exp(-self.gamma * sq)


@dataclasses.dataclass(frozen=True)
class LinearKernelGenerator:
    """K(x, z) = x·zᵀ (KernelGenerator.scala's linear kernel).  Routed
    through the ``ops/gram_pallas`` dispatcher by
    :class:`~keystone_tpu.models.kernel_matrix.BlockKernelMatrix` like
    the Gaussian generator — one fused f32-accumulated MXU pass on
    Pallas-capable backends, this exact chain (bit-identical)
    everywhere else."""

    solver_grade: bool = True

    def __call__(self, x: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
        if self.solver_grade:
            return sdot(x, z.T)
        return jnp.matmul(x, z.T, preferred_element_type=jnp.float32)


@dataclasses.dataclass(frozen=True)
class PolynomialKernelGenerator:
    """K(x, z) = (α·x·zᵀ + c)^degree — the polynomial kernel, gemm
    expansion form.  ``degree`` is a static int (one fit = one degree =
    one compile, the ``gamma`` discipline).  Dispatcher-routed like the
    Gaussian/linear generators: the Pallas megakernel fuses the gemm
    with the affine+power epilogue in VMEM; the XLA fallback IS this
    ``__call__`` (bit-identical by construction)."""

    degree: int = 2
    alpha: float = 1.0
    c: float = 1.0
    solver_grade: bool = True

    def __call__(self, x: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
        if self.solver_grade:
            cross = sdot(x, z.T)
        else:
            cross = jnp.matmul(x, z.T, preferred_element_type=jnp.float32)
        return (self.alpha * cross + self.c) ** int(self.degree)


class KernelBlockLinearMapper(Transformer):
    """Predicts K(x_test, X_train)·α, streaming over train blocks so the
    test×train kernel never fully materializes
    (KernelBlockLinearMapper.scala)."""

    def __init__(self, kernel_gen, train_x, alpha, block_size: int, train_n: int):
        self.kernel_gen = kernel_gen
        self.train_x = train_x  # (n_rows, d), padded
        self.alpha = alpha  # (n_rows, k); zero on padding rows
        self.block_size = int(block_size)
        self.train_n = int(train_n)

    def apply_batch(self, xs, mask=None):
        return _krr_predict(
            xs, self.train_x, self.alpha, self.kernel_gen.gamma, self.block_size
        )

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class KernelRidgeRegressionEstimator(LabelEstimator):
    """``cache_kernel_blocks`` reproduces the reference's cached-RDD
    kernel column blocks (KernelMatrix.scala § BlockKernelMatrix): the
    fit sweeps through a BlockKernelMatrix LRU, so epochs ≥ 2 reread
    cached blocks (n² HBM) instead of recomputing the ‖x−z‖² gemms.
    Measured on v5 lite (BASELINE.md "KRR kernel-block cache"): the
    recompute sweep wins below d≈2·10³ (~4× at d=64, ~1.3× at d=1024) —
    the MXU regenerates blocks faster than HBM rereads them while the
    gemm is small — so recompute stays the default; caching wins for
    wide features (~2.2× at d=4096, n=8k) when K fits HBM."""

    # class-level default for pre-option pickles
    kernel_cache_dir = None

    def __init__(
        self,
        kernel_gen: GaussianKernelGenerator,
        lam: float = 1e-3,
        block_size: int = 1024,
        num_epochs: int = 1,
        cache_kernel_blocks: bool = False,
        kernel_cache_dir: Optional[str] = None,
    ):
        self.kernel_gen = kernel_gen
        self.lam = float(lam)
        self.block_size = int(block_size)
        self.num_epochs = int(num_epochs)
        self.cache_kernel_blocks = bool(cache_kernel_blocks)
        #: with cache_kernel_blocks, K beyond the HBM budget spills its
        #: column blocks here (the reference's executor-disk cached
        #: RDDs); None → a temp dir, deleted after the fit
        self.kernel_cache_dir = kernel_cache_dir

    def params(self):
        return (
            self.kernel_gen.gamma,
            self.lam,
            self.block_size,
            self.num_epochs,
            self.cache_kernel_blocks,
        )

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("KernelRidgeRegressionEstimator requires labels")
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            if data.is_host:
                raise TypeError(
                    "host-payload stream reached a kernel solver; "
                    "featurize to arrays before the fit"
                )
            return self.fit_stream_dataset(data, labels)
        return self._fit(data.array, labels.array, data.n)

    def fit_stream_dataset(
        self, data, labels, spill_dir=None, checkpoint_dir=None, prefetch=None
    ) -> "OutOfCoreKernelBlockLinearMapper":
        """Out-of-core fit: spill the streamed train rows to a
        :class:`~keystone_tpu.workflow.blockstore.RowBlockStore` once,
        then run the streamed gram-block BCD sweep from disk (the
        default path when a StreamDataset reaches this estimator
        through the DAG).

        Unlike the block least-squares spill, the row-block store BACKS
        THE FITTED MODEL — kernel prediction is K(x_test, X_train)·α,
        so the train rows are part of the model and the store is NOT
        deleted after the fit.  Pass ``spill_dir`` to choose where it
        lives (default: the PipelineEnv state dir, else a temp dir).

        ``prefetch`` — block read-ahead depth for the sweep (None →
        ``KEYSTONE_OC_PREFETCH`` env, else 2; the shared [1, 64] bound
        of :func:`~keystone_tpu.models.block_ls._oc_prefetch`)."""
        from keystone_tpu.models.block_ls import _spill_dir
        from keystone_tpu.obs import ledger
        from keystone_tpu.workflow.blockstore import RowBlockStore

        with ledger.span("solver.spill", solver="krr", n=data.n):
            store = RowBlockStore.from_batches(
                _spill_dir(spill_dir),
                data.batches(),
                data.n,
                self.block_size,
            )
        try:
            return self.fit_store(
                store, labels, checkpoint_dir=checkpoint_dir, prefetch=prefetch
            )
        except BaseException:
            # a failed SWEEP must not orphan the auto-created spill (a
            # crash-restart loop would accumulate one full dataset copy
            # per attempt — the retry re-spills, and checkpoint
            # fingerprints are content-based so resume still works).
            # An EXPLICIT spill_dir is user-owned: left for inspection.
            if spill_dir is None:
                import shutil

                shutil.rmtree(store.directory, ignore_errors=True)
            raise

    def fit_store(
        self, store, labels, checkpoint_dir=None, prefetch=None
    ) -> "OutOfCoreKernelBlockLinearMapper":
        """Fit from an existing RowBlockStore: the n×n kernel never
        materializes and the train matrix never fully resides in HBM —
        row blocks stream disk→host→device through
        ``blockstore.iter_device_blocks`` while the (α, F) carries are
        donated epoch-over-epoch (see :func:`_oc_krr_fit`).

        ``prefetch`` as in :meth:`fit_stream_dataset`.  With
        ``checkpoint_dir``, each completed epoch saves (α, F) through
        the shared durable helper and an interrupted fit resumes from
        the last epoch (corrupt newest falls back to last-good)."""
        from keystone_tpu.workflow.dataset import as_dataset

        labels = as_dataset(labels)
        if labels.n != store.n:
            raise ValueError(f"labels n={labels.n} != store n={store.n}")
        alpha = _oc_krr_fit(
            store,
            labels.array,
            float(labels.n),
            self.kernel_gen.gamma,
            self.lam,
            self.num_epochs,
            checkpoint_dir=checkpoint_dir,
            prefetch=prefetch,
        )
        return OutOfCoreKernelBlockLinearMapper(
            self.kernel_gen, store.directory, alpha, labels.n
        )

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        x = jnp.asarray(x, jnp.float32)
        y = jnp.asarray(y, jnp.float32)
        n_rows = x.shape[0]
        bs = self.block_size
        nb = -(-n_rows // bs)
        if nb * bs != n_rows:
            x = jnp.pad(x, ((0, nb * bs - n_rows), (0, 0)))
            y = jnp.pad(y, ((0, nb * bs - n_rows), (0, 0)))
        if self.cache_kernel_blocks:
            alpha = _krr_fit_cached(
                x,
                y,
                n,
                self.kernel_gen,
                self.lam,
                bs,
                self.num_epochs,
                cache_dir=self.kernel_cache_dir,
            )
        else:
            from keystone_tpu.obs import ledger

            # device_wait: obs-gated sync charging the solve to the
            # ledger's device-busy account (inert without a run)
            alpha = ledger.device_wait(
                _krr_fit(
                    x, y, jnp.float32(n), self.kernel_gen.gamma, self.lam,
                    bs, self.num_epochs, obs=ledger.solver_obs(),
                )
            )
        return KernelBlockLinearMapper(self.kernel_gen, x, alpha, bs, n)


@partial(jax.jit, static_argnames=("bs", "num_epochs", "obs"))
def _krr_fit(x, y, n, gamma, lam, bs, num_epochs, obs=False):
    """The in-core sweep as one XLA program.

    ``obs`` (static): emit a per-epoch ``solver.epoch`` convergence
    point (dual residual objective ½‖Y−F‖²/n) to the active run ledger
    via ``jax.debug.callback``.  Same math either way — the flag only
    adds the host callback, and is resolved at trace time so the inert
    program carries no callbacks at all (pinned byte-identical, like
    the other solvers)."""
    n_rows = x.shape[0]
    nb = n_rows // bs
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    x = constrain(x, DATA_AXIS)
    y = y * row_ok[:, None]
    kern = GaussianKernelGenerator(gamma)

    alpha0 = jnp.zeros_like(y)
    f0 = jnp.zeros_like(y)

    def block_step(b, carry):
        alpha, f = carry
        xb = lax.dynamic_slice_in_dim(x, b * bs, bs)
        ok_b = lax.dynamic_slice_in_dim(row_ok, b * bs, bs)
        # kernel column block K(:, b): (n_rows, bs); mask padding rows/cols
        kcol = kern(x, xb) * row_ok[:, None] * ok_b[None, :]
        kbb = lax.dynamic_slice_in_dim(kcol, b * bs, bs)
        # make the pad diagonal identity so the solve stays PD
        kbb = kbb + jnp.diag(1.0 - ok_b)
        ab = lax.dynamic_slice_in_dim(alpha, b * bs, bs)
        yb = lax.dynamic_slice_in_dim(y, b * bs, bs)
        fb = lax.dynamic_slice_in_dim(f, b * bs, bs)
        target = yb - fb + kbb @ ab
        ab_new = solve_spd(kbb, target, reg=lam * n) * ok_b[:, None]
        f_new = f + kcol @ (ab_new - ab)
        alpha_new = lax.dynamic_update_slice_in_dim(alpha, ab_new, b * bs, axis=0)
        return alpha_new, f_new

    def epoch(carry, e):
        carry = lax.fori_loop(0, nb, block_step, carry)
        if obs:
            from keystone_tpu.obs import ledger

            _, f = carry
            r = y - f
            jax.debug.callback(
                ledger.solver_callback("krr", "epoch", "objective"),
                e,
                0.5 * jnp.vdot(r, r) / n,
            )
        return carry, None

    # xs only when observing — the inert program stays byte-identical
    # to the pre-obs one (see models/kmeans.py)
    if obs:
        (alpha, _), _ = lax.scan(epoch, (alpha0, f0), jnp.arange(num_epochs))
    else:
        (alpha, _), _ = lax.scan(epoch, (alpha0, f0), None, length=num_epochs)
    return alpha


@jax.jit
def _cached_block_update(kcol, kbb, row_ok, ok_b, ab, yb, fb, lam_n):
    """One Gauss–Seidel block update from a PRE-COMPUTED kernel column
    block (same math as the inlined sweep in _krr_fit)."""
    kcol = kcol * row_ok[:, None] * ok_b[None, :]
    kbb = kbb * ok_b[:, None] * ok_b[None, :] + jnp.diag(1.0 - ok_b)
    target = yb - fb + kbb @ ab
    ab_new = solve_spd(kbb, target, reg=lam_n) * ok_b[:, None]
    return ab_new, kcol @ (ab_new - ab)


def _krr_fit_cached(x, y, n, kern, lam, bs, num_epochs, cache_dir=None):
    """Gauss–Seidel sweep through a BlockKernelMatrix LRU: kernel column
    blocks are computed once and REREAD on later epochs (the reference's
    cached-RDD strategy, KernelMatrix.scala).  Python-level block loop —
    the cache is a host-side structure — with each block update jitted.

    When K exceeds the HBM budget the cache goes TIERED: a partial HBM
    LRU backed by disk-persisted column blocks (the reference spilled
    cached RDDs to executor disk/memory the same way), so the cached
    mode no longer silently requires K ≲ HBM."""
    import shutil
    import tempfile

    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix
    from keystone_tpu.workflow.profiling import device_hbm_budget

    # fits always use solver-grade (true f32) kernel gemms, matching
    # _krr_fit — the cache flag must not silently relax solve numerics
    kern = dataclasses.replace(kern, solver_grade=True)
    n_rows = x.shape[0]
    nb = n_rows // bs
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    x = constrain(x, DATA_AXIS)  # kernel gemms contract over the data axis
    y = jnp.asarray(y, jnp.float32) * row_ok[:, None]
    k_bytes = n_rows * n_rows * 4
    budget = device_hbm_budget(0.5)
    tmp_dir = None
    if k_bytes <= budget:
        # capacity nb²: every tile of every column block stays cached, so
        # epochs >= 2 recompute nothing (full-K HBM residency; partial
        # LRU capacity would thrash under sequential sweeps)
        km = BlockKernelMatrix(kern, x, bs, cache_blocks=nb * nb)
    else:
        spill = cache_dir
        if spill is None:
            spill = tmp_dir = tempfile.mkdtemp(prefix="krr_kcache_")
        hbm_cols = max(1, int(budget // max(n_rows * bs * 4, 1)))
        km = BlockKernelMatrix(
            kern, x, bs, cache_blocks=0, spill_dir=spill, hbm_cols=hbm_cols
        )
    alpha = jnp.zeros_like(y)
    f = jnp.zeros_like(y)
    lam_n = jnp.float32(lam * n)
    import time as _time

    import numpy as np

    from keystone_tpu.obs import ledger

    observe = ledger.solver_obs()
    try:
        for e in range(num_epochs):
            t_epoch = _time.perf_counter()
            hits0 = km.cache_hits
            for b in range(nb):
                lo = b * bs
                kcol = km.column_block(b)
                ab_new, f_delta = _cached_block_update(
                    kcol,
                    kcol[lo : lo + bs],
                    row_ok,
                    row_ok[lo : lo + bs],
                    alpha[lo : lo + bs],
                    y[lo : lo + bs],
                    f[lo : lo + bs],
                    lam_n,
                )
                alpha = lax.dynamic_update_slice_in_dim(alpha, ab_new, lo, axis=0)
                f = f + f_delta
            if observe:
                # per-epoch objective is a real device read — obs-gated,
                # so the inert sweep carries no sync at all
                ledger.solver_epoch(
                    "krr.cached",
                    epoch=e,
                    objective=float(np.asarray(_krr_objective(y, f, n))),  # lint: allow-host-sync
                    epoch_seconds=_time.perf_counter() - t_epoch,
                    cache_hits=km.cache_hits - hits0,
                )
    finally:
        if tmp_dir is not None:
            jax.block_until_ready(alpha)
            shutil.rmtree(tmp_dir, ignore_errors=True)
    return alpha


@partial(jax.jit, static_argnames=("bs",))
def _krr_predict(xs, train_x, alpha, gamma, bs):
    kern = GaussianKernelGenerator(gamma, solver_grade=False)
    n_rows = train_x.shape[0]
    nb = n_rows // bs
    out0 = jnp.zeros((xs.shape[0], alpha.shape[1]), jnp.float32)

    def body(b, out):
        xb = lax.dynamic_slice_in_dim(train_x, b * bs, bs)
        ab = lax.dynamic_slice_in_dim(alpha, b * bs, bs)
        return out + kern(xs, xb) @ ab

    return lax.fori_loop(0, nb, body, out0)


@jax.jit
def _krr_objective(y, f, n):
    """Dual residual objective ½‖Y−F‖²/n of a KRR carry — one tiny
    jitted reduction so obs-enabled host loops never pull the (n × k)
    residual to host just to norm it."""
    r = y - f
    return 0.5 * jnp.vdot(r, r) / n


# --------------------------------------------------------------------------
# Out-of-core kernel BCD (train rows streamed from disk).
#
# The in-core sweep (_krr_fit) needs the full (n, d) train matrix plus
# the (n, k) α/F carries resident; the million-row regime the fork's
# paper targets (arXiv:1602.05310) does not fit.  Out-of-core form: the
# rows live in a RowBlockStore on host disk, and the per-(epoch, block)
# update streams the WHOLE matrix once per column block through
# blockstore.iter_device_blocks — every K_{ib} tile is computed on the
# fly from two resident (bs, d) row blocks via the ‖x−z‖² gemm
# expansion (the gram Pallas megakernel on capable backends), so HBM
# holds two row blocks, the per-block (bs, k) α/F/Y slices, and nothing
# n²-shaped, ever.
#
# Per step b the math is exactly _krr_fit's:
#     K_bb       from the staged X_b           (diag step: solve + Δα_b)
#     F_i += K_ib·Δα_b  for every row block i  (off-diag steps)
# The stream order per epoch is  [b, 0, 1, …, b−1, b+1, …]  for each b
# — nb² staged blocks per epoch, one generator for the whole sweep so
# the disk→host→device pipeline never drains at step boundaries.
# --------------------------------------------------------------------------


def _oc_gram(x, z, gamma, use_pallas: bool):
    """Trace-time gram dispatch for the SOLVER path: Pallas megakernel
    when enabled (f32 operand stream — kernel values feed Cholesky
    solves), else the bit-identical GaussianKernelGenerator XLA chain
    (solver-grade sdot)."""
    from keystone_tpu.ops import gram_pallas

    if use_pallas:
        return gram_pallas.gram_block_pallas(x, z, gamma, mxu="f32")
    return gram_pallas._gram_block_xla(x, z, gamma, solver_grade=True)


@partial(
    jax.jit, static_argnames=("gamma", "use_pallas"), donate_argnums=(1, 2)
)
def _oc_krr_diag_step(xb, fb, ab, yb, ok_b, lam_n, gamma, use_pallas=False):
    """One diagonal (solve) step of the out-of-core sweep.

    The carried ``(fb, ab)`` slices are DONATED (aliased onto the
    step's outputs): epoch N's dual state lands in epoch N−1's HBM —
    in the out-of-core regime HBM headroom is what bounds the block
    size.  The staged ``xb`` is NOT donated: the off-diagonal steps of
    this same block sweep still read it.  The fourth output is a
    non-donated (1, 1) ``tick`` (the PR-7 pattern): both real outputs
    are donated into later steps, so neither can be waited on for flow
    control — the sweep ``block_until_ready``s the tick two steps
    behind to bound its dispatch-queue lead."""
    kbb = _oc_gram(xb, xb, gamma, use_pallas)
    kbb = kbb * ok_b[:, None] * ok_b[None, :] + jnp.diag(1.0 - ok_b)
    target = yb - fb + kbb @ ab
    ab_new = solve_spd(kbb, target, reg=lam_n) * ok_b[:, None]
    dab = ab_new - ab
    # diag(1−ok)·Δα is zero row-by-row (Δα is masked), so using the
    # solve-regularized kbb here matches _krr_fit's unregularized kcol
    # tile exactly
    fb_new = fb + kbb @ dab
    return ab_new, fb_new, dab, ab_new[:1, :1]


@partial(
    jax.jit, static_argnames=("gamma", "use_pallas"), donate_argnums=(0,)
)
def _oc_krr_offdiag_step(fi, xi, xb, dab, ok_i, ok_b, gamma, use_pallas=False):
    """One off-diagonal F update: F_i += K(X_i, X_b)·Δα_b.  ``fi`` is
    donated (the running residual slice reuses its own HBM); the
    streamed ``xi`` is not (it frees by refcount when the loop drops
    it), and ``dab`` is read by every off-diag step of the block."""
    kib = _oc_gram(xi, xb, gamma, use_pallas) * ok_i[:, None] * ok_b[None, :]
    fi_new = fi + kib @ dab
    return fi_new, fi_new[:1, :1]


def _oc_krr_fit(
    store,
    y,
    n,
    gamma,
    lam,
    num_epochs,
    checkpoint_dir=None,
    prefetch=None,
    use_pallas=None,
):
    """Stream train-row blocks from ``store`` through kernel BCD sweeps.

    ``y``: (n, k) labels; ``n``: true row count; returns the dual
    coefficients α as one (nb·bs, k) array (zero on padding rows).

    ``prefetch`` rides the shared ``[1, 64]``-bounded resolution
    (:func:`~keystone_tpu.models.block_ls._oc_prefetch`, env override
    ``KEYSTONE_OC_PREFETCH``).  With ``checkpoint_dir``, each completed
    epoch saves (epoch, α, F) through ``utils/durable`` (atomic write,
    BLAKE2b sidecar, keep-2 rotation) and an interrupted fit resumes
    from the last completed epoch — a corrupt newest checkpoint falls
    back to the previous one bit-identically.  The ``kernel.sweep``
    fault site fires once per diagonal step.
    """
    import os
    import time as _time

    import numpy as np

    from keystone_tpu.faults import fault_point
    from keystone_tpu.models.block_ls import _oc_prefetch
    from keystone_tpu.obs import ledger, metrics
    from keystone_tpu.ops.gram_pallas import gram_pallas_enabled

    if jax.process_count() > 1:
        raise NotImplementedError(
            "out-of-core kernel BCD is single-process for now: the dual "
            "carries are row-blocked, and sharding kernel tiles across "
            "hosts is future work"
        )
    bs, nb = store.block_size, store.num_blocks
    n_rows = nb * bs
    prefetch = _oc_prefetch(prefetch)
    if use_pallas is None:
        use_pallas = gram_pallas_enabled(store.d)
    gamma = float(gamma)
    y = jnp.asarray(y, jnp.float32)
    if y.shape[0] > n_rows:
        # mesh-sharded label Datasets pad rows to a device-count
        # multiple that can exceed the store's block padding; those
        # rows are zero by the sharding contract and past row_ok anyway
        y = y[:n_rows]
    if y.shape[0] < n_rows:
        y = jnp.pad(y, ((0, n_rows - y.shape[0]), (0, 0)))
    k = y.shape[1]
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    y = y * row_ok[:, None]
    # per-block carries: (bs, k) slices, donated step-over-step — the
    # full α/F never need to exist as single arrays during the sweep
    yb = [y[b * bs : (b + 1) * bs] for b in range(nb)]
    ok = [row_ok[b * bs : (b + 1) * bs] for b in range(nb)]
    ab = [jnp.zeros((bs, k), jnp.float32) for _ in range(nb)]
    fb = [jnp.zeros((bs, k), jnp.float32) for _ in range(nb)]
    lam_n = jnp.float32(lam * n)
    start = 0

    ckpt_path = problem = None
    if checkpoint_dir is not None:
        import hashlib

        from keystone_tpu.utils import durable

        os.makedirs(checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(checkpoint_dir, "krr_epoch.npz")
        # Content-based problem fingerprint (the _oc_bcd_fit discipline):
        # resuming with different data, labels, γ, λ, or blocking must
        # restart, while a re-spill of IDENTICAL rows to a new directory
        # must still resume — so hash content probes, never paths.
        # Probe FIRST, MIDDLE, and LAST row blocks (one block alone
        # would accept data that drifted anywhere past block 0; a full
        # scan would re-read the entire store just to decide a resume)
        h = hashlib.sha256()
        for pb in sorted({0, nb // 2, nb - 1}):
            h.update(np.ascontiguousarray(store.read_block(pb)).tobytes())
        probe = h.hexdigest()
        fp = hashlib.sha256()
        fp.update(
            repr(
                (
                    store.n,
                    store.d,
                    bs,
                    (n_rows, k),
                    float(lam),
                    gamma,
                    float(n),
                    probe,
                )
            ).encode()
        )
        # label probes: first + last rows AND a 64-row stride — one row
        # alone would accept a resume whose labels share row 0 but
        # differ later (easy for classification indicator matrices)
        fp.update(np.asarray(y[:1]).tobytes())
        fp.update(np.asarray(y[-1:]).tobytes())
        fp.update(np.asarray(y[:: max(1, n_rows // 64)]).tobytes())
        problem = fp.hexdigest()

        # newest→last-good scan (utils/durable): a corrupt newest epoch
        # falls back to the previous one instead of a scratch fit
        loaded = durable.load_npz(
            ckpt_path,
            validate=lambda z: str(z.get("problem")) == problem
            and z["alpha"].shape == (nb, bs, k)
            and z["f"].shape == (nb, bs, k),
        )
        if loaded is not None:
            z, _ = loaded
            start = int(z["epoch"]) + 1
            ab = [jnp.asarray(z["alpha"][b]) for b in range(nb)]
            fb = [jnp.asarray(z["f"][b]) for b in range(nb)]

    # one stream order for the whole remaining fit: per (epoch, b) the
    # diag block leads, then every other row block for the F pass —
    # nb² staged blocks per epoch, one generator end to end so the
    # double-buffered feed never drains at step boundaries
    order = []
    for _ in range(start, num_epochs):
        for b in range(nb):
            order.append(b)
            order.extend(i for i in range(nb) if i != b)

    from collections import deque

    observe = ledger.solver_obs()
    per_epoch = nb * nb
    pending: deque = deque()
    epoch = start
    t_epoch = _time.perf_counter()
    xb_cur = dab = None
    b_cur = -1
    # the default stage() covers this store: device_put + on-device f32
    # cast for bf16 stores (solver math stays f32 after the half-width
    # wire crossing)
    for i, (j, a) in enumerate(
        store.iter_device_blocks(order, prefetch=prefetch)
    ):
        pos = i % per_epoch
        if pos % nb == 0:
            # diagonal step: X_b stays resident for this block's F pass
            b_cur = j
            fault_point("kernel.sweep", block=str(j))
            xb_cur = a
            ab[j], fb[j], dab, tick = _oc_krr_diag_step(
                xb_cur, fb[j], ab[j], yb[j], ok[j], lam_n,
                gamma=gamma, use_pallas=use_pallas,
            )
        else:
            fb[j], tick = _oc_krr_offdiag_step(
                fb[j], a, xb_cur, dab, ok[j], ok[b_cur],
                gamma=gamma, use_pallas=use_pallas,
            )
        # compute backpressure: ready-wait the non-donated tick two
        # steps back (see _oc_krr_diag_step) — the staging window only
        # bounds transfers, not the dispatch queue
        pending.append(tick)
        if len(pending) > 2:
            ledger.device_wait(pending.popleft(), force=True)
        if pos == per_epoch - 1:
            save_seconds = None
            if ckpt_path is not None:
                from keystone_tpu.utils import durable

                # required sync (the host reads below consume α/F);
                # metered as device-busy either way
                ledger.device_wait((ab, fb), force=True)
                a_host = np.stack([np.asarray(x) for x in ab])  # lint: allow-host-sync
                f_host = np.stack([np.asarray(x) for x in fb])  # lint: allow-host-sync
                t_save = _time.perf_counter()
                durable.save_npz(
                    ckpt_path,
                    {
                        # host scalars: savez coerces — no device read
                        "epoch": epoch,
                        "alpha": a_host,
                        "f": f_host,
                        "problem": problem,
                    },
                    keep=2,
                )
                save_seconds = _time.perf_counter() - t_save
                metrics.observe("solver.checkpoint_save_seconds", save_seconds)
            if observe:
                # per-epoch objective is a real device read — charge the
                # wait to the device-busy account (obs-gated: the inert
                # sweep carries no sync at all)
                t_dev = _time.perf_counter()
                obj = float(np.asarray(_krr_objective(jnp.stack(yb), jnp.stack(fb), jnp.float32(n))))  # lint: allow-host-sync
                metrics.observe(
                    "device.busy_seconds", _time.perf_counter() - t_dev
                )
                ledger.solver_epoch(
                    "krr.out_of_core",
                    epoch=epoch,
                    objective=obj,
                    epoch_seconds=_time.perf_counter() - t_epoch,
                    checkpoint_save_seconds=save_seconds,
                )
            t_epoch = _time.perf_counter()
            epoch += 1
    return ledger.device_wait(jnp.concatenate(ab, axis=0))


@partial(jax.jit, static_argnames=("gamma", "mxu", "use_pallas"))
def _oc_krr_predict_block(out, xs, xb, ab, gamma, mxu="f32", use_pallas=False):
    """One streamed prediction accumulation: out += K(xs, X_b)·α_b.
    Scoring, not solving — the gram rides the apply precision policy
    (``mxu``), matching KernelBlockLinearMapper's non-solver-grade
    predict gemms."""
    from keystone_tpu.ops import gram_pallas

    if use_pallas:
        kb = gram_pallas.gram_block_pallas(xs, xb, gamma, mxu=mxu)
    else:
        kb = gram_pallas._gram_block_xla(xs, xb, gamma, solver_grade=False)
    return out + kb @ ab


class OutOfCoreKernelBlockLinearMapper(Transformer):
    """Predicts K(x_test, X_train)·α with the TRAIN rows streamed from
    a RowBlockStore — for kernel models the train matrix IS part of the
    model, and in the out-of-core regime it stays on disk at predict
    time too.  The store directory must survive as long as the fitted
    model does (see ``fit_stream_dataset``)."""

    #: apply_batch drives its own per-block jitted programs over a host
    #: streaming loop; the generic per-instance jit wrapper would trace
    #: the loop into ONE program embedding every train block as a
    #: constant — the exact n×d residency the out-of-core tier exists
    #: to avoid
    self_jitted = True

    def __init__(self, kernel_gen, store_directory, alpha, train_n):
        self.kernel_gen = kernel_gen
        self.store_directory = str(store_directory)
        self.alpha = alpha  # (nb*bs, k); zero on padding rows
        self.train_n = int(train_n)

    def _store(self):
        st = self.__dict__.get("_store_obj")
        if st is None:
            from keystone_tpu.workflow.blockstore import RowBlockStore

            st = RowBlockStore(self.store_directory)
            self.__dict__["_store_obj"] = st
        return st

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_store_obj", None)  # handles don't pickle; reopen lazily
        return state

    def apply_batch(self, xs, mask=None):
        from collections import deque

        from keystone_tpu.obs import ledger
        from keystone_tpu.ops.gram_pallas import gram_pallas_enabled
        from keystone_tpu.utils import precision

        st = self._store()
        xs = jnp.asarray(xs, jnp.float32)
        out = jnp.zeros((xs.shape[0], self.alpha.shape[1]), jnp.float32)
        bs = st.block_size
        mxu = precision.apply_mode()
        use_pallas = gram_pallas_enabled(st.d)
        # dispatch-queue backpressure (the iter_device_blocks contract):
        # the staging window bounds transfers only, so without a
        # ready-wait two steps back a slow per-block gram lets every
        # staged train block pile up in HBM pinned by its queued
        # execution — the residency this tier exists to avoid.  ``out``
        # is rebound, never donated, so old bindings are waitable.
        pending: deque = deque()
        for b, blk in st.iter_device_blocks(range(st.num_blocks)):
            out = _oc_krr_predict_block(
                out,
                xs,
                blk,
                self.alpha[b * bs : (b + 1) * bs],
                gamma=float(self.kernel_gen.gamma),
                mxu=mxu,
                use_pallas=use_pallas,
            )
            pending.append(out)
            if len(pending) > 2:
                ledger.device_wait(pending.popleft(), force=True)
        return out

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]
