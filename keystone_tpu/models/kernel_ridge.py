"""Kernel ridge regression by block coordinate descent.

Reference [fork]: nodes/learning/KernelRidgeRegression.scala,
KernelBlockLinearMapper.scala, KernelMatrix.scala § BlockKernelMatrix and
KernelGenerator § GaussianKernelGenerator — Stephen Tu's block
Gauss–Seidel KRR (arXiv:1602.05310): kernel-matrix column blocks are
materialized (cached RDDs) and the dual coefficients are swept blockwise:

    α_b ← (K_bb + λnI)⁻¹ (Y_b − F_b + K_bb α_b),   F = K·α

TPU form: kernel blocks are computed on the fly from row-sharded X with
the ‖x−z‖² gemm expansion (never materializing the full n×n K), the block
solve runs replicated, and F updates contract over ICI.  The whole
multi-epoch sweep is one jitted program.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain, solve_spd
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import sdot


@dataclasses.dataclass(frozen=True)
class GaussianKernelGenerator:
    """K(x, z) = exp(−γ‖x−z‖²) via the gemm expansion
    (KernelGenerator.scala § GaussianKernelGenerator)."""

    gamma: float
    #: solver-grade (true f32) MXU passes for the distance gemm.  True
    #: during fits — the kernel values enter the block solves — but
    #: predict-time generators use default precision: inference has no
    #: downstream solve and the full-precision passes cost ~2×.
    solver_grade: bool = True

    def __call__(self, x: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
        xn = jnp.sum(x * x, axis=1, keepdims=True)
        zn = jnp.sum(z * z, axis=1)
        if self.solver_grade:
            cross = sdot(x, z.T)
        else:
            cross = jnp.matmul(x, z.T, preferred_element_type=jnp.float32)
        sq = jnp.maximum(xn - 2.0 * cross + zn, 0.0)
        return jnp.exp(-self.gamma * sq)


class KernelBlockLinearMapper(Transformer):
    """Predicts K(x_test, X_train)·α, streaming over train blocks so the
    test×train kernel never fully materializes
    (KernelBlockLinearMapper.scala)."""

    def __init__(self, kernel_gen, train_x, alpha, block_size: int, train_n: int):
        self.kernel_gen = kernel_gen
        self.train_x = train_x  # (n_rows, d), padded
        self.alpha = alpha  # (n_rows, k); zero on padding rows
        self.block_size = int(block_size)
        self.train_n = int(train_n)

    def apply_batch(self, xs, mask=None):
        return _krr_predict(
            xs, self.train_x, self.alpha, self.kernel_gen.gamma, self.block_size
        )

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class KernelRidgeRegressionEstimator(LabelEstimator):
    """``cache_kernel_blocks`` reproduces the reference's cached-RDD
    kernel column blocks (KernelMatrix.scala § BlockKernelMatrix): the
    fit sweeps through a BlockKernelMatrix LRU, so epochs ≥ 2 reread
    cached blocks (n² HBM) instead of recomputing the ‖x−z‖² gemms.
    Measured on v5 lite (BASELINE.md "KRR kernel-block cache"): the
    recompute sweep wins below d≈2·10³ (~4× at d=64, ~1.3× at d=1024) —
    the MXU regenerates blocks faster than HBM rereads them while the
    gemm is small — so recompute stays the default; caching wins for
    wide features (~2.2× at d=4096, n=8k) when K fits HBM."""

    # class-level default for pre-option pickles
    kernel_cache_dir = None

    def __init__(
        self,
        kernel_gen: GaussianKernelGenerator,
        lam: float = 1e-3,
        block_size: int = 1024,
        num_epochs: int = 1,
        cache_kernel_blocks: bool = False,
        kernel_cache_dir: Optional[str] = None,
    ):
        self.kernel_gen = kernel_gen
        self.lam = float(lam)
        self.block_size = int(block_size)
        self.num_epochs = int(num_epochs)
        self.cache_kernel_blocks = bool(cache_kernel_blocks)
        #: with cache_kernel_blocks, K beyond the HBM budget spills its
        #: column blocks here (the reference's executor-disk cached
        #: RDDs); None → a temp dir, deleted after the fit
        self.kernel_cache_dir = kernel_cache_dir

    def params(self):
        return (
            self.kernel_gen.gamma,
            self.lam,
            self.block_size,
            self.num_epochs,
            self.cache_kernel_blocks,
        )

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("KernelRidgeRegressionEstimator requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        x = jnp.asarray(x, jnp.float32)
        y = jnp.asarray(y, jnp.float32)
        n_rows = x.shape[0]
        bs = self.block_size
        nb = -(-n_rows // bs)
        if nb * bs != n_rows:
            x = jnp.pad(x, ((0, nb * bs - n_rows), (0, 0)))
            y = jnp.pad(y, ((0, nb * bs - n_rows), (0, 0)))
        if self.cache_kernel_blocks:
            alpha = _krr_fit_cached(
                x,
                y,
                n,
                self.kernel_gen,
                self.lam,
                bs,
                self.num_epochs,
                cache_dir=self.kernel_cache_dir,
            )
        else:
            alpha = _krr_fit(
                x, y, jnp.float32(n), self.kernel_gen.gamma, self.lam,
                bs, self.num_epochs,
            )
        return KernelBlockLinearMapper(self.kernel_gen, x, alpha, bs, n)


@partial(jax.jit, static_argnames=("bs", "num_epochs"))
def _krr_fit(x, y, n, gamma, lam, bs, num_epochs):
    n_rows = x.shape[0]
    nb = n_rows // bs
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    x = constrain(x, DATA_AXIS)
    y = y * row_ok[:, None]
    kern = GaussianKernelGenerator(gamma)

    alpha0 = jnp.zeros_like(y)
    f0 = jnp.zeros_like(y)

    def block_step(b, carry):
        alpha, f = carry
        xb = lax.dynamic_slice_in_dim(x, b * bs, bs)
        ok_b = lax.dynamic_slice_in_dim(row_ok, b * bs, bs)
        # kernel column block K(:, b): (n_rows, bs); mask padding rows/cols
        kcol = kern(x, xb) * row_ok[:, None] * ok_b[None, :]
        kbb = lax.dynamic_slice_in_dim(kcol, b * bs, bs)
        # make the pad diagonal identity so the solve stays PD
        kbb = kbb + jnp.diag(1.0 - ok_b)
        ab = lax.dynamic_slice_in_dim(alpha, b * bs, bs)
        yb = lax.dynamic_slice_in_dim(y, b * bs, bs)
        fb = lax.dynamic_slice_in_dim(f, b * bs, bs)
        target = yb - fb + kbb @ ab
        ab_new = solve_spd(kbb, target, reg=lam * n) * ok_b[:, None]
        f_new = f + kcol @ (ab_new - ab)
        alpha_new = lax.dynamic_update_slice_in_dim(alpha, ab_new, b * bs, axis=0)
        return alpha_new, f_new

    def epoch(carry, _):
        return lax.fori_loop(0, nb, block_step, carry), None

    (alpha, _), _ = lax.scan(epoch, (alpha0, f0), None, length=num_epochs)
    return alpha


@jax.jit
def _cached_block_update(kcol, kbb, row_ok, ok_b, ab, yb, fb, lam_n):
    """One Gauss–Seidel block update from a PRE-COMPUTED kernel column
    block (same math as the inlined sweep in _krr_fit)."""
    kcol = kcol * row_ok[:, None] * ok_b[None, :]
    kbb = kbb * ok_b[:, None] * ok_b[None, :] + jnp.diag(1.0 - ok_b)
    target = yb - fb + kbb @ ab
    ab_new = solve_spd(kbb, target, reg=lam_n) * ok_b[:, None]
    return ab_new, kcol @ (ab_new - ab)


def _krr_fit_cached(x, y, n, kern, lam, bs, num_epochs, cache_dir=None):
    """Gauss–Seidel sweep through a BlockKernelMatrix LRU: kernel column
    blocks are computed once and REREAD on later epochs (the reference's
    cached-RDD strategy, KernelMatrix.scala).  Python-level block loop —
    the cache is a host-side structure — with each block update jitted.

    When K exceeds the HBM budget the cache goes TIERED: a partial HBM
    LRU backed by disk-persisted column blocks (the reference spilled
    cached RDDs to executor disk/memory the same way), so the cached
    mode no longer silently requires K ≲ HBM."""
    import shutil
    import tempfile

    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix
    from keystone_tpu.workflow.profiling import device_hbm_budget

    # fits always use solver-grade (true f32) kernel gemms, matching
    # _krr_fit — the cache flag must not silently relax solve numerics
    kern = dataclasses.replace(kern, solver_grade=True)
    n_rows = x.shape[0]
    nb = n_rows // bs
    row_ok = (jnp.arange(n_rows) < n).astype(jnp.float32)
    x = constrain(x, DATA_AXIS)  # kernel gemms contract over the data axis
    y = jnp.asarray(y, jnp.float32) * row_ok[:, None]
    k_bytes = n_rows * n_rows * 4
    budget = device_hbm_budget(0.5)
    tmp_dir = None
    if k_bytes <= budget:
        # capacity nb²: every tile of every column block stays cached, so
        # epochs >= 2 recompute nothing (full-K HBM residency; partial
        # LRU capacity would thrash under sequential sweeps)
        km = BlockKernelMatrix(kern, x, bs, cache_blocks=nb * nb)
    else:
        spill = cache_dir
        if spill is None:
            spill = tmp_dir = tempfile.mkdtemp(prefix="krr_kcache_")
        hbm_cols = max(1, int(budget // max(n_rows * bs * 4, 1)))
        km = BlockKernelMatrix(
            kern, x, bs, cache_blocks=0, spill_dir=spill, hbm_cols=hbm_cols
        )
    alpha = jnp.zeros_like(y)
    f = jnp.zeros_like(y)
    lam_n = jnp.float32(lam * n)
    try:
        for _ in range(num_epochs):
            for b in range(nb):
                lo = b * bs
                kcol = km.column_block(b)
                ab_new, f_delta = _cached_block_update(
                    kcol,
                    kcol[lo : lo + bs],
                    row_ok,
                    row_ok[lo : lo + bs],
                    alpha[lo : lo + bs],
                    y[lo : lo + bs],
                    f[lo : lo + bs],
                    lam_n,
                )
                alpha = lax.dynamic_update_slice_in_dim(alpha, ab_new, lo, axis=0)
                f = f + f_delta
    finally:
        if tmp_dir is not None:
            jax.block_until_ready(alpha)
            shutil.rmtree(tmp_dir, ignore_errors=True)
    return alpha


@partial(jax.jit, static_argnames=("bs",))
def _krr_predict(xs, train_x, alpha, gamma, bs):
    kern = GaussianKernelGenerator(gamma, solver_grade=False)
    n_rows = train_x.shape[0]
    nb = n_rows // bs
    out0 = jnp.zeros((xs.shape[0], alpha.shape[1]), jnp.float32)

    def body(b, out):
        xb = lax.dynamic_slice_in_dim(train_x, b * bs, bs)
        ab = lax.dynamic_slice_in_dim(alpha, b * bs, bs)
        return out + kern(xs, xb) @ ab

    return lax.fori_loop(0, nb, body, out0)
