"""Exact least-squares solvers.

Reference: nodes/learning/LinearMapper.scala § LinearMapEstimator /
LinearMapper and nodes/learning/LocalLeastSquaresEstimator.scala.

The reference computes per-partition ``AᵀA`` / ``Aᵀb`` gemms, treeReduces
them to the driver, Cholesky-solves there, and broadcasts the model.  Here
the whole fit is ONE jitted program: the einsum contraction over the
row-sharded batch axis becomes an XLA all-reduce over ICI, and the solve
runs replicated on every device — no driver round-trip exists at all.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.common import (
    kahan_add,
    solve_spd,
    stage_stream_batch,
    xtx_xty,
)
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import sdot


class LinearMapper(Transformer):
    """Applies ``xW + b`` (nodes/learning/LinearMapper.scala § LinearMapper)."""

    traced_attrs = ("weights", "intercept")

    def __init__(self, weights: jnp.ndarray, intercept: Optional[jnp.ndarray] = None):
        self.weights = weights
        self.intercept = intercept

    def apply_one(self, x):
        out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_batch(self, xs, mask=None):
        out = xs @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_dataset(self, ds):
        # sparse scoring (LBFGS.scala sparse path): score scipy rows by
        # gathering weight rows — never densify n×d at huge vocab
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows, score_sparse_dataset

        if ds.is_host and is_scipy_sparse_rows(ds.items):
            return score_sparse_dataset(ds, self.weights, self.intercept)
        return super().apply_dataset(ds)


class LinearMapEstimator(LabelEstimator):
    """Exact ridge least squares via normal equations
    (nodes/learning/LinearMapper.scala § LinearMapEstimator).

    With ``fit_intercept`` the solve runs on (weighted-)centered data and
    recovers the intercept as ``ȳ − x̄·W``, matching the reference's
    mean-subtraction path.
    """

    def __init__(self, lam: float = 0.0, fit_intercept: bool = True):
        self.lam = float(lam)
        self.fit_intercept = fit_intercept

    def params(self):
        return (self.lam, self.fit_intercept)

    def choose_physical(self, sample, full_n=None):
        """Physical choice (workflow/NodeOptimizationRule), two axes like
        the reference's rule:

        - sparsity: on host datasets of scipy sparse rows, the dense
          normal equations would densify n×d AND form a d×d Gram —
          infeasible at text-scale vocabularies — so route to the
          sparse-gradient L-BFGS solver, which minimizes the SAME
          objective (1/(2n)‖XW−Y‖² + λ/2‖W‖² ⇒ (XᵀX+λnI)W = XᵀY).  An
          intercept survives the swap (unregularized constant column).
        - size: when the FULL problem is small (n·d below the measured
          crossover — BASELINE.md "Local vs distributed solve"), pick
          :class:`LocalLeastSquaresEstimator`, the unsharded
          single-device solve with no collectives and no mesh padding
          (the reference's collect()+LAPACK path for small data)."""
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows

        if sample is not None and sample.is_host and is_scipy_sparse_rows(
            sample.items
        ):
            from keystone_tpu.models.lbfgs import SparseLBFGSwithL2

            return SparseLBFGSwithL2(
                lam=self.lam,
                num_iterations=100,
                fit_intercept=self.fit_intercept,
            )
        if (
            sample is not None
            and not sample.is_host
            and full_n is not None
            and sample.array.ndim == 2
            and full_n * sample.array.shape[1] <= _LOCAL_SOLVE_MAX_ELEMENTS
        ):
            return LocalLeastSquaresEstimator(
                lam=self.lam, fit_intercept=self.fit_intercept
            )
        return self

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("LinearMapEstimator requires labels")
        # robustness, not just optimization: host CSR datasets must fit
        # even when NodeChoiceRule didn't run (custom optimizers,
        # best-effort sampling failures) — route like choose_physical
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows

        if data.is_host and is_scipy_sparse_rows(data.items):
            return self.choose_physical(data).fit_dataset(data, labels)
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            if data.is_host:
                raise TypeError(
                    "host-payload stream reached the exact solver with "
                    "non-CSR items; featurize to arrays (or CSR) first"
                )
            # out-of-core: labels are (n, k) and stay in memory; features
            # stream past the sufficient-statistic accumulators
            import numpy as np

            y = np.asarray(labels.numpy())

            def pairs():
                offset = 0
                for b in data.batches():
                    yield b, y[offset : offset + len(b)]
                    offset += len(b)

            return self.fit_stream(pairs)
        w, b = _fit_normal_equations(
            data.array,
            labels.array,
            jnp.float32(data.n),
            self.lam,
            self.fit_intercept,
        )
        return LinearMapper(w, b if self.fit_intercept else None)

    def fit_arrays(self, x, y=None) -> LinearMapper:
        x = jnp.asarray(x)
        w, b = _fit_normal_equations(
            x, jnp.asarray(y), jnp.float32(x.shape[0]), self.lam, self.fit_intercept
        )
        return LinearMapper(w, b if self.fit_intercept else None)

    def fit_stream(self, batches) -> LinearMapper:
        """Out-of-core exact least squares from a stream of host batches.

        ``batches``: a callable returning an iterator of ``(x, y)`` host
        arrays (re-invoked per pass), or a re-iterable (e.g. a list).
        The normal equations only need accumulated sufficient statistics,
        so HBM holds one batch plus the (d, d)/(d, k) accumulators — the
        dataset can be arbitrarily larger than device memory (the
        reference's analogue: features as spilled RDDs, SURVEY §2.9).

        Two passes when ``fit_intercept``: means first, then Gramians of
        EXPLICITLY centered batches — the one-pass shortcut
        ``XᵀX − n·x̄x̄ᵀ`` cancels catastrophically in f32 (see
        _fit_normal_equations).  Accumulators are Kahan-compensated, so
        rounding error stays O(ε) instead of growing with batch count.
        """
        get = batches if callable(batches) else lambda: iter(batches)
        if not self.fit_intercept:
            gram = None
            n = 0
            for bx, by in get():
                bx, by, bn, row_ok = stage_stream_batch(bx, by)
                n += bn
                gram = _acc_gram(gram, bx, by, None, None, row_ok)
            if n == 0:
                raise ValueError("empty batch stream")
            w = solve_spd(gram[0], gram[2], reg=self.lam * n)
            return LinearMapper(w, None)
        sums = None
        n = 0
        for bx, by in get():
            bx, by, bn, row_ok = stage_stream_batch(bx, by)
            n += bn
            sums = _acc_sums(sums, bx, by)
        if n == 0:
            raise ValueError("empty batch stream")
        xm, ym = sums[0] / n, sums[2] / n
        gram = None
        n2 = 0
        for bx, by in get():
            bx, by, bn, row_ok = stage_stream_batch(bx, by)
            n2 += bn
            gram = _acc_gram(gram, bx, by, xm, ym, row_ok)
        if n2 != n:
            raise ValueError(
                f"batch stream is not re-iterable: first pass saw {n} rows, "
                f"second pass {n2}. Pass a CALLABLE returning a fresh "
                "iterator (or a re-iterable like a list), not a one-shot "
                "generator."
            )
        w = solve_spd(gram[0], gram[2], reg=self.lam * n)
        return LinearMapper(w, ym - xm @ w)


@jax.jit
def _acc_sums(carry, x, y):
    """carry = (s1x, c1x, s1y, c1y) Kahan-compensated column sums."""
    bx, by = jnp.sum(x, axis=0), jnp.sum(y, axis=0)
    if carry is None:
        return bx, jnp.zeros_like(bx), by, jnp.zeros_like(by)
    s1x, c1x, s1y, c1y = carry
    s1x, c1x = kahan_add(s1x, c1x, bx)
    s1y, c1y = kahan_add(s1y, c1y, by)
    return s1x, c1x, s1y, c1y


@jax.jit
def _acc_gram(carry, x, y, xm, ym, row_ok):
    """carry = (sxx, cxx, sxy, cxy) Kahan-compensated Gramian sums."""
    if xm is not None:
        # center with the GLOBAL means; mask keeps shard-padding rows at 0
        x = (x - xm) * row_ok
        y = (y - ym) * row_ok
    gxx, gxy = xtx_xty(x, y)
    if carry is None:
        return gxx, jnp.zeros_like(gxx), gxy, jnp.zeros_like(gxy)
    sxx, cxx, sxy, cxy = carry
    sxx, cxx = kahan_add(sxx, cxx, gxx)
    sxy, cxy = kahan_add(sxy, cxy, gxy)
    return sxx, cxx, sxy, cxy


#: Alias matching common usage in reference pipelines.
LeastSquaresEstimator = LinearMapEstimator


#: n·d crossover below which the unsharded local solve beats the sharded
#: normal-equations path.  Measured on an 8-device mesh (BASELINE.md
#: "Local vs distributed solve"): local wins through n·d = 2²⁰
#: (4096×256: 49 ms vs 52 ms, and 2.7× at 256×64), the sharded path wins
#: from n·d = 2²³ up (2.2× at 16384×512); the boundary sits between.
_LOCAL_SOLVE_MAX_ELEMENTS = 1 << 21


class LocalLeastSquaresEstimator(LabelEstimator):
    """Single-device exact solve via QR/SVD lstsq — the physical
    alternative the optimizer picks for small data
    (nodes/learning/LocalLeastSquaresEstimator.scala).  No collectives:
    everything is gathered to one device, like the reference's
    ``collect()`` + LAPACK path."""

    fit_intercept = True  # class default for pre-option pickles

    def __init__(self, lam: float = 0.0, fit_intercept: bool = True):
        self.lam = float(lam)
        self.fit_intercept = bool(fit_intercept)

    def params(self):
        return (self.lam, self.fit_intercept)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None) -> LinearMapper:
        if labels is None:
            raise ValueError("LocalLeastSquaresEstimator requires labels")
        x = jnp.asarray(data.numpy())
        y = jnp.asarray(labels.numpy())
        return self.fit_arrays(x, y)

    def fit_arrays(self, x, y=None) -> LinearMapper:
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        if self.fit_intercept:
            xm = jnp.mean(x, axis=0)
            ym = jnp.mean(y, axis=0)
            xc, yc = x - xm, y - ym
        else:
            xc, yc = x, y
        if self.lam > 0.0:
            w = solve_spd(sdot(xc.T, xc), sdot(xc.T, yc), reg=self.lam * x.shape[0])
        else:
            w = jnp.linalg.lstsq(xc, yc)[0]
        if not self.fit_intercept:
            return LinearMapper(w, None)
        return LinearMapper(w, ym - xm @ w)


@partial(jax.jit, static_argnames=("fit_intercept",))
def _fit_normal_equations(x, y, n, lam, fit_intercept):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    if fit_intercept:
        # Means over the true row count: padding rows are zero, so plain
        # sums divided by n are exact.
        xm = jnp.sum(x, axis=0) / n
        ym = jnp.sum(y, axis=0) / n
        # Center EXPLICITLY before the Gramian (pad rows masked back to 0).
        # The algebraic shortcut XᵀX − n·x̄x̄ᵀ cancels catastrophically in
        # f32 when feature magnitudes are large (e.g. 0–255 pixels).
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
        xc = (x - xm) * row_ok
        yc = (y - ym) * row_ok
        xtx_c, xty_c = xtx_xty(xc, yc)
        w = solve_spd(xtx_c, xty_c, reg=lam * n)
        b = ym - xm @ w
        return w, b
    xtx, xty = xtx_xty(x, y)
    w = solve_spd(xtx, xty, reg=lam * n)
    return w, jnp.zeros((y.shape[1],), jnp.float32)
