"""Kernel matrix abstraction.

Reference [fork]: nodes/learning/KernelMatrix.scala § KernelMatrix /
BlockKernelMatrix — the interface the block-coordinate KRR solver uses to
get kernel column blocks, with caching of materialized blocks (cached
RDDs upstream).

TPU form: blocks are computed on demand from row-sharded X via the gemm
expansion and optionally kept in an HBM-side LRU (the cache analogue);
the full n×n matrix never materializes.  KernelRidgeRegressionEstimator
inlines this computation inside its jitted sweep for speed; this class is
the standalone/introspection API.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

import jax.numpy as jnp

from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator


class BlockKernelMatrix:
    """K(X, X) exposed as (row-block, col-block) tiles with LRU caching."""

    def __init__(
        self,
        kernel_gen: GaussianKernelGenerator,
        x: jnp.ndarray,
        block_size: int = 1024,
        cache_blocks: int = 8,
    ):
        self.kernel_gen = kernel_gen
        self.x = jnp.asarray(x, jnp.float32)
        self.block_size = int(block_size)
        self.n = self.x.shape[0]
        self.num_blocks = -(-self.n // self.block_size)
        self._cache: "OrderedDict[Tuple[int, int], jnp.ndarray]" = OrderedDict()
        self._cache_blocks = int(cache_blocks)
        # assembled (n, bs) column blocks, cached whole: the BCD sweep
        # rereads columns across epochs, and re-concatenating tiles per
        # access would copy the full n² every epoch
        self._col_cache: "OrderedDict[int, jnp.ndarray]" = OrderedDict()

    def _rows(self, b: int) -> jnp.ndarray:
        lo = b * self.block_size
        return self.x[lo : lo + self.block_size]

    def block(self, i: int, j: int) -> jnp.ndarray:
        """K[X_i, X_j] — (<=bs, <=bs)."""
        key = (i, j)
        if key in self._cache:
            self._cache.move_to_end(key)
            return self._cache[key]
        blk = self.kernel_gen(self._rows(i), self._rows(j))
        self._cache[key] = blk
        if len(self._cache) > self._cache_blocks:
            self._cache.popitem(last=False)
        return blk

    def column_block(self, j: int) -> jnp.ndarray:
        """K[:, X_j] — (n, <=bs); the unit the BCD sweep consumes.

        Cached WHOLE (one (n, bs) gemm, reread free on later sweeps)
        when a full sweep's columns fit the budget (num_blocks² tiles ≤
        cache_blocks ⇔ num_blocks columns); otherwise a sweep would
        insert-then-evict every entry, so compute without caching."""
        if self.num_blocks == 0:
            return jnp.zeros((0, 0), jnp.float32)
        if self.num_blocks * self.num_blocks <= self._cache_blocks:
            blk = self._col_cache.get(j)
            if blk is None:
                blk = self.kernel_gen(self.x, self._rows(j))
                self._col_cache[j] = blk
                if len(self._col_cache) > self.num_blocks:
                    self._col_cache.popitem(last=False)
            else:
                self._col_cache.move_to_end(j)
            return blk
        return self.kernel_gen(self.x, self._rows(j))

    def diag_block(self, j: int) -> jnp.ndarray:
        """K[X_j, X_j]; reads through the column cache in the cached
        regime so the SAME n² budget serves every access path."""
        if self.num_blocks * self.num_blocks <= self._cache_blocks:
            lo = j * self.block_size
            return self.column_block(j)[lo : lo + self.block_size]
        return self.block(j, j)

    def matvec(self, v: jnp.ndarray) -> jnp.ndarray:
        """K @ v computed blockwise (n never squares in memory).

        Reads through the column cache when a full sweep fits the budget
        (repeat matvecs and BCD sweeps then share one cached copy of K);
        otherwise streams column gemms without polluting the cache."""
        if self.num_blocks == 0:
            return jnp.zeros((self.n,) + v.shape[1:], jnp.float32)
        cached = self.num_blocks * self.num_blocks <= self._cache_blocks
        out = jnp.zeros((self.n,) + v.shape[1:], jnp.float32)
        for j in range(self.num_blocks):
            lo = j * self.block_size
            vj = v[lo : lo + self.block_size]
            kcol = (
                self.column_block(j)
                if cached
                else self.kernel_gen(self.x, self._rows(j))
            )
            out = out + kcol @ vj
        return out
