"""Kernel matrix abstraction.

Reference [fork]: nodes/learning/KernelMatrix.scala § KernelMatrix /
BlockKernelMatrix — the interface the block-coordinate KRR solver uses to
get kernel column blocks, with caching of materialized blocks (cached
RDDs upstream).

TPU form: blocks are computed on demand from row-sharded X via the gemm
expansion and optionally kept in an HBM-side LRU (the cache analogue);
the full n×n matrix never materializes.  KernelRidgeRegressionEstimator
inlines this computation inside its jitted sweep for speed; this class is
the standalone/introspection API.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

import jax.numpy as jnp

from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator


class BlockKernelMatrix:
    """K(X, X) exposed as (row-block, col-block) tiles with LRU caching."""

    def __init__(
        self,
        kernel_gen: GaussianKernelGenerator,
        x: jnp.ndarray,
        block_size: int = 1024,
        cache_blocks: int = 8,
        spill_dir: Optional[str] = None,
        hbm_cols: int = 1,
    ):
        self.kernel_gen = kernel_gen
        self.x = jnp.asarray(x, jnp.float32)
        self.block_size = int(block_size)
        self.n = self.x.shape[0]
        self.num_blocks = -(-self.n // self.block_size)
        self._cache: "OrderedDict[Tuple[int, int], jnp.ndarray]" = OrderedDict()
        self._cache_blocks = int(cache_blocks)
        # assembled (n, bs) column blocks, cached whole: the BCD sweep
        # rereads columns across epochs, and re-concatenating tiles per
        # access would copy the full n² every epoch
        self._col_cache: "OrderedDict[int, jnp.ndarray]" = OrderedDict()
        #: disk tier for K beyond HBM (the reference's cached blocks
        #: spilled to executor disk): computed column blocks persist as
        #: npy files; HBM holds an LRU of up to ``hbm_cols`` whole
        #: columns, evicted columns reload from disk instead of
        #: recomputing the gemm
        self.spill_dir = spill_dir
        self.hbm_cols = max(1, int(hbm_cols))
        #: block-cache accounting (HBM LRU + disk tier): the cached
        #: KRR sweep's per-epoch telemetry reports hits so an operator
        #: can SEE whether epochs ≥ 2 actually reread or thrashed
        self.cache_hits = 0
        self.cache_misses = 0
        if spill_dir is not None:
            self._init_spill_dir(spill_dir)

    def _compute(self, a, b_rows):
        """One gram gemm.  First-class generators (Gaussian,
        polynomial, linear — ``models/kernel_ridge.py``) route through
        the ``ops/gram_pallas`` dispatcher: the fused megakernel on
        capable backends (solver-grade fits stream f32, scoring
        generators ride the apply precision policy), the generator's
        own XLA chain — bit-identically — everywhere else.  Duck-typed
        generators are never routed: the generator is called as-is."""
        from keystone_tpu.models.kernel_ridge import (
            GaussianKernelGenerator,
            LinearKernelGenerator,
            PolynomialKernelGenerator,
        )

        kg = self.kernel_gen
        if isinstance(
            kg,
            (
                GaussianKernelGenerator,
                PolynomialKernelGenerator,
                LinearKernelGenerator,
            ),
        ):
            from keystone_tpu.ops import gram_pallas

            if gram_pallas.gram_pallas_enabled(int(self.x.shape[1])):
                if getattr(kg, "solver_grade", True):
                    mxu = "f32"
                else:
                    from keystone_tpu.utils import precision

                    mxu = precision.apply_mode()
                out = gram_pallas.gram_block_for(kg, a, b_rows, mxu=mxu)
                if out is not None:
                    return out
        return kg(a, b_rows)

    def _init_spill_dir(self, spill_dir: str) -> None:
        """Create/validate the disk tier.  Spilled columns are only
        valid for THIS (data, kernel, blocking) triple: a reused cache
        dir from a different fit would silently serve a different
        problem's kernel matrix, so the dir carries a content
        fingerprint.  On mismatch only files this cache owns
        (``kcol_*.npy`` + ``kcache_meta.json``) are removed; a directory
        holding anything else is refused rather than clobbered.

        Concurrency contract: multiple processes may share a spill dir
        only for the SAME problem (same fingerprint — the pid-suffixed
        temp + ``os.replace`` writers in :meth:`_column_via_disk` make
        that safe).  Concurrent fits of *different* problems must use
        distinct dirs: this init clears on mismatch without a lock."""
        import hashlib
        import json
        import os

        import numpy as np

        probe = hashlib.sha256()
        # the kernel identity is the generator's type + ALL its scalar
        # parameters, not just gamma: a different generator reusing the
        # dir must not pass validation.  Collected explicitly (dataclass
        # fields, else public scalar attrs incl. class-level defaults) —
        # default object repr is id-based and would break cross-process
        # reuse of the spill dir
        import dataclasses as _dc
        import numbers

        kg = self.kernel_gen
        if _dc.is_dataclass(kg):
            raw = _dc.asdict(kg)
            strict = True  # every declared field IS a kernel parameter
        else:
            # dir() + getattr: covers instance attrs, class-level
            # defaults anywhere in the MRO, AND property-backed params
            # (a vars() scan silently drops properties — two kernels
            # differing only in a property value must not fingerprint
            # identically)
            raw = {}
            for pk in dir(type(kg)):
                if pk.startswith("_"):
                    continue
                try:
                    pv = getattr(kg, pk)
                except Exception:
                    continue
                if not callable(pv):
                    raw[pk] = pv
            for pk, pv in getattr(kg, "__dict__", {}).items():
                if not pk.startswith("_") and not callable(pv):
                    raw[pk] = pv
            strict = False  # duck-typed attrs may include non-params
        kp = {}
        for pk, pv in raw.items():
            if isinstance(pv, (str, tuple)):
                kp[pk] = pv
            elif isinstance(pv, numbers.Number):
                # coerce THROUGH f32: the device computes the kernel in
                # f32, so np.float32(0.02) and 0.02 are the same kernel
                # even though float(np.float32(0.02)) != 0.02 — and numpy
                # scalars must not be silently EXCLUDED from the identity
                kp[pk] = float(np.float32(pv))
            elif strict:
                # silently dropping a declared field would let two
                # different kernels fingerprint identically — refuse
                raise TypeError(
                    f"kernel generator field {pk!r} ({type(pv).__name__}) "
                    "cannot be fingerprinted for the spill dir; use "
                    "scalar/str/tuple fields or manage the cache dir "
                    "per problem"
                )
        kern_params = tuple(sorted(kp.items()))
        probe.update(
            repr(
                (
                    self.n,
                    self.block_size,
                    type(kg).__name__,
                    kern_params,
                    tuple(self.x.shape),
                )
            ).encode()
        )
        # first/last rows pin the data identity (order-sensitive)
        probe.update(np.asarray(self.x[:1]).tobytes())
        probe.update(np.asarray(self.x[-1:]).tobytes())
        fingerprint = probe.hexdigest()
        meta_path = os.path.join(spill_dir, "kcache_meta.json")
        if os.path.isdir(spill_dir):
            try:
                with open(meta_path) as f:
                    if json.load(f).get("fingerprint") == fingerprint:
                        return  # reusable: same problem
            except Exception:
                pass
            entries = os.listdir(spill_dir)
            owned = [
                e
                for e in entries
                # kcol_*.npy plus everything the durable spill path
                # derives from it: the BLAKE2b sidecar (.npy.b2) and
                # abandoned atomic-write temps (.npy.tmp.<pid>.<tid>,
                # .npy.b2.tmp.*) left by a crashed writer — a surviving
                # tmp must not make a reusable cache dir look foreign
                if e == "kcache_meta.json"
                or (e.startswith("kcol_") and ".npy" in e)
            ]
            # dotfiles (.nfsXXXX silly-renames, .DS_Store) are OS
            # artifacts, not user data: left alone, never grounds for
            # refusing an otherwise-dedicated cache dir
            foreign = [
                e for e in entries if e not in owned and not e.startswith(".")
            ]
            if foreign:
                raise ValueError(
                    f"kernel spill dir {spill_dir!r} (kernel_cache_dir at the "
                    f"estimator level) holds files this cache does not own "
                    f"({foreign[:5]}{'...' if len(foreign) > 5 else ''}); "
                    "refusing to clear it — pass an empty or dedicated directory"
                )
            for e in owned:
                # a surviving stale kcol under a fresh fingerprint would
                # be trusted by _column_via_disk — failed removal must
                # abort, not degrade to silent cache corruption
                os.remove(os.path.join(spill_dir, e))
        os.makedirs(spill_dir, exist_ok=True)
        with open(meta_path, "w") as f:
            json.dump({"fingerprint": fingerprint}, f)

    def _rows(self, b: int) -> jnp.ndarray:
        lo = b * self.block_size
        return self.x[lo : lo + self.block_size]

    def block(self, i: int, j: int) -> jnp.ndarray:
        """K[X_i, X_j] — (<=bs, <=bs)."""
        key = (i, j)
        if key in self._cache:
            self._cache.move_to_end(key)
            self.cache_hits += 1
            return self._cache[key]
        self.cache_misses += 1
        blk = self._compute(self._rows(i), self._rows(j))
        self._cache[key] = blk
        if len(self._cache) > self._cache_blocks:
            self._cache.popitem(last=False)
        return blk

    def column_block(self, j: int) -> jnp.ndarray:
        """K[:, X_j] — (n, <=bs); the unit the BCD sweep consumes.

        Cached WHOLE (one (n, bs) gemm, reread free on later sweeps)
        when a full sweep's columns fit the budget (num_blocks² tiles ≤
        cache_blocks ⇔ num_blocks columns).  With ``spill_dir``, columns
        beyond the HBM budget persist on disk and reload instead of
        recomputing (K-beyond-HBM cached mode).  Otherwise a sweep would
        insert-then-evict every entry, so compute without caching."""
        if self.num_blocks == 0:
            return jnp.zeros((0, 0), jnp.float32)
        if self.num_blocks * self.num_blocks <= self._cache_blocks:
            blk = self._col_cache.get(j)
            if blk is None:
                self.cache_misses += 1
                blk = self._compute(self.x, self._rows(j))
                self._col_cache[j] = blk
                if len(self._col_cache) > self.num_blocks:
                    self._col_cache.popitem(last=False)
            else:
                self.cache_hits += 1
                self._col_cache.move_to_end(j)
            return blk
        if self.spill_dir is not None:
            return self._column_via_disk(j)
        return self._compute(self.x, self._rows(j))

    def _column_via_disk(self, j: int) -> jnp.ndarray:
        """HBM-LRU → disk → compute-and-persist, in that order.

        The disk tier rides ``utils/durable`` end to end: spilled
        columns publish atomically (per-pid/thread tmp + fsync +
        rename) with a BLAKE2b sidecar, reads retry transient errors
        with backoff, and a torn or bit-flipped spill block — which the
        raw ``np.load`` path silently trusted — is detected
        (checksum/shape mismatch), counted as
        ``kernel.spill_corruption``, quarantined off disk, and
        REGENERATED from the gemm instead of poisoning every later
        epoch of the sweep."""
        import os

        import numpy as np

        from keystone_tpu.obs import metrics
        from keystone_tpu.utils import durable

        blk = self._col_cache.get(j)
        if blk is not None:
            self.cache_hits += 1
            self._col_cache.move_to_end(j)
            return blk
        self.cache_misses += 1
        path = os.path.join(self.spill_dir, f"kcol_{j:05d}.npy")
        expected = (self.n, self._rows(j).shape[0])
        blk = None
        if os.path.exists(path):

            def _read():
                # sidecar verification (spills written by this version
                # always have one; legacy sidecar-less files pass the
                # shape check only)
                durable.verify_checksum(path)
                raw = np.load(path)
                if raw.shape != expected:
                    raise durable.CorruptStateError(
                        f"kernel spill column {path} has shape "
                        f"{raw.shape}, expected {expected}"
                    )
                return raw

            try:
                raw = durable.with_retries(
                    _read, description=f"kernel spill read {path}"
                )
                metrics.inc("kernel.spill_reads")
                metrics.inc("kernel.spill_read_bytes", int(raw.nbytes))
                blk = jnp.asarray(raw)
            except durable.CorruptStateError:
                metrics.inc("kernel.spill_corruption")
                for p in (path, durable.checksum_path(path)):
                    try:
                        os.remove(p)
                    except OSError:
                        pass  # regeneration below rewrites both anyway
        if blk is None:
            blk = self._compute(self.x, self._rows(j))
            host = np.asarray(blk)

            def _write(tmp):
                with open(tmp, "wb") as f:
                    np.save(f, host)

            durable.atomic_write(path, _write)
            metrics.inc("kernel.spill_writes")
            metrics.inc("kernel.spill_write_bytes", int(host.nbytes))
        self._col_cache[j] = blk
        if len(self._col_cache) > self.hbm_cols:
            self._col_cache.popitem(last=False)  # evictee stays on disk
        return blk

    def diag_block(self, j: int) -> jnp.ndarray:
        """K[X_j, X_j]; reads through the column cache in the cached and
        disk-tier regimes so the SAME budget serves every access path."""
        if (
            self.num_blocks * self.num_blocks <= self._cache_blocks
            or self.spill_dir is not None
        ):
            lo = j * self.block_size
            return self.column_block(j)[lo : lo + self.block_size]
        return self.block(j, j)

    def matvec(self, v: jnp.ndarray) -> jnp.ndarray:
        """K @ v computed blockwise (n never squares in memory).

        Reads through the column cache when a full sweep fits the budget
        (repeat matvecs and BCD sweeps then share one cached copy of K);
        otherwise streams column gemms without polluting the cache."""
        if self.num_blocks == 0:
            return jnp.zeros((self.n,) + v.shape[1:], jnp.float32)
        cached = (
            self.num_blocks * self.num_blocks <= self._cache_blocks
            or self.spill_dir is not None  # disk tier: reread, not regen
        )
        out = jnp.zeros((self.n,) + v.shape[1:], jnp.float32)
        for j in range(self.num_blocks):
            lo = j * self.block_size
            vj = v[lo : lo + self.block_size]
            kcol = (
                self.column_block(j)
                if cached
                else self._compute(self.x, self._rows(j))
            )
            out = out + kcol @ vj
        return out
