"""ZCA whitening.

Reference: nodes/images/ZCAWhitener.scala § ZCAWhitenerEstimator — SVD of
the centered patch matrix; whitening map W = V·(S²/n + εI)^(−1/2)·Vᵀ so
whitened patches stay in the original coordinate system (used on CIFAR
random patches before convolution, pipelines/images/cifar/RandomPatchCifar.scala).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import sdot


class ZCAWhitener(Transformer):
    traced_attrs = ("whitener", "mean")

    def __init__(self, whitener: jnp.ndarray, mean: jnp.ndarray):
        self.whitener = whitener  # (d, d)
        self.mean = mean  # (d,)

    def apply_batch(self, xs, mask=None):
        return (xs - self.mean) @ self.whitener

    def apply_one(self, x):
        return (x - self.mean) @ self.whitener


class ZCAWhitenerEstimator(Estimator):
    def __init__(self, eps: float = 1e-1):
        self.eps = float(eps)

    def params(self):
        return (self.eps,)

    def fit_dataset(self, data: Dataset) -> ZCAWhitener:
        w, m = _zca_fit(data.array, jnp.float32(data.n), self.eps)
        return ZCAWhitener(w, m)

    def fit_arrays(self, x) -> ZCAWhitener:
        x = jnp.asarray(x, jnp.float32)
        w, m = _zca_fit(x, jnp.float32(x.shape[0]), self.eps)
        return ZCAWhitener(w, m)


@jax.jit
def _zca_fit(x, n, eps):
    mean = jnp.sum(x, axis=0) / n
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
    xc = (x - mean) * row_ok
    cov = sdot(xc.T, xc) / n
    evals, evecs = jnp.linalg.eigh(cov)
    inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(evals, 0.0) + eps)
    whitener = (evecs * inv_sqrt) @ evecs.T
    return whitener, mean
