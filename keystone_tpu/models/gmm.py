"""Diagonal-covariance Gaussian mixture model (EM).

Reference: nodes/learning/GaussianMixtureModel.scala §
GaussianMixtureModelEstimator — the Fisher-vector vocabulary model.  The
reference's production path is the native EncEval C++ EM
(utils/external/EncEval.scala via JNI, SURVEY.md §2.8); this is its
TPU-native replacement: EM as a jitted lax.scan whose E-step
responsibilities come from one log-density gemm and whose M-step
sufficient statistics contract over the row-sharded axis (the treeReduce).

Initialization: k-means++ centers, global variance — deterministic given
the seed, like the reference's seeded sampling.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain
from keystone_tpu.models.kmeans import _kmeans_fit
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import sdot

_LOG2PI = 1.8378770664093453


def _log_gaussians(x, means, variances, log_weights, dot=None):
    """(n, K) log w_k + log N(x; μ_k, diag σ²_k) via gemm expansion.

    ``dot`` overrides the two gemms — the Fisher-vector bf16 apply path
    passes utils/precision.apply_dot so the posterior contractions ride
    the policy; the default plain ``@`` keeps EM solver math (and every
    other caller) bit-identical to before."""
    if dot is None:
        dot = lambda a, b: a @ b  # noqa: E731 - the inert gemm, verbatim
    inv = 1.0 / variances  # (K, d)
    # ‖(x−μ)/σ‖² = Σ x²/σ² − 2 Σ xμ/σ² + Σ μ²/σ²
    quad = (
        dot(x * x, inv.T)
        - 2.0 * dot(x, (means * inv).T)
        + jnp.sum(means * means * inv, axis=1)
    )
    log_norm = -0.5 * (jnp.sum(jnp.log(variances), axis=1) + x.shape[1] * _LOG2PI)
    return log_weights + log_norm - 0.5 * quad


class GaussianMixtureModel(Transformer):
    """Posterior responsibilities transformer; carries (weights, means,
    variances) for Fisher-vector encoding."""

    traced_attrs = ("weights", "means", "variances")

    def __init__(self, weights, means, variances):
        self.weights = weights  # (K,)
        self.means = means  # (K, d)
        self.variances = variances  # (K, d)

    @property
    def k(self):
        return self.means.shape[0]

    def log_responsibilities(self, x):
        lg = _log_gaussians(x, self.means, self.variances, jnp.log(self.weights))
        return lg - jax.scipy.special.logsumexp(lg, axis=1, keepdims=True)

    def apply_batch(self, xs, mask=None):
        r = jnp.exp(self.log_responsibilities(xs))
        return (r, mask) if mask is not None else r

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


# Pytree registration lets a fitted GMM ride as a TRACED jit argument
# (FisherVector.traced_attrs carries the whole model object), so its
# arrays are never embedded as program constants — see
# Transformer.traced_attrs for the measured lowering/compile-cache cost
# of device-array closure constants.
jax.tree_util.register_pytree_node(
    GaussianMixtureModel,
    lambda g: ((g.weights, g.means, g.variances), None),
    lambda _, c: GaussianMixtureModel(*c),
)


class GaussianMixtureModelEstimator(Estimator):
    def __init__(
        self,
        k: int,
        max_iterations: int = 50,
        min_variance: float = 1e-6,
        seed: int = 0,
        kmeans_iters: int = 10,
    ):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.min_variance = float(min_variance)
        self.seed = int(seed)
        self.kmeans_iters = int(kmeans_iters)

    def params(self):
        return (
            self.k,
            self.max_iterations,
            self.min_variance,
            self.seed,
            self.kmeans_iters,
        )

    def fit_dataset(self, data: Dataset) -> GaussianMixtureModel:
        from keystone_tpu.obs import ledger

        obs = ledger.solver_obs()
        x = data.array
        if data.mask is not None:
            # ragged prep (flatten, mask, true count) lives INSIDE
            # _gmm_fit's jit — one program, not two
            w, m, v = _gmm_fit(
                x, None, data.mask, self.k, self.max_iterations,
                self.min_variance, self.seed, self.kmeans_iters, obs=obs,
            )
        else:
            # row mask + PRNG key are built INSIDE _gmm_fit (row_ok=None)
            # — eager, the iota/less/convert/threefry preamble was 4 tiny
            # compiled programs per fit (r5 call-site attribution)
            w, m, v = _gmm_fit(
                x, float(data.n), None, self.k, self.max_iterations,
                self.min_variance, self.seed, self.kmeans_iters, obs=obs,
            )
        return GaussianMixtureModel(w, m, v)

    def fit_arrays(self, x) -> GaussianMixtureModel:
        from keystone_tpu.obs import ledger

        x = jnp.asarray(x, jnp.float32)
        w, m, v = _gmm_fit(
            x, float(x.shape[0]), None, self.k, self.max_iterations,
            self.min_variance, self.seed, self.kmeans_iters,
            obs=ledger.solver_obs(),
        )
        return GaussianMixtureModel(w, m, v)


@partial(jax.jit, static_argnames=("iters", "obs"))
def _em_steps(x, n, row_ok, w0, mu0, var0, iters, min_var, obs=False):
    """``iters`` EM steps from a given initial GMM (the deterministic part
    of the fit; also the contract of the native C++ EM in
    ops/fisher_ffi.py § gmm_em_ffi, which parity-tests against this).

    ``obs`` (static): per-EM-iteration ``solver.epoch`` telemetry (mean
    log-likelihood — the logsumexp is already computed for the E-step,
    so the extra cost is one masked reduction) via
    ``jax.debug.callback``; the inert program carries no callbacks."""

    def em(carry, it):
        w, mu, var = carry
        lg = _log_gaussians(x, mu, var, jnp.log(w))
        lse = jax.scipy.special.logsumexp(lg, axis=1, keepdims=True)
        lr = lg - lse
        r = jnp.exp(lr) * row_ok[:, None]  # (n, K)
        nk = constrain(jnp.sum(r, axis=0))  # psum over 'data'
        nk = jnp.maximum(nk, 1e-10)
        mu_new = constrain(sdot(r.T, x)) / nk[:, None]
        ex2 = constrain(sdot(r.T, x * x)) / nk[:, None]
        var_new = jnp.maximum(ex2 - mu_new * mu_new, min_var)
        w_new = nk / n
        if obs:
            from keystone_tpu.obs import ledger

            loglik = constrain(jnp.sum(lse[:, 0] * row_ok)) / n
            jax.debug.callback(
                ledger.solver_callback("gmm", "epoch", "mean_log_likelihood"),
                it,
                loglik,
            )
        return (w_new, mu_new, var_new), None

    # xs only when observing — the inert program stays byte-identical
    # to the pre-obs one (see models/kmeans.py)
    if obs:
        (w, mu, var), _ = lax.scan(em, (w0, mu0, var0), jnp.arange(iters))
    else:
        (w, mu, var), _ = lax.scan(em, (w0, mu0, var0), None, length=iters)
    return w, mu, var


@partial(jax.jit, static_argnames=("k", "iters", "kmeans_iters", "obs"))
def _gmm_fit(x, n, row_ok, k, iters, min_var, seed, kmeans_iters, obs=False):
    # the eager preambles (ragged flatten/mask/count; dense iota/less;
    # PRNGKey) were ~7 extra compiled programs per fit, each a ~0.1 s
    # compile-cache RPC on the tunneled backend (r5 call-site
    # attribution) — all live inside this one program now
    if row_ok is not None and row_ok.ndim == 2:  # ragged (n,max_k) mask
        x = x.reshape(-1, x.shape[-1])
        valid = (row_ok.reshape(-1) > 0).astype(jnp.float32)
        x = x * valid[:, None]
        n = jnp.sum(valid)
        row_ok = valid
    elif row_ok is not None:  # 1-D row mask (n,): valid-row indicator
        # n may arrive as None (fit_dataset's mask branch) — derive it
        # from the mask, and zero masked rows so they can't leak into
        # the moment sums (the pre-r5 handling, regressed when the
        # ragged path was fused into this jit)
        row_ok = (row_ok.reshape(-1) > 0).astype(jnp.float32)
        x = x * row_ok[:, None]
        if n is None:
            n = jnp.sum(row_ok)
    elif row_ok is None:
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)
    key = jax.random.PRNGKey(seed)
    x = constrain(x.astype(jnp.float32), DATA_AXIS)
    means0 = _kmeans_fit(x, row_ok, k, kmeans_iters, key, obs=obs)
    gmean = jnp.sum(x * row_ok[:, None], axis=0) / n
    gvar = jnp.sum((x - gmean) ** 2 * row_ok[:, None], axis=0) / n
    var0 = jnp.tile(jnp.maximum(gvar, min_var)[None, :], (k, 1))
    w0 = jnp.full((k,), 1.0 / k, jnp.float32)
    return _em_steps(x, n, row_ok, w0, means0, var0, iters, min_var, obs=obs)
