"""Logistic regression via L-BFGS.

Reference: nodes/learning/LogisticRegressionEstimator.scala — wraps Spark
MLlib's LogisticRegressionWithLBFGS (Amazon-reviews pipeline).  Here the
softmax cross-entropy objective plugs directly into the same jitted
L-BFGS machinery as the least-squares solvers; gradients contract over the
row-sharded batch (all-reduce over ICI).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.common import constrain
from keystone_tpu.models.lbfgs import lbfgs_minimize
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer


class LogisticRegressionModel(Transformer):
    traced_attrs = ("weights",)

    def __init__(self, weights: jnp.ndarray):
        self.weights = weights  # (d, K)

    def apply_batch(self, xs, mask=None):
        return xs @ self.weights  # logits; MaxClassifier takes argmax

    def apply_one(self, x):
        return x @ self.weights

    def apply_dataset(self, ds):
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows, score_sparse_dataset

        if ds.is_host and is_scipy_sparse_rows(ds.items):
            return score_sparse_dataset(ds, self.weights)
        return super().apply_dataset(ds)

    def predict_proba(self, xs):
        return jax.nn.softmax(xs @ self.weights, axis=-1)


class LogisticRegressionEstimator(LabelEstimator):
    """labels: int class ids (n,) or indicator matrix (n, K)."""

    def __init__(
        self,
        num_classes: int,
        lam: float = 0.0,
        num_iters: int = 100,
        history: int = 10,
    ):
        self.num_classes = int(num_classes)
        self.lam = float(lam)
        self.num_iters = int(num_iters)
        self.history = int(history)

    def params(self):
        return (self.num_classes, self.lam, self.num_iters, self.history)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("LogisticRegressionEstimator requires labels")
        # sparse text (MLlib's logreg consumed SparseVectors; same role):
        # host CSR rows fit via gather/scatter gradients, never
        # densified; rows are nnz-BUCKETED so one dense document can't
        # inflate the whole corpus's padding
        from keystone_tpu.ops.sparse import (
            BucketedSparseRows,
            is_scipy_sparse_rows,
        )

        if data.is_host and is_scipy_sparse_rows(data.items):
            sp = BucketedSparseRows.from_scipy_rows(data.items)
            return self.fit_sparse(sp, labels.array, n=data.n)
        return self._fit(data.array, labels.array, data.n)

    def fit_sparse(self, sp, y, n: Optional[int] = None):
        """Fit from a PaddedSparseRows or BucketedSparseRows matrix."""
        from keystone_tpu.ops.sparse import bucketize_with_labels, host_onehot

        onehot = host_onehot(y, self.num_classes)
        bidx, bvals, boh, n, d, brow_ok = bucketize_with_labels(sp, onehot, n=n)
        w = _logreg_fit_sparse(
            bidx,
            bvals,
            boh,
            brow_ok,
            jnp.float32(n),
            d,
            self.lam,
            self.num_iters,
            self.history,
        )
        return LogisticRegressionModel(w)

    def _onehot(self, y):
        y = jnp.asarray(y)
        if y.ndim == 1:
            return jax.nn.one_hot(y.astype(jnp.int32), self.num_classes)
        return (y > 0).astype(jnp.float32)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        onehot = self._onehot(y)
        w = _logreg_fit(
            jnp.asarray(x, jnp.float32),
            onehot,
            jnp.float32(n),
            self.lam,
            self.num_iters,
            self.history,
        )
        return LogisticRegressionModel(w)


@partial(jax.jit, static_argnames=("num_iters", "history"))
def _logreg_fit(x, onehot, n, lam, num_iters, history):
    x = constrain(x, DATA_AXIS)
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)
    onehot = onehot * row_ok[:, None]

    def value_and_grad(w):
        logits = x @ w
        lse = jax.scipy.special.logsumexp(logits, axis=1)
        ll = jnp.sum(logits * onehot, axis=1) - lse * row_ok
        f = -jnp.sum(ll) / n + 0.5 * lam * jnp.vdot(w, w)
        p = jax.nn.softmax(logits, axis=1) * row_ok[:, None]
        g = constrain(x.T @ (p - onehot)) / n + lam * w
        return f, g

    w0 = jnp.zeros((x.shape[1], onehot.shape[1]), jnp.float32)
    return lbfgs_minimize(value_and_grad, w0, max_iter=num_iters, history=history)


@partial(jax.jit, static_argnames=("d", "num_iters", "history"))
def _logreg_fit_sparse(bidx, bvals, bonehot, brow_ok, n, d, lam, num_iters, history):
    """Softmax CE on bucketed COO features: forward = gather-matvec,
    gradient = scatter-add (same sparse primitives as the LS solver),
    summed over nnz buckets (row order is loss-irrelevant).  Padding
    entries have value 0 and padding rows have zero one-hots, so neither
    contributes to loss or gradient — EXCEPT the softmax's normalizer,
    which is why padding rows are masked explicitly via ``brow_ok``, the
    per-bucket valid-row masks (TRACED — counts must not recompile)."""
    from keystone_tpu.ops.sparse import sparse_grad, sparse_matmul

    bidx = tuple(constrain(i, DATA_AXIS) for i in bidx)
    bvals = tuple(constrain(v, DATA_AXIS) for v in bvals)
    bonehot = tuple(constrain(o, DATA_AXIS) for o in bonehot)
    row_oks = tuple(constrain(m, DATA_AXIS) for m in brow_ok)

    def value_and_grad(w):
        f = 0.5 * lam * jnp.vdot(w, w)
        g = lam * w
        for idx, vals, onehot, row_ok in zip(bidx, bvals, bonehot, row_oks):
            logits = sparse_matmul(idx, vals, w)
            lse = jax.scipy.special.logsumexp(logits, axis=1)
            ll = jnp.sum(logits * onehot, axis=1) - lse * row_ok
            f = f - jnp.sum(ll) / n
            p = jax.nn.softmax(logits, axis=1) * row_ok[:, None]
            g = g + constrain(sparse_grad(idx, vals, p - onehot, d)) / n
        return f, g

    w0 = jnp.zeros((d, bonehot[0].shape[1]), jnp.float32)
    return lbfgs_minimize(value_and_grad, w0, max_iter=num_iters, history=history)
