"""Shared solver numerics."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as _mesh


def solve_spd(A: jnp.ndarray, B: jnp.ndarray, reg: float = 0.0) -> jnp.ndarray:
    """Solve (A + reg·I) X = B for symmetric positive-definite A via
    Cholesky — the on-device replacement for every reference driver-side
    ``cholesky(... + λI) \\ ...`` (e.g. nodes/learning/BlockLeastSquares.scala)."""
    d = A.shape[0]
    A = A + reg * jnp.eye(d, dtype=A.dtype)
    c, lower = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve((c, lower), B)


def constrain(x, *spec):
    """Sharding-constrain ``x`` to PartitionSpec(*spec) on the current mesh."""
    mesh = _mesh.current_mesh()
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def xtx_xty(x: jnp.ndarray, y: jnp.ndarray):
    """Replicated (XᵀX, XᵀY) from row-sharded X, Y.

    The reference's per-partition gemm + treeReduce pair (SURVEY.md §3.2);
    zero padding rows contribute nothing, so padded Datasets are safe.
    """
    from keystone_tpu.parallel.collectives import sharded_gram, sharded_matmul

    return sharded_gram(x), sharded_matmul(x, y)
