"""Shared solver numerics."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as _mesh


def solve_spd(A: jnp.ndarray, B: jnp.ndarray, reg: float = 0.0) -> jnp.ndarray:
    """Solve (A + reg·I) X = B for symmetric positive-definite A via
    Cholesky — the on-device replacement for every reference driver-side
    ``cholesky(... + λI) \\ ...`` (e.g. nodes/learning/BlockLeastSquares.scala)."""
    d = A.shape[0]
    A = A + reg * jnp.eye(d, dtype=A.dtype)
    c, lower = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve((c, lower), B)


def constrain(x, *spec):
    """Sharding-constrain ``x`` to PartitionSpec(*spec) on the current mesh."""
    mesh = _mesh.current_mesh()
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def xtx_xty(x: jnp.ndarray, y: jnp.ndarray):
    """Replicated (XᵀX, XᵀY) from row-sharded X, Y.

    The reference's per-partition gemm + treeReduce pair (SURVEY.md §3.2);
    zero padding rows contribute nothing, so padded Datasets are safe.
    """
    from keystone_tpu.parallel.collectives import sharded_gram, sharded_matmul

    return sharded_gram(x), sharded_matmul(x, y)


def kahan_add(s, c, inc):
    """One compensated-summation step: returns (new_sum, new_compensation).
    Used by the streaming (out-of-core) fits so accumulator rounding error
    stays O(ε) instead of growing with batch count.  XLA does not
    reassociate floats by default, so the compensation survives jit."""
    y = inc - c
    t = s + y
    return t, (t - s) - y


def stage_stream_batch(*host_arrays):
    """Host batch arrays → mesh-sharded device arrays, true row count, and
    a pad-row mask, with the row capacity bucketed to the next power of
    two.  Bucketing bounds jit recompiles for variable-size streams to
    O(log max_batch) shapes instead of one per distinct size; zero pad
    rows are masked by ``row_ok`` wherever sums would see them."""
    bn = int(np.shape(host_arrays[0])[0])
    cap = 1 << max(0, (bn - 1)).bit_length()  # next pow2 >= bn
    staged = []
    for a in host_arrays:
        a = np.asarray(a, np.float32)
        if cap != a.shape[0]:
            a = np.pad(a, [(0, cap - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
        staged.append(_mesh.shard_batch(a))
    row_ok = (jnp.arange(staged[0].shape[0]) < bn).astype(jnp.float32)[:, None]
    return (*staged, bn, row_ok)
