"""Multinomial naive Bayes.

Reference: nodes/learning/NaiveBayes.scala § NaiveBayesEstimator — a port
of MLlib's multinomial NB used as the Newsgroups pipeline's alternative
head.  Log priors + smoothed log conditionals; the model transformer
outputs per-class log-posterior scores (argmax-compatible with
MaxClassifier).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.common import constrain
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer


class NaiveBayesModel(Transformer):
    def __init__(self, log_prior: jnp.ndarray, log_cond: jnp.ndarray):
        self.log_prior = log_prior  # (K,)
        self.log_cond = log_cond  # (K, d)

    def apply_batch(self, xs, mask=None):
        return xs @ self.log_cond.T + self.log_prior

    def apply_one(self, x):
        return x @ self.log_cond.T + self.log_prior


class NaiveBayesEstimator(LabelEstimator):
    """labels: int class ids (n,) or one-hot/±1 indicator matrix (n, K)."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = int(num_classes)
        self.lam = float(lam)  # additive smoothing

    def params(self):
        return (self.num_classes, self.lam)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("NaiveBayesEstimator requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        lp, lc = _nb_fit(x, _to_onehot(y, self.num_classes), jnp.float32(n), self.lam)
        return NaiveBayesModel(lp, lc)


def _to_onehot(y, k):
    y = jnp.asarray(y)
    if y.ndim == 1:
        return jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
    return (y > 0).astype(jnp.float32)


@jax.jit
def _nb_fit(x, onehot, n, lam):
    x = constrain(x.astype(jnp.float32), DATA_AXIS)
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)
    onehot = onehot * row_ok[:, None]
    class_counts = constrain(jnp.sum(onehot, axis=0))  # (K,)
    feat_counts = constrain(onehot.T @ x)  # (K, d) — treeAggregate analogue
    log_prior = jnp.log(jnp.maximum(class_counts, 1e-10)) - jnp.log(n)
    smoothed = feat_counts + lam
    log_cond = jnp.log(smoothed) - jnp.log(
        jnp.sum(smoothed, axis=1, keepdims=True)
    )
    return log_prior, log_cond
