"""Multinomial naive Bayes.

Reference: nodes/learning/NaiveBayes.scala § NaiveBayesEstimator — a port
of MLlib's multinomial NB used as the Newsgroups pipeline's alternative
head.  Log priors + smoothed log conditionals; the model transformer
outputs per-class log-posterior scores (argmax-compatible with
MaxClassifier).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.common import constrain
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer


class NaiveBayesModel(Transformer):
    traced_attrs = ("log_prior", "log_cond")

    def __init__(self, log_prior: jnp.ndarray, log_cond: jnp.ndarray):
        self.log_prior = log_prior  # (K,)
        self.log_cond = log_cond  # (K, d)

    def apply_batch(self, xs, mask=None):
        return xs @ self.log_cond.T + self.log_prior

    def apply_one(self, x):
        return x @ self.log_cond.T + self.log_prior

    def apply_dataset(self, ds):
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows, score_sparse_dataset

        if ds.is_host and is_scipy_sparse_rows(ds.items):
            return score_sparse_dataset(ds, self.log_cond.T, self.log_prior)
        return super().apply_dataset(ds)


class NaiveBayesEstimator(LabelEstimator):
    """labels: int class ids (n,) or one-hot/±1 indicator matrix (n, K)."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = int(num_classes)
        self.lam = float(lam)  # additive smoothing

    def params(self):
        return (self.num_classes, self.lam)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("NaiveBayesEstimator requires labels")
        # sparse counts: the sufficient statistic onehotᵀX is a
        # scatter-add over the COO entries — never densify n×d.  Rows
        # are nnz-BUCKETED so one dense document doesn't inflate the
        # whole corpus's padding (the count sum is row-permutation
        # invariant, so summing per-bucket contributions is exact).
        from keystone_tpu.ops.sparse import (
            BucketedSparseRows,
            bucketize_with_labels,
            is_scipy_sparse_rows,
        )

        if data.is_host and is_scipy_sparse_rows(data.items):
            from keystone_tpu.ops.sparse import host_onehot

            sp = BucketedSparseRows.from_scipy_rows(data.items)
            # host one-hot: labels get permuted in numpy next, so a
            # device one-hot would round-trip the tunnel for nothing
            onehot = host_onehot(labels.numpy(), self.num_classes)
            bidx, bvals, boh, n, d, _row_ok = bucketize_with_labels(
                sp, onehot, n=data.n
            )
            lp, lc = _nb_fit_sparse(
                bidx, bvals, boh, jnp.float32(n), d, self.lam
            )
            return NaiveBayesModel(lp, lc)
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        lp, lc = _nb_fit(x, _to_onehot(y, self.num_classes), jnp.float32(n), self.lam)
        return NaiveBayesModel(lp, lc)


def _to_onehot(y, k):
    y = jnp.asarray(y)
    if y.ndim == 1:
        return jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
    return (y > 0).astype(jnp.float32)


@partial(jax.jit, static_argnames=("d",))
def _nb_fit_sparse(bidx, bvals, bonehot, n, d, lam):
    """Sparse multinomial NB: feat_counts = (Xᵀ·onehot)ᵀ via scatter-add
    on bucketed COO entries (sparse_grad per bucket, summed — bucket
    values/labels are pre-zeroed on padding rows); identical math to
    _nb_fit."""
    from keystone_tpu.ops.sparse import sparse_grad

    class_counts = jnp.zeros((bonehot[0].shape[1],), jnp.float32)
    feat_counts = jnp.zeros((bonehot[0].shape[1], d), jnp.float32)
    for idx, vals, onehot in zip(bidx, bvals, bonehot):
        idx = constrain(idx, DATA_AXIS)
        vals = constrain(vals, DATA_AXIS)
        onehot = constrain(onehot, DATA_AXIS)
        class_counts = class_counts + jnp.sum(onehot, axis=0)
        feat_counts = feat_counts + sparse_grad(idx, vals, onehot, d).T
    class_counts = constrain(class_counts)
    feat_counts = constrain(feat_counts)
    return _nb_finish(class_counts, feat_counts, n, lam)


@jax.jit
def _nb_fit(x, onehot, n, lam):
    x = constrain(x.astype(jnp.float32), DATA_AXIS)
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)
    onehot = onehot * row_ok[:, None]
    class_counts = constrain(jnp.sum(onehot, axis=0))  # (K,)
    feat_counts = constrain(onehot.T @ x)  # (K, d) — treeAggregate analogue
    return _nb_finish(class_counts, feat_counts, n, lam)


def _nb_finish(class_counts, feat_counts, n, lam):
    """Shared prior/smoothing/log-conditional tail of both fit paths."""
    log_prior = jnp.log(jnp.maximum(class_counts, 1e-10)) - jnp.log(n)
    smoothed = feat_counts + lam
    log_cond = jnp.log(smoothed) - jnp.log(
        jnp.sum(smoothed, axis=1, keepdims=True)
    )
    return log_prior, log_cond
