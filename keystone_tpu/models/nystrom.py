"""Nyström kernel feature approximation — the kernel tier's scale-out.

Reference [fork]: the kernel block-coordinate line (arXiv:1602.05310)
pairs the exact blockwise KRR solver with approximation tiers for the
regime where even out-of-core exact sweeps are too expensive: Nyström
(Williams & Seeger) and random features (the repo already ships
``ops.CosineRandomFeatures`` for the latter).  Nyström samples *m*
landmark rows L from the training set and maps

    φ(x) = K(x, L) · (K_LL + εI)^{−1/2}        (m-dim features)

so that φ(x)·φ(z)ᵀ ≈ K(x, z) — the million-row kernel problem becomes a
d=m LINEAR problem that the existing ``BlockLeastSquaresEstimator``
(in-core, out-of-core, checkpointed — the whole PR-7 machinery) solves
as-is.  This is what opens the kernel-TIMIT / kernel-CIFAR scenario
family in ``pipelines/`` without an n×n anything.

Numerics: landmark sampling is seeded and content-independent (uniform
without replacement); the K_LL gram and the whitening solve are
SOLVER-GRADE f32 under every ``KEYSTONE_MATMUL`` mode (registered in
``analysis/precision.SOLVER_ENTRIES``); the *apply* gemms — K(x, L)
and the whitening projection — are scoring, riding the apply precision
policy like every other forward op.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer


@jax.jit
def _nystrom_whiten(lmk, gamma, reg):
    """(K_LL + reg·m·I)^{−1/2} via a symmetric eigendecomposition —
    solver math: the gram gemm is solver-grade (sdot) and the
    eigenbasis projection accumulates f32.  ``reg`` scales with m the
    way the KRR solve's λn does, so the floor is shape-independent."""
    kern = GaussianKernelGenerator(gamma)  # solver_grade=True
    m = lmk.shape[0]
    kmm = kern(lmk, lmk)
    kmm = 0.5 * (kmm + kmm.T) + reg * m * jnp.eye(m, dtype=jnp.float32)
    evals, evecs = jnp.linalg.eigh(kmm)
    # clamp: K_LL is PSD up to rounding; a tiny negative eigenvalue must
    # not turn the whitening into NaNs
    inv_sqrt = evecs * jax.lax.rsqrt(jnp.maximum(evals, 1e-12))[None, :]
    return jnp.dot(
        inv_sqrt, evecs.T, preferred_element_type=jnp.float32
    )


class NystromFeatureMap(Transformer):
    """φ(x) = K(x, L)·W for fitted landmarks L and whitening W.

    Scoring, not solving: the K(x, L) gram rides the apply precision
    policy (the Pallas gram megakernel streams it bf16 on capable
    backends under ``bf16``/``bf16_apply``) and the whitening
    projection goes through ``precision.apply_dot``."""

    traced_attrs = ("landmarks", "whiten")

    def __init__(self, kernel_gen, landmarks, whiten):
        self.kernel_gen = kernel_gen
        self.landmarks = landmarks  # (m, d) f32
        self.whiten = whiten  # (m, m) f32

    def jit_static(self):
        return (float(self.kernel_gen.gamma),)

    def apply_batch(self, xs, mask=None):
        from keystone_tpu.ops import gram_pallas
        from keystone_tpu.utils import precision

        xs = xs.astype(jnp.float32)
        mode = precision.apply_mode()
        knm = gram_pallas.gram_block(
            xs,
            self.landmarks,
            float(self.kernel_gen.gamma),
            solver_grade=False,
            mxu=mode,
        )
        return precision.apply_dot(knm, self.whiten, mode=mode)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class NystromFeatures(Estimator):
    """Landmark sampling + whitening solve; the fitted transformer is a
    :class:`NystromFeatureMap` whose output feeds any linear solver
    (canonically ``BlockLeastSquaresEstimator``).

    ``num_landmarks`` rows are drawn uniformly without replacement with
    a seeded rng.  Fitting from a (non-host) ``StreamDataset`` never
    materializes the stream: the sampled global row indices are chosen
    up front (``data.n`` is known) and collected in ONE pass over the
    batches — the out-of-core landmark path the million-row recipes
    use.  K_nm itself is never formed at fit time; it streams at apply
    time batch by batch through the pipeline machinery."""

    def __init__(
        self,
        kernel_gen: GaussianKernelGenerator,
        num_landmarks: int = 1024,
        reg: float = 1e-6,
        seed: int = 0,
    ):
        self.kernel_gen = kernel_gen
        self.num_landmarks = int(num_landmarks)
        self.reg = float(reg)
        self.seed = int(seed)

    def params(self):
        return (
            self.kernel_gen.gamma,
            self.num_landmarks,
            self.reg,
            self.seed,
        )

    def fit_dataset(self, data: Dataset):
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            if data.is_host:
                raise TypeError(
                    "host-payload stream reached NystromFeatures; "
                    "featurize to arrays before the fit"
                )
            return self._fit_landmarks(self._sample_stream(data))
        return self.fit_arrays(data.array[: data.n])

    def fit_arrays(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        m = min(self.num_landmarks, n)
        idx = np.sort(
            np.random.default_rng(self.seed).choice(n, size=m, replace=False)
        )
        return self._fit_landmarks(x[idx])

    def _sample_stream(self, data) -> np.ndarray:
        """One streaming pass collecting the pre-chosen landmark rows:
        indices are sampled against the KNOWN row count, so the draw is
        identical to the in-core path on the same seed."""
        n = data.n
        m = min(self.num_landmarks, n)
        idx = np.sort(
            np.random.default_rng(self.seed).choice(n, size=m, replace=False)
        )
        rows = []
        offset = 0
        take = 0  # cursor into the sorted index list
        for batch in data.batches():
            batch = np.asarray(batch, np.float32)
            hi = offset + batch.shape[0]
            while take < m and idx[take] < hi:
                rows.append(batch[idx[take] - offset])
                take += 1
            offset = hi
            if take >= m:
                break
        if take < m:
            raise ValueError(
                f"stream delivered {offset} rows; cannot sample "
                f"{m} landmarks from a declared n={n}"
            )
        return np.stack(rows)

    def _fit_landmarks(self, lmk: np.ndarray) -> NystromFeatureMap:
        lmk = jnp.asarray(lmk, jnp.float32)
        from keystone_tpu.obs import ledger

        whiten = ledger.device_wait(
            _nystrom_whiten(
                lmk,
                jnp.float32(self.kernel_gen.gamma),
                jnp.float32(self.reg),
            )
        )
        return NystromFeatureMap(self.kernel_gen, lmk, whiten)
