"""Learning nodes / solvers (reference src/main/scala/nodes/learning/).

Every estimator here follows the reference's distributed pattern translated
to TPU (SURVEY.md §3.2): per-partition gemm + treeReduce becomes a sharded
einsum whose contraction over the row-sharded axis XLA lowers to an
all-reduce over ICI; the driver-side Cholesky solve becomes a replicated
on-device solve; broadcast of weights is replicated sharding.
"""

from keystone_tpu.models.linear import (  # noqa: F401
    LeastSquaresEstimator,
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
)
from keystone_tpu.models.block_ls import (  # noqa: F401
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
)
from keystone_tpu.models.block_weighted_ls import (  # noqa: F401
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.models.lbfgs import (  # noqa: F401
    DenseLBFGSwithL2,
    SparseLBFGSwithL2,
    lbfgs_minimize,
)
from keystone_tpu.models.pca import (  # noqa: F401
    DistributedPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from keystone_tpu.models.zca import ZCAWhitener, ZCAWhitenerEstimator  # noqa: F401
from keystone_tpu.models.kmeans import KMeansModel, KMeansPlusPlusEstimator  # noqa: F401
from keystone_tpu.models.gmm import (  # noqa: F401
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.models.naive_bayes import NaiveBayesEstimator, NaiveBayesModel  # noqa: F401
from keystone_tpu.models.logistic import (  # noqa: F401
    LogisticRegressionEstimator,
    LogisticRegressionModel,
)
from keystone_tpu.models.kernel_ridge import (  # noqa: F401
    GaussianKernelGenerator,
    KernelBlockLinearMapper,
    KernelRidgeRegressionEstimator,
    LinearKernelGenerator,
    OutOfCoreKernelBlockLinearMapper,
    PolynomialKernelGenerator,
)
from keystone_tpu.models.nystrom import (  # noqa: F401
    NystromFeatureMap,
    NystromFeatures,
)

# Reference-named aliases (KeystoneML class names without the Estimator
# suffix: nodes/learning/BlockWeightedLeastSquares.scala,
# nodes/learning/KernelRidgeRegression.scala)
BlockWeightedLeastSquares = BlockWeightedLeastSquaresEstimator
KernelRidgeRegression = KernelRidgeRegressionEstimator
