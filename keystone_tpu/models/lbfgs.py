"""Batch L-BFGS with L2 regularization.

Reference: nodes/learning/LBFGS.scala § DenseLBFGSwithL2 /
SparseLBFGSwithL2 with gradient classes (LeastSquaresDenseGradient,
LeastSquaresSparseGradient): per-iteration distributed gradients via
``treeAggregate`` of per-partition gemms, Breeze L-BFGS line search on the
driver.

TPU form: the gradient is a sharded einsum over the row-sharded batch
(all-reduce over ICI), and the *entire* L-BFGS loop — two-loop recursion,
backtracking Armijo line search, rolling (s, y) history — is one jitted
``lax.scan``.  There is no driver: every device runs the identical
replicated optimizer state.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.models.common import constrain
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.utils.precision import sdot


def lbfgs_minimize(
    value_and_grad: Callable,
    x0: jnp.ndarray,
    max_iter: int = 50,
    history: int = 10,
    tol: float = 1e-7,
    max_line_search: int = 20,
):
    """Minimize a smooth function of one array with L-BFGS.

    ``value_and_grad(x) -> (f, g)`` must be jit-traceable.  Returns the
    final iterate.  The whole loop compiles to a single XLA program.

    The iterate and the (m, ·) history buffers are kept FLATTENED: a
    (m, d, k) history pads its k lane dim to the 128-wide TPU tile (1.7×
    extra HBM at k=147 — the difference between fitting and OOM at
    d=10⁶), while (m, d·k) pads only the tail of one axis.
    """
    m = history
    shape = x0.shape
    orig_vag = value_and_grad
    x0 = jnp.asarray(x0).reshape(-1)

    def value_and_grad(x):
        f, g = orig_vag(x.reshape(shape))
        return f, jnp.asarray(g).reshape(-1)

    def dot(a, b):
        return jnp.vdot(a, b)

    def two_loop(g, s_hist, y_hist, rho_hist, count):
        """Standard two-loop recursion over the rolling history."""
        q = g
        alphas = jnp.zeros((m,), jnp.float32)

        def bwd(i, carry):
            q, alphas = carry
            idx = (count - 1 - i) % m
            valid = i < jnp.minimum(count, m)
            a = rho_hist[idx] * dot(s_hist[idx], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a * y_hist[idx]
            return q, alphas.at[idx].set(a)

        q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
        # initial Hessian scaling γ = sᵀy / yᵀy of the newest pair
        newest = (count - 1) % m
        gamma = jnp.where(
            count > 0,
            dot(s_hist[newest], y_hist[newest])
            / jnp.maximum(dot(y_hist[newest], y_hist[newest]), 1e-20),
            1.0,
        )
        r = gamma * q

        def fwd(i, r):
            idx = (count - jnp.minimum(count, m) + i) % m
            valid = i < jnp.minimum(count, m)
            beta = rho_hist[idx] * dot(y_hist[idx], r)
            upd = (alphas[idx] - beta) * s_hist[idx]
            return r + jnp.where(valid, 1.0, 0.0) * upd

        return lax.fori_loop(0, m, fwd, r)

    def line_search(x, f, g, p):
        """Backtracking Armijo (c1=1e-4), halving from t=1."""
        gp = dot(g, p)
        c1 = 1e-4

        def cond(carry):
            t, it, f_new = carry
            return jnp.logical_and(it < max_line_search, f_new > f + c1 * t * gp)

        def body(carry):
            t, it, _ = carry
            t = t * 0.5
            f_new, _ = value_and_grad(x + t * p)
            return t, it + 1, f_new

        f1, _ = value_and_grad(x + p)
        t, _, _ = lax.while_loop(cond, body, (jnp.float32(1.0), 0, f1))
        return t

    def step(carry, _):
        x, f, g, s_hist, y_hist, rho_hist, count, done = carry

        def do_step(_):
            p = -two_loop(g, s_hist, y_hist, rho_hist, count)
            # fall back to steepest descent if p isn't a descent direction
            p = jnp.where(dot(p, g) < 0, p, -g)
            t = line_search(x, f, g, p)
            x_new = x + t * p
            f_new, g_new = value_and_grad(x_new)
            s = x_new - x
            yv = g_new - g
            sy = dot(s, yv)
            idx = count % m
            ok = sy > 1e-10  # curvature condition; skip update otherwise
            s_h = jnp.where(ok, s_hist.at[idx].set(s), s_hist)
            y_h = jnp.where(ok, y_hist.at[idx].set(yv), y_hist)
            r_h = jnp.where(ok, rho_hist.at[idx].set(1.0 / jnp.maximum(sy, 1e-20)), rho_hist)
            cnt = jnp.where(ok, count + 1, count)
            gnorm = jnp.sqrt(dot(g_new, g_new))
            return x_new, f_new, g_new, s_h, y_h, r_h, cnt, gnorm < tol

        def skip(_):
            return x, f, g, s_hist, y_hist, rho_hist, count, done

        carry = lax.cond(done, skip, do_step, None)
        return carry, carry[1]

    f0, g0 = value_and_grad(x0)
    s_hist = jnp.zeros((m, x0.size), jnp.float32)
    y_hist = jnp.zeros((m, x0.size), jnp.float32)
    rho_hist = jnp.zeros((m,), jnp.float32)
    init = (x0, f0, g0, s_hist, y_hist, rho_hist, 0, jnp.array(False))
    (x, f, g, *_), _ = lax.scan(step, init, None, length=max_iter)
    return x.reshape(shape)


class DenseLBFGSwithL2(LabelEstimator):
    """Least-squares loss + L2, minimized with L-BFGS
    (nodes/learning/LBFGS.scala § DenseLBFGSwithL2).

    loss(W) = 1/(2n)·‖XW − Y‖² + (λ/2)·‖W‖²
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 50,
        history: int = 10,
        fit_intercept: bool = False,
    ):
        self.lam = float(lam)
        self.num_iterations = int(num_iterations)
        self.history = int(history)
        self.fit_intercept = fit_intercept

    def params(self):
        return (self.lam, self.num_iterations, self.history, self.fit_intercept)

    def choose_physical(self, sample):
        """Dense vs sparse physical choice (the reference's
        NodeOptimizationRule picking LeastSquaresDenseGradient vs
        LeastSquaresSparseGradient from sampled data): host datasets of
        scipy sparse rows route to the sparse-gradient solver."""
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows

        if (
            type(self) is DenseLBFGSwithL2
            and sample is not None
            and sample.is_host
            and is_scipy_sparse_rows(sample.items)
        ):
            return SparseLBFGSwithL2(
                lam=self.lam,
                num_iterations=self.num_iterations,
                history=self.history,
                # survives the swap: the sparse path models the intercept
                # as an unregularized constant column
                fit_intercept=self.fit_intercept,
            )
        return self

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("DenseLBFGSwithL2 requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        w, b = _lbfgs_least_squares(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(y, jnp.float32),
            jnp.float32(n),
            self.lam,
            self.num_iterations,
            self.history,
            self.fit_intercept,
        )
        return LinearMapper(w, b if self.fit_intercept else None)


class SparseLBFGSwithL2(DenseLBFGSwithL2):
    """Sparse-gradient variant (LBFGS.scala § SparseLBFGSwithL2 /
    LeastSquaresSparseGradient).

    Features stay in COO form, nnz-BUCKETED (ops/sparse.BucketedSparseRows
    — rows grouped by power-of-two nnz caps so one dense-ish document
    doesn't inflate every row's padding), never the dense n×d matrix:
    the forward pass gathers weight rows, the gradient scatter-adds into
    (d, k), both row-chunked so the live intermediate stays bounded at
    any (vocab, k).  At 100k+ vocabulary this is ~3 orders of magnitude
    less memory than densifying, which is exactly how the reference ran
    text at scale.

    ``fit_intercept=True`` augments each row with a constant feature
    (index d, value 1) whose weight is excluded from the L2 penalty —
    the sparse-safe intercept (centering would densify; the constant
    column does not).

    Accepts: a host Dataset of scipy sparse rows (what ``Sparsify``
    emits), a ``PaddedSparseRows``/``BucketedSparseRows`` directly via
    :meth:`fit_sparse`, or — fallback — any dense input, which routes to
    the dense solver so the optimizer's physical-choice rule can still
    select either class name.
    """

    # already the sparse physical form: restore the base hook (the same
    # function object Estimator defines) so NodeChoiceRule's
    # is-overridden guard skips the (expensive) sample execution
    # entirely for nodes that could never swap
    choose_physical = LabelEstimator.choose_physical

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        from keystone_tpu.ops.sparse import (
            BucketedSparseRows,
            is_scipy_sparse_rows,
        )

        if labels is None:
            raise ValueError("SparseLBFGSwithL2 requires labels")
        if data.is_host and is_scipy_sparse_rows(data.items):
            sp = BucketedSparseRows.from_scipy_rows(data.items)
            return self.fit_sparse(sp, labels.array, n=data.n)
        return super().fit_dataset(data, labels)

    def fit_sparse(self, sp, y, n: Optional[int] = None):
        """Fit from a PaddedSparseRows or BucketedSparseRows matrix."""
        from keystone_tpu.ops.sparse import bucketize_with_labels

        d = sp.num_features
        intercept = bool(self.fit_intercept)
        bidx, bvals, by, n, d_aug, _row_ok = bucketize_with_labels(
            sp, y, n=n, intercept=intercept
        )
        k = by[0].shape[1]
        # L-BFGS history is 2·m weight-sized buffers; at text-scale
        # (d=10⁶, k=147 → 0.6 GB per buffer) a fixed m=10 alone exceeds
        # HBM.  Cap m so the history fits in a fraction of the device,
        # trading convergence rate for feasibility (still L-BFGS, just
        # shorter memory).
        from keystone_tpu.workflow.profiling import device_hbm_budget

        per_pair = 2 * d_aug * k * 4
        # 0.2: the line search holds ~6 more weight-sized temporaries
        # (x, g, p, trial iterates, value_and_grad activations) beyond
        # the 2·m history buffers — measured at d=10⁶·k=147, 0.35 OOMed
        hist_fraction = 0.2
        history = min(
            self.history,
            max(2, int(device_hbm_budget(hist_fraction) // per_pair)),
        )
        if history < self.history:
            import logging

            logging.getLogger(__name__).info(
                "sparse L-BFGS: history %d -> %d (weight-sized pairs are "
                "%.2f GB each; keeping them under %d%% of HBM)",
                self.history,
                history,
                per_pair / 2**30,
                int(hist_fraction * 100),
            )
        w = _lbfgs_sparse_least_squares(
            tuple(bidx),
            tuple(bvals),
            tuple(by),
            jnp.float32(n),
            d_aug,
            self.lam,
            self.num_iterations,
            history,
            intercept,
        )
        if intercept:
            return LinearMapper(w[:d], w[d])
        return LinearMapper(w, None)


@partial(
    jax.jit, static_argnames=("d", "num_iterations", "history", "intercept")
)
def _lbfgs_sparse_least_squares(
    bidx, bvals, by, n, d, lam, num_iterations, history, intercept=False
):
    """L-BFGS least squares on bucketed COO features: the model (d, k) is
    replicated; per-iteration work is a row-sharded gather-matvec forward
    and a scatter-add gradient per bucket, all-reduced over the mesh —
    the sparse analogue of the dense path's einsum + psum.  Bucket
    padding rows carry value-0 entries and zero labels, so they
    contribute nothing.  With ``intercept``, the last weight row is the
    unregularized bias of the constant column."""
    from keystone_tpu.ops.sparse import sparse_grad, sparse_matmul

    bidx = tuple(constrain(i, DATA_AXIS) for i in bidx)
    bvals = tuple(constrain(v, DATA_AXIS) for v in bvals)
    by = tuple(constrain(y, DATA_AXIS) for y in by)
    k = by[0].shape[1]
    # L2 mask: exclude the intercept row from the penalty
    if intercept:
        reg = jnp.ones((d, 1), jnp.float32).at[d - 1].set(0.0)
    else:
        reg = jnp.ones((d, 1), jnp.float32)

    def value_and_grad(w):
        wp = w * reg
        f = 0.5 * lam * jnp.vdot(wp, wp)
        g = lam * wp
        for idx, vals, y in zip(bidx, bvals, by):
            r = sparse_matmul(idx, vals, w) - y  # (rows_b, k), row-sharded
            f = f + 0.5 * jnp.vdot(r, r) / n
            g = g + constrain(sparse_grad(idx, vals, r, d)) / n
        return f, g

    w0 = jnp.zeros((d, k), jnp.float32)
    return lbfgs_minimize(
        value_and_grad, w0, max_iter=num_iterations, history=history
    )


@partial(jax.jit, static_argnames=("num_iterations", "history", "fit_intercept"))
def _lbfgs_least_squares(x, y, n, lam, num_iterations, history, fit_intercept):
    if fit_intercept:
        xm = jnp.sum(x, axis=0) / n
        ym = jnp.sum(y, axis=0) / n
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
        x = (x - xm) * row_ok
        y = (y - ym) * row_ok
    x = constrain(x, DATA_AXIS)
    y = constrain(y, DATA_AXIS)

    def value_and_grad(w):
        r = x @ w - y  # (n_rows, k), row-sharded; pad rows are zero
        f = 0.5 * jnp.vdot(r, r) / n + 0.5 * lam * jnp.vdot(w, w)
        g = constrain(sdot(x.T, r)) / n + lam * w
        return f, g

    w0 = jnp.zeros((x.shape[1], y.shape[1]), jnp.float32)
    w = lbfgs_minimize(
        value_and_grad, w0, max_iter=num_iterations, history=history
    )
    b = ym - xm @ w if fit_intercept else jnp.zeros((y.shape[1],), jnp.float32)
    return w, b
