"""Batch L-BFGS with L2 regularization.

Reference: nodes/learning/LBFGS.scala § DenseLBFGSwithL2 /
SparseLBFGSwithL2 with gradient classes (LeastSquaresDenseGradient,
LeastSquaresSparseGradient): per-iteration distributed gradients via
``treeAggregate`` of per-partition gemms, Breeze L-BFGS line search on the
driver.

TPU form: the gradient is a sharded einsum over the row-sharded batch
(all-reduce over ICI), and the *entire* L-BFGS loop — two-loop recursion,
backtracking Armijo line search, rolling (s, y) history — is one jitted
``lax.scan``.  There is no driver: every device runs the identical
replicated optimizer state.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.models.common import constrain
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.utils.precision import sdot


def _lbfgs_machinery(
    vag_of_data: Callable,
    shape,
    m: int,
    tol: float,
    max_line_search: int,
    obs_label: Optional[str] = None,
):
    """``(init, step)`` over FLAT iterates for the L-BFGS loop.

    ``obs_label``: when set, every effective step emits a
    ``solver.epoch`` convergence point (objective + grad norm) to the
    active run ledger via ``jax.debug.callback``.  The label is resolved
    at TRACE time and threaded as a static jit argument by the callers,
    so with observability off the compiled program is exactly the
    pre-obs one (no callbacks, no host traffic).

    ``vag_of_data(data, x) -> (f, g)`` with ``x`` in its ORIGINAL shape;
    ``data`` is an arbitrary pytree threaded through explicitly (rather
    than closed over) so the resumable driver's jitted chunks take the
    feature arrays as arguments — a closure would embed them as XLA
    constants, doubling HBM for large fits.  ``step(data, carry)``
    returns ``(carry, f)`` (scan-compatible); ``init(data, x0_flat)``
    builds the carry ``(x, f, g, s_hist, y_hist, rho_hist, count,
    done)`` — exactly the state a mid-fit checkpoint must persist.
    """

    def value_and_grad(data, x):
        f, g = vag_of_data(data, x.reshape(shape))
        return f, jnp.asarray(g).reshape(-1)

    def dot(a, b):
        return jnp.vdot(a, b)

    def two_loop(g, s_hist, y_hist, rho_hist, count):
        """Standard two-loop recursion over the rolling history."""
        q = g
        alphas = jnp.zeros((m,), jnp.float32)

        def bwd(i, carry):
            q, alphas = carry
            idx = (count - 1 - i) % m
            valid = i < jnp.minimum(count, m)
            a = rho_hist[idx] * dot(s_hist[idx], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a * y_hist[idx]
            return q, alphas.at[idx].set(a)

        q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
        # initial Hessian scaling γ = sᵀy / yᵀy of the newest pair
        newest = (count - 1) % m
        gamma = jnp.where(
            count > 0,
            dot(s_hist[newest], y_hist[newest])
            / jnp.maximum(dot(y_hist[newest], y_hist[newest]), 1e-20),
            1.0,
        )
        r = gamma * q

        def fwd(i, r):
            idx = (count - jnp.minimum(count, m) + i) % m
            valid = i < jnp.minimum(count, m)
            beta = rho_hist[idx] * dot(y_hist[idx], r)
            upd = (alphas[idx] - beta) * s_hist[idx]
            return r + jnp.where(valid, 1.0, 0.0) * upd

        return lax.fori_loop(0, m, fwd, r)

    def line_search(data, x, f, g, p):
        """Backtracking Armijo (c1=1e-4), halving from t=1."""
        gp = dot(g, p)
        c1 = 1e-4

        def cond(carry):
            t, it, f_new = carry
            return jnp.logical_and(it < max_line_search, f_new > f + c1 * t * gp)

        def body(carry):
            t, it, _ = carry
            t = t * 0.5
            f_new, _ = value_and_grad(data, x + t * p)
            return t, it + 1, f_new

        f1, _ = value_and_grad(data, x + p)
        t, _, _ = lax.while_loop(cond, body, (jnp.float32(1.0), 0, f1))
        return t

    def step(data, carry):
        x, f, g, s_hist, y_hist, rho_hist, count, done = carry

        def do_step(_):
            p = -two_loop(g, s_hist, y_hist, rho_hist, count)
            # fall back to steepest descent if p isn't a descent direction
            p = jnp.where(dot(p, g) < 0, p, -g)
            t = line_search(data, x, f, g, p)
            x_new = x + t * p
            f_new, g_new = value_and_grad(data, x_new)
            s = x_new - x
            yv = g_new - g
            sy = dot(s, yv)
            idx = count % m
            ok = sy > 1e-10  # curvature condition; skip update otherwise
            s_h = jnp.where(ok, s_hist.at[idx].set(s), s_hist)
            y_h = jnp.where(ok, y_hist.at[idx].set(yv), y_hist)
            r_h = jnp.where(ok, rho_hist.at[idx].set(1.0 / jnp.maximum(sy, 1e-20)), rho_hist)
            cnt = jnp.where(ok, count + 1, count)
            gnorm = jnp.sqrt(dot(g_new, g_new))
            if obs_label is not None:
                # fires only on EFFECTIVE steps (the cond's done branch
                # skips it), so the ledger series is the true trajectory
                from keystone_tpu.obs import ledger as _ledger

                jax.debug.callback(
                    _ledger.solver_callback(
                        obs_label, "objective", "grad_norm"
                    ),
                    f_new,
                    gnorm,
                )
            return x_new, f_new, g_new, s_h, y_h, r_h, cnt, gnorm < tol

        def skip(_):
            return x, f, g, s_hist, y_hist, rho_hist, count, done

        carry = lax.cond(done, skip, do_step, None)
        return carry, carry[1]

    def init(data, x0_flat):
        f0, g0 = value_and_grad(data, x0_flat)
        s_hist = jnp.zeros((m, x0_flat.size), jnp.float32)
        y_hist = jnp.zeros((m, x0_flat.size), jnp.float32)
        rho_hist = jnp.zeros((m,), jnp.float32)
        return (
            x0_flat,
            f0,
            g0,
            s_hist,
            y_hist,
            rho_hist,
            jnp.int32(0),
            jnp.array(False),
        )

    return init, step


def lbfgs_minimize(
    value_and_grad: Callable,
    x0: jnp.ndarray,
    max_iter: int = 50,
    history: int = 10,
    tol: float = 1e-7,
    max_line_search: int = 20,
    obs_label: Optional[str] = None,
):
    """Minimize a smooth function of one array with L-BFGS.

    ``value_and_grad(x) -> (f, g)`` must be jit-traceable.  Returns the
    final iterate.  The whole loop compiles to a single XLA program.

    The iterate and the (m, ·) history buffers are kept FLATTENED: a
    (m, d, k) history pads its k lane dim to the 128-wide TPU tile (1.7×
    extra HBM at k=147 — the difference between fitting and OOM at
    d=10⁶), while (m, d·k) pads only the tail of one axis.
    """
    shape = jnp.shape(x0)
    init, step = _lbfgs_machinery(
        lambda _, x: value_and_grad(x),
        shape,
        history,
        tol,
        max_line_search,
        obs_label=obs_label,
    )
    carry = init(None, jnp.asarray(x0).reshape(-1))
    (x, *_), _ = lax.scan(
        lambda c, _: step(None, c), carry, None, length=max_iter
    )
    return x.reshape(shape)


def lbfgs_minimize_resumable(
    vag_of_data: Callable,
    data,
    x0,
    max_iter: int,
    history: int,
    tol: float = 1e-7,
    max_line_search: int = 20,
    checkpoint_every: int = 10,
    save_cb=None,
    load_cb=None,
):
    """L-BFGS as a host loop of jitted ``checkpoint_every``-step chunks,
    persisting the FULL optimizer carry (iterate, gradient, s/y/ρ
    history, count) between chunks so an interrupted fit resumes exactly
    (VERDICT r3 weak-3: the reference's text fits run hours; a mid-fit
    kill must not lose everything — nodes/learning/LBFGS.scala had
    Spark lineage underneath it).

    ``load_cb() -> (it_done, host_carry) | None`` and
    ``save_cb(it_done, host_carry)`` own durability (and, in
    multi-process runs, the broadcast of the resume decision — see
    ``_lbfgs_checkpoint_callbacks``).  The trajectory is IDENTICAL to
    :func:`lbfgs_minimize` (same step function; chunking only cuts the
    scan), so resumed == uninterrupted to float tolerance.
    """
    import numpy as np

    shape = jnp.shape(x0)
    init, step = _lbfgs_machinery(
        vag_of_data, shape, history, tol, max_line_search
    )

    # the scan carry is DONATED: chunk N's optimizer state (iterate,
    # gradient, 2·m weight-sized history buffers) lands in chunk N−1's
    # HBM instead of transiently doubling the (2m+2)·d·k footprint at
    # every chunk boundary — at text scale that doubling is GBs.  The
    # caller rebinds `carry` to the output immediately, and save_cb only
    # ever sees the NEW carry.
    @partial(jax.jit, static_argnames=("iters",), donate_argnums=(1,))
    def chunk(data, carry, iters):
        return lax.scan(
            lambda c, _: step(data, c), carry, None, length=iters
        )[0]

    start, carry = 0, None
    if load_cb is not None:
        loaded = load_cb()
        if loaded is not None:
            start, host_carry = loaded
            if start > max_iter:
                # a COMPLETED longer fit's checkpoint: resuming would
                # silently return more-iterated weights for a shorter
                # requested fit — refit from scratch instead (start ==
                # max_iter is fine: same fit re-requested, reuse it)
                start, host_carry = 0, None
            if host_carry is not None:
                carry = tuple(jnp.asarray(a) for a in host_carry)
    if carry is None:
        start = 0
        carry = jax.jit(init)(data, jnp.asarray(x0).reshape(-1))
    from keystone_tpu.obs import ledger, metrics

    observe = ledger.active() is not None
    it = start
    while it < max_iter:
        import time as _time

        t_chunk = _time.perf_counter()
        n_steps = min(checkpoint_every, max_iter - it)
        carry = chunk(data, carry, n_steps)
        it += n_steps
        save_seconds = None
        if save_cb is not None:
            # the DEVICE carry is handed over: at d·k·(2m+2) scale the
            # host copy is GBs, and non-writer processes must not pay it
            # (save_cb converts after its process-index check)
            ledger.device_wait(carry, force=True)
            t_save = _time.perf_counter()
            save_cb(it, carry)
            save_seconds = _time.perf_counter() - t_save
            metrics.observe("solver.checkpoint_save_seconds", save_seconds)
        if observe:
            # per-chunk convergence point from the (replicated) carry;
            # the per-iteration series inside the chunk rides the
            # machinery's own callback when obs_label was threaded
            f, gnorm = _carry_stats(carry[1], carry[2])
            ledger.solver_epoch(
                "lbfgs.chunk",
                it=int(it),
                objective=float(np.asarray(f)),  # lint: allow-host-sync
                grad_norm=float(np.asarray(gnorm)),  # lint: allow-host-sync
                chunk_seconds=_time.perf_counter() - t_chunk,
                checkpoint_save_seconds=save_seconds,
            )
    return carry[0].reshape(shape)


@jax.jit
def _carry_stats(f, g):
    """(objective, ‖g‖) of a resumable-driver carry — one tiny program,
    so the obs-enabled chunk loop never pulls the weight-sized gradient
    to host just to norm it."""
    return f, jnp.sqrt(jnp.vdot(g, g))


def _lbfgs_checkpoint_callbacks(
    checkpoint_dir: str, problem: str, tag: str, flat_size: int, m: int
):
    """(load_cb, save_cb) persisting the L-BFGS carry to
    ``<dir>/lbfgs_<tag>.npz`` through the hardened durable layer
    (utils/durable: atomic tmp+fsync+rename, BLAKE2b sidecar, rolling
    last-good fallback — a corrupt newest checkpoint resumes from the
    previous chunk instead of refitting from scratch), with
    content-fingerprint validation and — multi-process — process 0 alone
    reading and BROADCASTING the resume decision, because every process
    must enter the chunk loop at the same iteration or the collectives
    deadlock.  ``flat_size``/``m`` let every process build the carry
    template locally, so the broadcast pytree has uniform shapes with or
    without a checkpoint on disk."""
    import os

    import numpy as np

    from keystone_tpu.utils import durable

    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"lbfgs_{tag}.npz")
    keys = ("x", "f", "g", "s_hist", "y_hist", "rho_hist", "count", "done")
    template = (
        np.zeros((flat_size,), np.float32),
        np.float32(0),
        np.zeros((flat_size,), np.float32),
        np.zeros((m, flat_size), np.float32),
        np.zeros((m, flat_size), np.float32),
        np.zeros((m,), np.float32),
        np.int32(0),
        np.bool_(False),
    )

    def _valid(z) -> bool:
        if str(z.get("problem")) != problem:
            return False  # a different fit's checkpoint: not corrupt, stale
        carry = tuple(np.asarray(z[k]) for k in keys)
        return all(a.shape == t.shape for a, t in zip(carry, template))

    def _read():
        loaded = durable.load_npz(path, validate=_valid)
        if loaded is None:
            return None  # no valid checkpoint at any depth: fit from scratch
        z, _ = loaded
        return int(z["it"]), tuple(np.asarray(z[k]) for k in keys)

    def load_cb():
        if jax.process_count() == 1:
            return _read()
        from jax.experimental import multihost_utils

        got = _read() if jax.process_index() == 0 else None
        it = int(
            multihost_utils.broadcast_one_to_all(
                np.int32(got[0] if got is not None else -1)
            )
        )
        if it < 0:
            return None
        carry = got[1] if got is not None else template
        carry = multihost_utils.broadcast_one_to_all(
            tuple(np.asarray(a, t.dtype) for a, t in zip(carry, template))
        )
        return it, tuple(carry)

    def save_cb(it, carry):
        # the carry is replicated across processes (deterministic same
        # math everywhere) — one writer suffices, and only it pays the
        # device→host copy
        if jax.process_index() != 0:
            return
        durable.save_npz(
            path,
            dict(
                {k: np.asarray(a) for k, a in zip(keys, carry)},
                it=np.int32(it),
                problem=problem,
            ),
            keep=2,
        )

    return load_cb, save_cb


class DenseLBFGSwithL2(LabelEstimator):
    """Least-squares loss + L2, minimized with L-BFGS
    (nodes/learning/LBFGS.scala § DenseLBFGSwithL2).

    loss(W) = 1/(2n)·‖XW − Y‖² + (λ/2)·‖W‖²
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 50,
        history: int = 10,
        fit_intercept: bool = False,
    ):
        self.lam = float(lam)
        self.num_iterations = int(num_iterations)
        self.history = int(history)
        self.fit_intercept = fit_intercept

    def params(self):
        return (self.lam, self.num_iterations, self.history, self.fit_intercept)

    def choose_physical(self, sample):
        """Dense vs sparse physical choice (the reference's
        NodeOptimizationRule picking LeastSquaresDenseGradient vs
        LeastSquaresSparseGradient from sampled data): host datasets of
        scipy sparse rows route to the sparse-gradient solver."""
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows

        if (
            type(self) is DenseLBFGSwithL2
            and sample is not None
            and sample.is_host
            and is_scipy_sparse_rows(sample.items)
        ):
            return SparseLBFGSwithL2(
                lam=self.lam,
                num_iterations=self.num_iterations,
                history=self.history,
                # survives the swap: the sparse path models the intercept
                # as an unregularized constant column
                fit_intercept=self.fit_intercept,
            )
        return self

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("DenseLBFGSwithL2 requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        from keystone_tpu.obs import ledger

        w, b = ledger.device_wait(
            _lbfgs_least_squares(
                jnp.asarray(x, jnp.float32),
                jnp.asarray(y, jnp.float32),
                jnp.float32(n),
                self.lam,
                self.num_iterations,
                self.history,
                self.fit_intercept,
                obs=ledger.solver_obs(),
            )
        )
        return LinearMapper(w, b if self.fit_intercept else None)

    def fit_checkpointed(
        self,
        data: Dataset,
        labels: Optional[Dataset] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 10,
    ):
        """Fit with mid-fit checkpoint/resume: the optimizer carry
        (iterate, gradient, s/y/ρ history, count) persists every
        ``checkpoint_every`` iterations, and an interrupted fit resumes
        from the last saved carry with the identical trajectory
        (VERDICT r3 weak-3; the BCD solvers' ``fit_checkpointed``
        analogue for the L-BFGS family)."""
        if labels is None:
            raise ValueError("fit_checkpointed requires labels")
        if checkpoint_dir is None:
            return self.fit_dataset(data, labels)
        w, b = _lbfgs_dense_checkpointed(
            data.array,
            labels.array,
            data.n,
            self.lam,
            self.num_iterations,
            self.history,
            self.fit_intercept,
            checkpoint_dir,
            checkpoint_every,
        )
        return LinearMapper(w, b if self.fit_intercept else None)


class SparseLBFGSwithL2(DenseLBFGSwithL2):
    """Sparse-gradient variant (LBFGS.scala § SparseLBFGSwithL2 /
    LeastSquaresSparseGradient).

    Features stay in COO form, nnz-BUCKETED (ops/sparse.BucketedSparseRows
    — rows grouped by power-of-two nnz caps so one dense-ish document
    doesn't inflate every row's padding), never the dense n×d matrix:
    the forward pass gathers weight rows, the gradient scatter-adds into
    (d, k), both row-chunked so the live intermediate stays bounded at
    any (vocab, k).  At 100k+ vocabulary this is ~3 orders of magnitude
    less memory than densifying, which is exactly how the reference ran
    text at scale.

    ``fit_intercept=True`` augments each row with a constant feature
    (index d, value 1) whose weight is excluded from the L2 penalty —
    the sparse-safe intercept (centering would densify; the constant
    column does not).

    Accepts: a host Dataset of scipy sparse rows (what ``Sparsify``
    emits), a ``PaddedSparseRows``/``BucketedSparseRows`` directly via
    :meth:`fit_sparse`, or — fallback — any dense input, which routes to
    the dense solver so the optimizer's physical-choice rule can still
    select either class name.
    """

    # already the sparse physical form: restore the base hook (the same
    # function object Estimator defines) so NodeChoiceRule's
    # is-overridden guard skips the (expensive) sample execution
    # entirely for nodes that could never swap
    choose_physical = LabelEstimator.choose_physical

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        from keystone_tpu.ops.sparse import (
            BucketedSparseRows,
            is_scipy_sparse_rows,
        )

        if labels is None:
            raise ValueError("SparseLBFGSwithL2 requires labels")
        if data.is_host and is_scipy_sparse_rows(data.items):
            sp = BucketedSparseRows.from_scipy_rows(data.items)
            return self.fit_sparse(sp, labels.array, n=data.n)
        return super().fit_dataset(data, labels)

    def _capped_history(self, d_aug: int, k: int) -> int:
        """HBM-capped history length m.  L-BFGS history is 2·m
        weight-sized buffers; at text-scale (d=10⁶, k=147 → 0.6 GB per
        buffer) a fixed m=10 alone exceeds HBM.  Cap m so the history
        fits in a fraction of the device, trading convergence rate for
        feasibility (still L-BFGS, just shorter memory)."""
        from keystone_tpu.workflow.profiling import device_hbm_budget

        per_pair = 2 * d_aug * k * 4
        # 0.2: the line search holds ~6 more weight-sized temporaries
        # (x, g, p, trial iterates, value_and_grad activations) beyond
        # the 2·m history buffers — measured at d=10⁶·k=147, 0.35 OOMed
        hist_fraction = 0.2
        history = min(
            self.history,
            max(2, int(device_hbm_budget(hist_fraction) // per_pair)),
        )
        if history < self.history:
            import logging

            logging.getLogger(__name__).info(
                "sparse L-BFGS: history %d -> %d (weight-sized pairs are "
                "%.2f GB each; keeping them under %d%% of HBM)",
                self.history,
                history,
                per_pair / 2**30,
                int(hist_fraction * 100),
            )
        return history

    def fit_sparse(
        self,
        sp,
        y,
        n: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 10,
    ):
        """Fit from a PaddedSparseRows or BucketedSparseRows matrix.
        With ``checkpoint_dir``, the fit persists the full optimizer
        carry every ``checkpoint_every`` iterations and resumes an
        interrupted run (VERDICT r3 weak-3)."""
        from keystone_tpu.ops.sparse import bucketize_with_labels

        d = sp.num_features
        intercept = bool(self.fit_intercept)
        bidx, bvals, by, n, d_aug, _row_ok = bucketize_with_labels(
            sp, y, n=n, intercept=intercept
        )
        k = by[0].shape[1]
        history = self._capped_history(d_aug, k)
        if checkpoint_dir is None:
            from keystone_tpu.obs import ledger

            w = _lbfgs_sparse_least_squares(
                tuple(bidx),
                tuple(bvals),
                tuple(by),
                jnp.float32(n),
                d_aug,
                self.lam,
                self.num_iterations,
                history,
                intercept,
                obs=ledger.solver_obs(),
            )
        else:
            w = _lbfgs_sparse_checkpointed(
                tuple(bidx),
                tuple(bvals),
                tuple(by),
                n,
                d_aug,
                self.lam,
                self.num_iterations,
                history,
                intercept,
                checkpoint_dir,
                checkpoint_every,
            )
        if intercept:
            return LinearMapper(w[:d], w[d])
        return LinearMapper(w, None)

    def fit_checkpointed(
        self,
        data,
        labels: Optional[Dataset] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 10,
        n: Optional[int] = None,
    ):
        """Sparse fit with mid-fit checkpoint/resume.  ``data`` may be a
        host Dataset of scipy sparse rows (the Sparsify output), a
        Padded/BucketedSparseRows, or dense (routes to the dense
        checkpointed path).  The checkpoint holds the full optimizer
        carry — at 1M-vocab scale the one solver family where a mid-fit
        kill used to lose everything (VERDICT r3 weak-3)."""
        from keystone_tpu.ops.sparse import (
            BucketedSparseRows,
            is_scipy_sparse_rows,
        )

        if labels is None:
            raise ValueError("fit_checkpointed requires labels")
        y = labels.array if isinstance(labels, Dataset) else labels
        if isinstance(data, Dataset):
            if data.is_host and is_scipy_sparse_rows(data.items):
                sp = BucketedSparseRows.from_scipy_rows(data.items)
                n = data.n
            else:
                return super().fit_checkpointed(
                    data, labels, checkpoint_dir, checkpoint_every
                )
        else:
            sp = data
        return self.fit_sparse(
            sp,
            y,
            n=n,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )


def _sparse_vag(data, w, *, d: int, intercept: bool):
    """The ONE sparse least-squares objective body, shared verbatim by
    the single-scan jitted solver and the checkpointed chunked driver —
    a fix applied to one path cannot silently miss the other.

    ``data = (bidx, bvals, by, n, lam)``: the model (d, k) is
    replicated; per-iteration work is a row-sharded gather-matvec
    forward and a scatter-add gradient per bucket, all-reduced over the
    mesh — the sparse analogue of the dense path's einsum + psum.
    Bucket padding rows carry value-0 entries and zero labels, so they
    contribute nothing.  With ``intercept``, the last weight row is the
    unregularized bias of the constant column (excluded from the L2
    penalty)."""
    from keystone_tpu.ops.sparse import sparse_grad, sparse_matmul

    bidx, bvals, by, n, lam = data
    bidx = tuple(constrain(i, DATA_AXIS) for i in bidx)
    bvals = tuple(constrain(v, DATA_AXIS) for v in bvals)
    by = tuple(constrain(y, DATA_AXIS) for y in by)
    if intercept:
        reg = jnp.ones((d, 1), jnp.float32).at[d - 1].set(0.0)
    else:
        reg = jnp.ones((d, 1), jnp.float32)
    wp = w * reg
    f = 0.5 * lam * jnp.vdot(wp, wp)
    g = lam * wp
    for idx, vals, y in zip(bidx, bvals, by):
        r = sparse_matmul(idx, vals, w) - y  # (rows_b, k), row-sharded
        f = f + 0.5 * jnp.vdot(r, r) / n
        g = g + constrain(sparse_grad(idx, vals, r, d)) / n
    return f, g


@partial(
    jax.jit,
    static_argnames=("d", "num_iterations", "history", "intercept", "obs"),
)
def _lbfgs_sparse_least_squares(
    bidx, bvals, by, n, d, lam, num_iterations, history, intercept=False,
    obs=False,
):
    """Single-XLA-program sparse L-BFGS (objective: :func:`_sparse_vag`)."""
    k = by[0].shape[1]
    data = (bidx, bvals, by, n, lam)
    w0 = jnp.zeros((d, k), jnp.float32)
    return lbfgs_minimize(
        lambda w: _sparse_vag(data, w, d=d, intercept=intercept),
        w0,
        max_iter=num_iterations,
        history=history,
        obs_label="lbfgs.sparse" if obs else None,
    )


def _lbfgs_sparse_checkpointed(
    bidx,
    bvals,
    by,
    n,
    d,
    lam,
    num_iterations,
    history,
    intercept,
    checkpoint_dir,
    checkpoint_every,
):
    """Sparse L-BFGS via the resumable chunked driver.  Same math as
    :func:`_lbfgs_sparse_least_squares` (the vag body is identical);
    only the scan is cut into checkpointable chunks."""
    import hashlib

    import numpy as np

    k = by[0].shape[1]
    fp = hashlib.sha256()
    fp.update(
        repr(
            (
                tuple(np.shape(i) for i in bidx),
                tuple(np.shape(yy) for yy in by),
                int(d),
                float(lam),
                float(n),
                bool(intercept),
                int(history),
                "sparse-v1",
            )
        ).encode()
    )
    # first rows of the first bucket pin the data identity.
    # gather_to_host, not np.asarray: bucket values/labels are
    # mesh-sharded and a row's shard may be non-addressable locally
    from keystone_tpu.parallel import multihost as _mh

    fp.update(_mh.gather_to_host(bidx[0][:1]).tobytes())
    fp.update(_mh.gather_to_host(bvals[0][:1]).tobytes())
    fp.update(_mh.gather_to_host(by[0][:1]).tobytes())
    load_cb, save_cb = _lbfgs_checkpoint_callbacks(
        checkpoint_dir, fp.hexdigest(), "sparse", d * k, history
    )
    return lbfgs_minimize_resumable(
        partial(_sparse_vag, d=d, intercept=intercept),
        (
            tuple(bidx),
            tuple(bvals),
            tuple(by),
            jnp.float32(n),
            jnp.float32(lam),
        ),
        jnp.zeros((d, k), jnp.float32),
        max_iter=num_iterations,
        history=history,
        checkpoint_every=checkpoint_every,
        save_cb=save_cb,
        load_cb=load_cb,
    )


@partial(jax.jit, static_argnames=("fit_intercept",))
def _lbfgs_center(x, y, n, fit_intercept):
    """The intercept centering of :func:`_lbfgs_least_squares`, split out
    so the checkpointed driver can run it once ahead of the chunks."""
    if fit_intercept:
        xm = jnp.sum(x, axis=0) / n
        ym = jnp.sum(y, axis=0) / n
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
        return (x - xm) * row_ok, (y - ym) * row_ok, xm, ym
    return (
        x,
        y,
        jnp.zeros((x.shape[1],), jnp.float32),
        jnp.zeros((y.shape[1],), jnp.float32),
    )


def _lbfgs_dense_checkpointed(
    x,
    y,
    n,
    lam,
    num_iterations,
    history,
    fit_intercept,
    checkpoint_dir,
    checkpoint_every,
):
    """Dense L-BFGS via the resumable chunked driver (same math as
    :func:`_lbfgs_least_squares`)."""
    import hashlib

    from keystone_tpu.parallel import multihost as _mh

    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    xc, yc, xm, ym = _lbfgs_center(x, y, jnp.float32(n), bool(fit_intercept))
    d, k = x.shape[1], y.shape[1]
    fp = hashlib.sha256()
    fp.update(
        repr(
            (
                tuple(x.shape),
                tuple(y.shape),
                float(lam),
                int(n),
                bool(fit_intercept),
                int(history),
                "dense-v1",
            )
        ).encode()
    )
    # gather_to_host, not np.asarray: rows may be sharded across
    # processes and a row's shard non-addressable locally
    fp.update(_mh.gather_to_host(x[:1]).tobytes())
    fp.update(_mh.gather_to_host(y[:1]).tobytes())
    load_cb, save_cb = _lbfgs_checkpoint_callbacks(
        checkpoint_dir, fp.hexdigest(), "dense", d * k, history
    )
    w = lbfgs_minimize_resumable(
        _dense_vag,
        (xc, yc, jnp.float32(n), jnp.float32(lam)),
        jnp.zeros((d, k), jnp.float32),
        max_iter=num_iterations,
        history=history,
        checkpoint_every=checkpoint_every,
        save_cb=save_cb,
        load_cb=load_cb,
    )
    b = (
        ym - xm @ w
        if fit_intercept
        else jnp.zeros((y.shape[1],), jnp.float32)
    )
    return w, b


def _dense_vag(data, w):
    """The ONE dense least-squares objective body, shared by the
    single-scan jitted solver and the checkpointed chunked driver.
    ``data = (xc, yc, n, lam)`` with xc/yc pre-centered (pad rows
    zero)."""
    xc, yc, n, lam = data
    xc = constrain(xc, DATA_AXIS)
    yc = constrain(yc, DATA_AXIS)
    r = xc @ w - yc  # (n_rows, k), row-sharded; pad rows are zero
    f = 0.5 * jnp.vdot(r, r) / n + 0.5 * lam * jnp.vdot(w, w)
    g = constrain(sdot(xc.T, r)) / n + lam * w
    return f, g


@partial(
    jax.jit,
    static_argnames=("num_iterations", "history", "fit_intercept", "obs"),
)
def _lbfgs_least_squares(
    x, y, n, lam, num_iterations, history, fit_intercept, obs=False
):
    xc, yc, xm, ym = _lbfgs_center.__wrapped__(x, y, n, fit_intercept)
    data = (xc, yc, n, lam)
    w0 = jnp.zeros((x.shape[1], y.shape[1]), jnp.float32)
    w = lbfgs_minimize(
        lambda w_: _dense_vag(data, w_),
        w0,
        max_iter=num_iterations,
        history=history,
        obs_label="lbfgs.dense" if obs else None,
    )
    b = ym - xm @ w if fit_intercept else jnp.zeros((y.shape[1],), jnp.float32)
    return w, b
