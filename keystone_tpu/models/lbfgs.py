"""Batch L-BFGS with L2 regularization.

Reference: nodes/learning/LBFGS.scala § DenseLBFGSwithL2 /
SparseLBFGSwithL2 with gradient classes (LeastSquaresDenseGradient,
LeastSquaresSparseGradient): per-iteration distributed gradients via
``treeAggregate`` of per-partition gemms, Breeze L-BFGS line search on the
driver.

TPU form: the gradient is a sharded einsum over the row-sharded batch
(all-reduce over ICI), and the *entire* L-BFGS loop — two-loop recursion,
backtracking Armijo line search, rolling (s, y) history — is one jitted
``lax.scan``.  There is no driver: every device runs the identical
replicated optimizer state.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.models.common import constrain
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.utils.precision import sdot


def lbfgs_minimize(
    value_and_grad: Callable,
    x0: jnp.ndarray,
    max_iter: int = 50,
    history: int = 10,
    tol: float = 1e-7,
    max_line_search: int = 20,
):
    """Minimize a smooth function of one array with L-BFGS.

    ``value_and_grad(x) -> (f, g)`` must be jit-traceable.  Returns the
    final iterate.  The whole loop compiles to a single XLA program.
    """
    m = history
    shape = x0.shape

    def dot(a, b):
        return jnp.vdot(a, b)

    def two_loop(g, s_hist, y_hist, rho_hist, count):
        """Standard two-loop recursion over the rolling history."""
        q = g
        alphas = jnp.zeros((m,), jnp.float32)

        def bwd(i, carry):
            q, alphas = carry
            idx = (count - 1 - i) % m
            valid = i < jnp.minimum(count, m)
            a = rho_hist[idx] * dot(s_hist[idx], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a * y_hist[idx]
            return q, alphas.at[idx].set(a)

        q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
        # initial Hessian scaling γ = sᵀy / yᵀy of the newest pair
        newest = (count - 1) % m
        gamma = jnp.where(
            count > 0,
            dot(s_hist[newest], y_hist[newest])
            / jnp.maximum(dot(y_hist[newest], y_hist[newest]), 1e-20),
            1.0,
        )
        r = gamma * q

        def fwd(i, r):
            idx = (count - jnp.minimum(count, m) + i) % m
            valid = i < jnp.minimum(count, m)
            beta = rho_hist[idx] * dot(y_hist[idx], r)
            upd = (alphas[idx] - beta) * s_hist[idx]
            return r + jnp.where(valid, 1.0, 0.0) * upd

        return lax.fori_loop(0, m, fwd, r)

    def line_search(x, f, g, p):
        """Backtracking Armijo (c1=1e-4), halving from t=1."""
        gp = dot(g, p)
        c1 = 1e-4

        def cond(carry):
            t, it, f_new = carry
            return jnp.logical_and(it < max_line_search, f_new > f + c1 * t * gp)

        def body(carry):
            t, it, _ = carry
            t = t * 0.5
            f_new, _ = value_and_grad(x + t * p)
            return t, it + 1, f_new

        f1, _ = value_and_grad(x + p)
        t, _, _ = lax.while_loop(cond, body, (jnp.float32(1.0), 0, f1))
        return t

    def step(carry, _):
        x, f, g, s_hist, y_hist, rho_hist, count, done = carry

        def do_step(_):
            p = -two_loop(g, s_hist, y_hist, rho_hist, count)
            # fall back to steepest descent if p isn't a descent direction
            p = jnp.where(dot(p, g) < 0, p, -g)
            t = line_search(x, f, g, p)
            x_new = x + t * p
            f_new, g_new = value_and_grad(x_new)
            s = x_new - x
            yv = g_new - g
            sy = dot(s, yv)
            idx = count % m
            ok = sy > 1e-10  # curvature condition; skip update otherwise
            s_h = jnp.where(ok, s_hist.at[idx].set(s), s_hist)
            y_h = jnp.where(ok, y_hist.at[idx].set(yv), y_hist)
            r_h = jnp.where(ok, rho_hist.at[idx].set(1.0 / jnp.maximum(sy, 1e-20)), rho_hist)
            cnt = jnp.where(ok, count + 1, count)
            gnorm = jnp.sqrt(dot(g_new, g_new))
            return x_new, f_new, g_new, s_h, y_h, r_h, cnt, gnorm < tol

        def skip(_):
            return x, f, g, s_hist, y_hist, rho_hist, count, done

        carry = lax.cond(done, skip, do_step, None)
        return carry, carry[1]

    f0, g0 = value_and_grad(x0)
    s_hist = jnp.zeros((m,) + shape, jnp.float32)
    y_hist = jnp.zeros((m,) + shape, jnp.float32)
    rho_hist = jnp.zeros((m,), jnp.float32)
    init = (x0, f0, g0, s_hist, y_hist, rho_hist, 0, jnp.array(False))
    (x, f, g, *_), _ = lax.scan(step, init, None, length=max_iter)
    return x


class DenseLBFGSwithL2(LabelEstimator):
    """Least-squares loss + L2, minimized with L-BFGS
    (nodes/learning/LBFGS.scala § DenseLBFGSwithL2).

    loss(W) = 1/(2n)·‖XW − Y‖² + (λ/2)·‖W‖²
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 50,
        history: int = 10,
        fit_intercept: bool = False,
    ):
        self.lam = float(lam)
        self.num_iterations = int(num_iterations)
        self.history = int(history)
        self.fit_intercept = fit_intercept

    def params(self):
        return (self.lam, self.num_iterations, self.history, self.fit_intercept)

    def choose_physical(self, sample):
        """Dense vs sparse physical choice (the reference's
        NodeOptimizationRule picking LeastSquaresDenseGradient vs
        LeastSquaresSparseGradient from sampled data): host datasets of
        scipy sparse rows route to the sparse-gradient solver."""
        from keystone_tpu.ops.sparse import is_scipy_sparse_rows

        if (
            type(self) is DenseLBFGSwithL2
            and not self.fit_intercept  # sparse path has no centering
            and sample is not None
            and sample.is_host
            and is_scipy_sparse_rows(sample.items)
        ):
            return SparseLBFGSwithL2(
                lam=self.lam,
                num_iterations=self.num_iterations,
                history=self.history,
                fit_intercept=False,
            )
        return self

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("DenseLBFGSwithL2 requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n):
        w, b = _lbfgs_least_squares(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(y, jnp.float32),
            jnp.float32(n),
            self.lam,
            self.num_iterations,
            self.history,
            self.fit_intercept,
        )
        return LinearMapper(w, b if self.fit_intercept else None)


class SparseLBFGSwithL2(DenseLBFGSwithL2):
    """Sparse-gradient variant (LBFGS.scala § SparseLBFGSwithL2 /
    LeastSquaresSparseGradient).

    Features stay in padded-COO form (ops/sparse.PaddedSparseRows —
    n·nnz (index, value) pairs, never the dense n×d matrix): the forward
    pass gathers weight rows, the gradient scatter-adds into (d, k).
    At 100k+ vocabulary this is ~3 orders of magnitude less memory than
    densifying, which is exactly how the reference ran text at scale.

    Accepts: a host Dataset of scipy sparse rows (what ``Sparsify``
    emits), a ``PaddedSparseRows`` directly via :meth:`fit_sparse`, or —
    fallback — any dense input, which routes to the dense solver so the
    optimizer's physical-choice rule can still select either class name.
    ``fit_intercept`` is not supported on the sparse path (centering
    would densify); construct with ``fit_intercept=False``.
    """

    # already the sparse physical form: restore the base hook (the same
    # function object Estimator defines) so NodeChoiceRule's
    # is-overridden guard skips the (expensive) sample execution
    # entirely for nodes that could never swap
    choose_physical = LabelEstimator.choose_physical

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        from keystone_tpu.ops.sparse import PaddedSparseRows, is_scipy_sparse_rows

        if labels is None:
            raise ValueError("SparseLBFGSwithL2 requires labels")
        if data.is_host and is_scipy_sparse_rows(data.items):
            sp = PaddedSparseRows.from_scipy_rows(data.items)
            return self.fit_sparse(sp, labels.array, n=data.n)
        return super().fit_dataset(data, labels)

    def fit_sparse(self, sp, y, n: Optional[int] = None):
        """Fit from a PaddedSparseRows feature matrix."""
        if self.fit_intercept:
            raise ValueError(
                "SparseLBFGSwithL2 does not support fit_intercept: "
                "centering would densify the features"
            )
        from keystone_tpu.ops.sparse import align_label_rows

        n = sp.n if n is None else int(n)
        y = align_label_rows(y, n, int(sp.indices.shape[0]))
        w = _lbfgs_sparse_least_squares(
            sp.indices,
            sp.values,
            y,
            jnp.float32(n),
            sp.num_features,
            self.lam,
            self.num_iterations,
            self.history,
        )
        return LinearMapper(w, None)


@partial(jax.jit, static_argnames=("d", "num_iterations", "history"))
def _lbfgs_sparse_least_squares(idx, vals, y, n, d, lam, num_iterations, history):
    """L-BFGS least squares on padded-COO features: the model (d, k) is
    replicated; per-iteration work is a row-sharded gather-matvec forward
    and a scatter-add gradient, all-reduced over the mesh — the sparse
    analogue of the dense path's einsum + psum."""
    from keystone_tpu.ops.sparse import sparse_grad, sparse_matmul

    idx = constrain(idx, DATA_AXIS)
    vals = constrain(vals, DATA_AXIS)
    y = constrain(y, DATA_AXIS)
    row_ok = (jnp.arange(y.shape[0]) < n).astype(jnp.float32)[:, None]
    y = y * row_ok
    vals = vals * row_ok  # padding rows contribute nothing anywhere

    def value_and_grad(w):
        r = sparse_matmul(idx, vals, w) - y  # (rows, k), row-sharded
        f = 0.5 * jnp.vdot(r, r) / n + 0.5 * lam * jnp.vdot(w, w)
        g = constrain(sparse_grad(idx, vals, r, d)) / n + lam * w
        return f, g

    w0 = jnp.zeros((d, y.shape[1]), jnp.float32)
    return lbfgs_minimize(
        value_and_grad, w0, max_iter=num_iterations, history=history
    )


@partial(jax.jit, static_argnames=("num_iterations", "history", "fit_intercept"))
def _lbfgs_least_squares(x, y, n, lam, num_iterations, history, fit_intercept):
    if fit_intercept:
        xm = jnp.sum(x, axis=0) / n
        ym = jnp.sum(y, axis=0) / n
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
        x = (x - xm) * row_ok
        y = (y - ym) * row_ok
    x = constrain(x, DATA_AXIS)
    y = constrain(y, DATA_AXIS)

    def value_and_grad(w):
        r = x @ w - y  # (n_rows, k), row-sharded; pad rows are zero
        f = 0.5 * jnp.vdot(r, r) / n + 0.5 * lam * jnp.vdot(w, w)
        g = constrain(sdot(x.T, r)) / n + lam * w
        return f, g

    w0 = jnp.zeros((x.shape[1], y.shape[1]), jnp.float32)
    w = lbfgs_minimize(
        value_and_grad, w0, max_iter=num_iterations, history=history
    )
    b = ym - xm @ w if fit_intercept else jnp.zeros((y.shape[1],), jnp.float32)
    return w, b
