"""K-means++ clustering.

Reference: nodes/learning/KMeansPlusPlus.scala § KMeansPlusPlusEstimator /
KMeansModel — k-means++ seeding, Lloyd iterations with BLAS-gemm distance
computation per partition; the model transformer emits one-hot cluster
assignments (used as a feature encoder, e.g. for random-patch vocabularies).

TPU form: seeding and Lloyd's loop are jitted lax scans; the (n, k)
distance matrix is one MXU gemm per iteration via the
‖x−c‖² = ‖x‖² − 2x·c + ‖c‖² expansion; assignment means come from a
one-hot einsum (segment-sum) contraction over the row-sharded axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer


def _sq_dists(x, centers):
    """(..., k) squared distances via the gemm expansion; x is (..., d)."""
    xn = jnp.sum(x * x, axis=-1, keepdims=True)
    cn = jnp.sum(centers * centers, axis=-1)
    return xn - 2.0 * (x @ centers.T) + cn


class KMeansModel(Transformer):
    """Emits one-hot nearest-center assignment (KMeansPlusPlus.scala §
    KMeansModel.apply)."""

    traced_attrs = ("centers",)

    def __init__(self, centers: jnp.ndarray):
        self.centers = centers  # (k, d)

    def apply_batch(self, xs, mask=None):
        d = _sq_dists(xs, self.centers)
        onehot = jax.nn.one_hot(jnp.argmin(d, axis=-1), self.centers.shape[0])
        if mask is not None:
            # ragged descriptor sets: zero padding rows' votes, keep the mask
            return onehot * mask[..., None], mask
        return onehot

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]

    def assign(self, xs):
        return jnp.argmin(_sq_dists(xs, self.centers), axis=1)


class KMeansPlusPlusEstimator(Estimator):
    def __init__(self, num_means: int, max_iterations: int = 20, seed: int = 0):
        self.num_means = int(num_means)
        self.max_iterations = int(max_iterations)
        self.seed = int(seed)

    def params(self):
        return (self.num_means, self.max_iterations, self.seed)

    def fit_dataset(self, data: Dataset) -> KMeansModel:
        from keystone_tpu.obs import ledger

        x = data.array
        if data.mask is not None:
            x = x.reshape(-1, x.shape[-1])
            row_ok = (data.mask.reshape(-1) > 0).astype(jnp.float32)
            x = x * row_ok[:, None]
        else:
            row_ok = (jnp.arange(x.shape[0]) < data.n).astype(jnp.float32)
        return KMeansModel(
            _kmeans_fit(
                x, row_ok, self.num_means, self.max_iterations,
                jax.random.PRNGKey(self.seed), obs=ledger.solver_obs(),
            )
        )

    def fit_arrays(self, x) -> KMeansModel:
        from keystone_tpu.obs import ledger

        x = jnp.asarray(x, jnp.float32)
        return KMeansModel(
            _kmeans_fit(
                x,
                jnp.ones((x.shape[0],), jnp.float32),
                self.num_means,
                self.max_iterations,
                jax.random.PRNGKey(self.seed),
                obs=ledger.solver_obs(),
            )
        )


def _row_at(x, idx):
    """``x[idx]`` for row-sharded x WITHOUT gathering x: a one-hot
    contraction over the sharded row axis, which XLA lowers to an
    all-reduce of one (d,) row — O(d) on the interconnect where a
    dynamic_slice on sharded rows all-gathers the full (n, d) matrix
    (caught by tests/test_sharding_gate.py).  Exact: every non-selected
    term is 0.0, and the pass is solver-grade so the selected row is not
    bf16-truncated."""
    from keystone_tpu.utils.precision import sdot

    onehot = constrain(
        (jnp.arange(x.shape[0]) == idx).astype(x.dtype), DATA_AXIS
    )
    return constrain(sdot(onehot, x))


@partial(jax.jit, static_argnames=("k", "iters", "obs"))
def _kmeans_fit(x, row_ok, k, iters, key, obs=False):
    """row_ok: (n_rows,) 1.0 for real rows, 0.0 for padding/invalid.

    ``obs`` (static): per-Lloyd-iteration ``solver.epoch`` telemetry
    (distortion + center shift) via ``jax.debug.callback`` — same math
    either way; the inert program carries no callbacks."""
    x = constrain(x.astype(jnp.float32), DATA_AXIS)
    n_rows = x.shape[0]

    # --- k-means++ seeding: sample propto min squared distance ---
    key, k0 = jax.random.split(key)
    first = jax.random.categorical(k0, jnp.log(row_ok + 1e-30))
    centers0 = jnp.zeros((k, x.shape[1]), jnp.float32).at[0].set(_row_at(x, first))

    def seed_step(i, carry):
        centers, key = carry
        # only the first i centers are set; mask the zero placeholders out
        dists = _sq_dists(x, centers)
        dists = jnp.where(jnp.arange(k)[None, :] < i, dists, jnp.inf)
        d = jnp.maximum(jnp.min(dists, axis=1), 0.0) * row_ok
        key, sk = jax.random.split(key)
        idx = jax.random.categorical(sk, jnp.log(d + 1e-30))
        return centers.at[i].set(_row_at(x, idx)), key

    centers, key = lax.fori_loop(1, k, seed_step, (centers0, key))

    # --- Lloyd iterations ---
    def lloyd(centers, it):
        d = _sq_dists(x, centers)
        assign = jax.nn.one_hot(jnp.argmin(d, axis=1), k) * row_ok[:, None]
        counts = constrain(jnp.sum(assign, axis=0))  # psum over 'data'
        sums = constrain(assign.T @ x)
        new = sums / jnp.maximum(counts, 1.0)[:, None]
        # keep old center for empty clusters
        new = jnp.where((counts > 0)[:, None], new, centers)
        if obs:
            from keystone_tpu.obs import ledger

            distortion = constrain(
                jnp.sum(jnp.maximum(jnp.min(d, axis=1), 0.0) * row_ok)
            )
            shift = jnp.sqrt(jnp.sum((new - centers) ** 2))
            jax.debug.callback(
                ledger.solver_callback(
                    "kmeans", "epoch", "distortion", "center_shift"
                ),
                it,
                distortion,
                shift,
            )
        return new, None

    # xs only exist when observing: the inert program must stay
    # byte-identical to the pre-obs one (the sharding gate pins its HLO,
    # and an iota xs measurably perturbs XLA's partitioning choices)
    if obs:
        centers, _ = lax.scan(lloyd, centers, jnp.arange(iters))
    else:
        centers, _ = lax.scan(lloyd, centers, None, length=iters)
    return centers
