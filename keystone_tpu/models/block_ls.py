"""Block coordinate descent ridge regression — the north-star solver.

Reference: nodes/learning/BlockLeastSquares.scala §
BlockLeastSquaresEstimator and BlockLinearMapper.scala: features are split
into fixed-size blocks (VectorSplitter); each epoch sweeps the blocks
Gauss–Seidel style — recompute the residual, form the block's normal
equations via per-partition gemm + treeReduce, solve on the driver with
Cholesky + λI, broadcast.  This is how d≈200k-dim Fisher-vector models
fit in memory.

TPU design: the entire multi-epoch sweep is ONE jitted
``lax.scan``-over-epochs of a ``lax.fori_loop``-over-blocks program.

  - X is laid out pre-blocked as (num_blocks, n, block_size), rows sharded
    over the mesh 'data' axis.  Block Gramians contract over rows → XLA
    all-reduce over ICI (the treeReduce).
  - The running prediction P = Σ_b X_b W_b (n, k) stays row-sharded; the
    class axis k is sharded over 'model', so the per-block multi-class
    solve is itself tensor-parallel (the reference's driver solve,
    eliminated).
  - Weights (num_blocks, block_size, k) are replicated over 'data'
    (broadcast analogue) and sharded over 'model' on k.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain, solve_spd
from keystone_tpu.parallel.collectives import sharded_gram, sharded_matmul
from jax.sharding import PartitionSpec as P
from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer


def blockify(x: jnp.ndarray, block_size: int):
    """(n, d) -> (num_blocks, n, block_size), zero-padding d if needed
    (the VectorSplitter analogue, nodes/util/VectorSplitter.scala)."""
    n, d = x.shape
    nb = -(-d // block_size)
    if nb * block_size != d:
        x = jnp.pad(x, ((0, 0), (0, nb * block_size - d)))
    return x.reshape(n, nb, block_size).transpose(1, 0, 2)


class BlockLinearMapper(Transformer):
    """Applies per-block weights and sums partial predictions
    (nodes/learning/BlockLinearMapper.scala).  ``weights`` is
    (num_blocks, block_size, k)."""

    traced_attrs = ("weights", "intercept", "feature_mean")

    def jit_static(self):
        return (self.block_size,)

    def __init__(
        self,
        weights: jnp.ndarray,
        block_size: int,
        intercept: Optional[jnp.ndarray] = None,
        feature_mean: Optional[jnp.ndarray] = None,
    ):
        self.weights = weights
        self.block_size = int(block_size)
        self.intercept = intercept
        self.feature_mean = feature_mean

    @property
    def flat_weights(self) -> jnp.ndarray:
        nb, bs, k = self.weights.shape
        return self.weights.reshape(nb * bs, k)

    def apply_batch(self, xs, mask=None):
        from keystone_tpu.utils import precision

        return _block_predict(
            xs,
            self.weights,
            self.intercept,
            self.feature_mean,
            mxu=precision.apply_mode(),
        )

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]

    def apply_and_evaluate(self, xs, eval_fn):
        """Stream per-block partial prediction sums to an eval callback
        (BlockLinearMapper.applyAndEvaluate) — used to watch convergence
        per block without materializing all partials."""
        xb = blockify(jnp.asarray(xs), self.block_size)
        acc = jnp.zeros((xs.shape[0], self.weights.shape[-1]), jnp.float32)
        results = []
        for b in range(self.weights.shape[0]):
            acc = acc + xb[b] @ self.weights[b]
            out = acc
            if self.feature_mean is not None or self.intercept is not None:
                out = acc + _offset(self.weights, self.feature_mean, self.intercept)
            results.append(eval_fn(out))
        return results


def _offset(weights, feature_mean, intercept):
    off = 0.0
    if feature_mean is not None:
        nb, bs, k = weights.shape
        pad = nb * bs - feature_mean.shape[0]
        if pad > 0:  # mean given at true d; weights are block-padded
            feature_mean = jnp.pad(feature_mean, (0, pad))
        off = off - feature_mean @ weights.reshape(nb * bs, k)
    if intercept is not None:
        off = off + intercept
    return off


@partial(jax.jit, static_argnames=("mxu",))
def _block_predict(xs, weights, intercept, feature_mean, mxu: str = "f32"):
    # Blocks are contiguous column ranges (blockify), so summing per-block
    # partials equals ONE flat matmul against the concatenated weights.
    # The blocked einsum compiled to a scan of dynamic-sliced weight reads
    # (async slice-copies dominated the scoring stage in device traces);
    # the flat dot streams the weights once, straight into the MXU.
    # Scoring (not solving), so the flat dot is under the apply precision
    # policy: 'bf16_apply' halves the (d × k) weight stream — at the
    # headline shape that is 32768×1000 f32 read per batch — with f32
    # accumulation; inert modes keep the exact pre-policy dot.
    xs = xs.astype(jnp.float32)
    nb, bs, k = weights.shape
    d = xs.shape[-1]
    if nb * bs != d:
        xs = jnp.pad(xs, ((0, 0), (0, nb * bs - d)))
    from keystone_tpu.utils import precision

    out = precision.apply_dot(xs, weights.reshape(nb * bs, k), mode=mxu)
    out = out + _offset(weights, feature_mean, intercept)
    return out


class BlockLeastSquaresEstimator(LabelEstimator):
    """Gauss–Seidel block coordinate descent ridge
    (nodes/learning/BlockLeastSquares.scala § BlockLeastSquaresEstimator).

    Math per (epoch, block):  W_b ← (X_bᵀX_b + nλI)⁻¹ X_bᵀ(Y − P + X_bW_b)
    where P = Σ_b X_b W_b is the running prediction.
    """

    # class-level default for pre-spill_dtype pickles
    spill_dtype = "float32"

    def __init__(
        self,
        block_size: int = 4096,
        num_iter: int = 1,
        lam: float = 0.0,
        fit_intercept: bool = True,
        spill_dtype: str = "float32",
    ):
        self.block_size = int(block_size)
        self.num_iter = int(num_iter)
        self.lam = float(lam)
        self.fit_intercept = fit_intercept
        #: out-of-core spill precision: "bfloat16" halves disk + wire
        #: bytes per sweep (a bandwidth lever — utils/precision.py);
        #: solver math stays f32 either way
        self.spill_dtype = str(spill_dtype)

    def params(self):
        return (
            self.block_size,
            self.num_iter,
            self.lam,
            self.fit_intercept,
            self.spill_dtype,
        )

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("BlockLeastSquaresEstimator requires labels")
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            if data.is_host:
                raise TypeError(
                    "host-payload stream reached a block solver; "
                    "featurize to arrays (or CSR) before the fit"
                )
            return self.fit_stream_dataset(data, labels)
        return self._fit(data.array, labels.array, data.n)

    def fit_stream_dataset(
        self, data, labels, spill_dir=None, checkpoint_dir=None, prefetch=None
    ) -> BlockLinearMapper:
        """Out-of-core fit: spill the streamed features to a block store
        once, then sweep blocks from disk (the default path when a
        StreamDataset reaches this estimator through the DAG).

        ``prefetch`` — block read-ahead depth for the sweep (None →
        ``KEYSTONE_OC_PREFETCH`` env, else 2; see :func:`_oc_prefetch`).

        The spill directory is deleted after a successful fit; on failure
        it is left behind for inspection (a later retry re-spills, and
        checkpoint fingerprints are content-based so resume still works)."""
        import shutil

        from keystone_tpu.obs import ledger
        from keystone_tpu.workflow.blockstore import FeatureBlockStore

        with ledger.span("solver.spill", solver="bcd", n=data.n):
            store = FeatureBlockStore.from_batches(
                _spill_dir(spill_dir),
                data.batches(),
                data.n,
                self.block_size,
                dtype=self.spill_dtype,
            )
        fitted = self.fit_store(
            store, labels, checkpoint_dir=checkpoint_dir, prefetch=prefetch
        )
        shutil.rmtree(store.directory, ignore_errors=True)
        return fitted

    def fit_store(
        self, store, labels, checkpoint_dir=None, prefetch=None
    ) -> BlockLinearMapper:
        """Fit from an existing FeatureBlockStore (features never fully
        resident in HBM; see _oc_bcd_fit).  ``prefetch`` as in
        :meth:`fit_stream_dataset`.

        Multi-process: ``store`` holds this process's row slice,
        ``labels`` is the GLOBAL label Dataset (made via
        ``multihost.make_global_dataset``); n checks and weighting use
        the global row count."""
        from keystone_tpu.workflow.dataset import as_dataset

        labels = as_dataset(labels)
        _check_store_rows(store, labels)
        y = labels.array.astype(jnp.float32)
        alpha = (jnp.arange(y.shape[0]) < labels.n).astype(jnp.float32)
        weights, xm, ym = _oc_bcd_fit(
            store,
            y,
            alpha,
            float(labels.n),
            self.lam,
            self.num_iter,
            self.fit_intercept,
            checkpoint_dir=checkpoint_dir,
            prefetch=prefetch,
        )
        return finish_block_model(
            weights, xm, ym, store.d, self.block_size, self.fit_intercept
        )

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n) -> BlockLinearMapper:
        x = x.astype(jnp.float32)
        y = y.astype(jnp.float32)
        nf = jnp.float32(n)
        xm = jnp.sum(x, axis=0) / nf if self.fit_intercept else None
        ym = jnp.sum(y, axis=0) / nf if self.fit_intercept else None
        # Center on padded arrays: pad rows become (−x̄), which would
        # corrupt Gramians — so mask them back to zero explicitly.
        if self.fit_intercept:
            row_ok = (jnp.arange(x.shape[0]) < n)[:, None].astype(jnp.float32)
            xc = (x - xm) * row_ok
            yc = (y - ym) * row_ok
        else:
            xc, yc = x, y
        from keystone_tpu.obs import ledger

        # device_wait: obs-gated sync charging the solve to the ledger's
        # device-busy account (inert — not even a block — without a run)
        weights = ledger.device_wait(
            _bcd_fit(
                blockify(xc, self.block_size),
                yc,
                nf,
                self.lam,
                self.num_iter,
                obs=ledger.solver_obs(),
            )
        )
        return finish_block_model(
            weights, xm, ym, x.shape[1], self.block_size, self.fit_intercept
        )

    def fit_checkpointed(self, data, labels, checkpoint_dir: str, prefetch=None):
        """Fit with per-epoch state checkpointing and resume.

        The reference has no mid-solver checkpointing (models are only
        saveable after fit — SURVEY.md §5); this closes that gap: each
        epoch's (W, P) lands in ``checkpoint_dir/bcd_epoch.npz``, and an
        interrupted fit resumes from the last completed epoch.

        ``prefetch`` rides the signature for parity with
        :meth:`fit_store` / :meth:`fit_stream_dataset`: when a
        checkpointed fit is routed out-of-core (a StreamDataset source
        spilled to a block store) the depth reaches ``_oc_bcd_fit``; the
        in-memory path here stages no disk blocks, so it is unused.
        """
        from keystone_tpu.workflow.dataset import StreamDataset as _SD

        if isinstance(data, _SD):
            return self.fit_stream_dataset(
                data, labels, checkpoint_dir=checkpoint_dir, prefetch=prefetch
            )
        import os

        import numpy as np

        from keystone_tpu.workflow.dataset import Dataset, as_dataset

        data = as_dataset(data)
        labels = as_dataset(labels)
        x = data.array.astype(jnp.float32)
        y = labels.array.astype(jnp.float32)
        n = data.n
        nf = jnp.float32(n)
        if self.fit_intercept:
            xm = jnp.sum(x, axis=0) / nf
            ym = jnp.sum(y, axis=0) / nf
            row_ok = (jnp.arange(x.shape[0]) < n)[:, None].astype(jnp.float32)
            xc = (x - xm) * row_ok
            yc = (y - ym) * row_ok
        else:
            xm = ym = None
            xc, yc = x, y
        xb = blockify(xc, self.block_size)
        nb, _, bs = xb.shape
        k = yc.shape[1]

        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "bcd_epoch.npz")
        # fingerprint the problem: resuming a checkpoint from different
        # data/labels/λ would silently break the P = Σ X_b W_b invariant.
        # Hash probe ROWS of each process's addressable shard (order-
        # sensitive: permutation-invariant scalar moments would accept a
        # reshuffled dataset and resume a stale W/P pair) and allgather
        # the per-process digests so the fingerprint is identical on
        # every process.
        import hashlib

        from keystone_tpu.parallel.multihost import gather_to_host, global_from_host

        def _probe_digest(*arrays) -> int:
            h = hashlib.sha256()
            for a in arrays:
                shards = getattr(a, "addressable_shards", None)
                # one-off pre-fit fingerprint read, not sweep-path
                loc = np.asarray(shards[0].data) if shards else np.asarray(a)  # lint: allow-host-sync
                h.update(loc[0].tobytes())
                h.update(loc[-1].tobytes())
            return int.from_bytes(h.digest()[:8], "little")

        local_digest = np.asarray([_probe_digest(x, y)], np.uint64)
        digests = tuple(gather_to_host(local_digest).ravel().tolist())
        fp = hashlib.sha256()
        fp.update(
            repr(
                (
                    x.shape,
                    y.shape,
                    int(n),
                    self.lam,
                    self.block_size,
                    bool(self.fit_intercept),
                    digests,
                )
            ).encode()
        )
        problem = fp.hexdigest()

        from keystone_tpu.utils import durable

        def _read_checkpoint():
            """(resume_epoch+1, w_host, p_host) or (0, zeros, zeros).
            durable.load_npz scans newest→last-good: a corrupt newest
            epoch checkpoint resumes from the previous epoch, not from
            scratch."""
            w0 = np.zeros((nb, bs, k), np.float32)
            p0 = np.zeros(yc.shape, np.float32)
            loaded = durable.load_npz(
                path,
                validate=lambda z: str(z.get("problem")) == problem
                and z["w"].shape == w0.shape
                and z["p"].shape == p0.shape,
            )
            if loaded is None:
                return 0, w0, p0
            z, _ = loaded
            return int(z["epoch"]) + 1, z["w"], z["p"]

        if jax.process_count() > 1:
            # processes must enter the epoch loop at the SAME iteration
            # (every sweep runs collectives): process 0's checkpoint
            # decision is broadcast, never decided per-process — a silent
            # local read failure would desynchronize and deadlock
            from jax.experimental import multihost_utils

            if jax.process_index() == 0:
                start, w_h, p_h = _read_checkpoint()
            else:
                start = 0
                w_h = np.zeros((nb, bs, k), np.float32)
                p_h = np.zeros(yc.shape, np.float32)
            start, w_h, p_h = multihost_utils.broadcast_one_to_all(
                (np.int32(start), np.asarray(w_h), np.asarray(p_h))
            )
            start = int(start)
        else:
            start, w_h, p_h = _read_checkpoint()

        w = jnp.zeros((nb, bs, k), jnp.float32)
        p = jnp.zeros_like(yc)
        if start > 0:
            # restore with mesh-wide shardings (w replicated, p like the
            # labels) — the host copies exist on every process
            mesh = getattr(yc.sharding, "mesh", None)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                w_sharding = NamedSharding(mesh, PartitionSpec())
            else:
                w_sharding = w.sharding
            w = global_from_host(w_h, w_sharding)
            p = global_from_host(p_h, yc.sharding)
        from keystone_tpu.obs import ledger, metrics

        observe = ledger.solver_obs()
        for e in range(start, self.num_iter):
            import time as _time

            # one sick host must abort ALL hosts at the epoch boundary
            # (SickHostError / DeadlineExceeded in bounded time) rather
            # than deadlock its peers inside the epoch's collectives.
            # Inert single-process / without KEYSTONE_HEALTH_TIMEOUT.
            from keystone_tpu.parallel.multihost import maybe_health_barrier

            maybe_health_barrier("bcd.checkpointed.epoch")
            t_epoch = _time.perf_counter()
            # donated carry: the old (w, p) buffers are consumed by the
            # epoch program and rebound to its outputs here
            w, p = _bcd_epoch(xb, yc, nf, self.lam, w, p)
            # required sync (the gathers below read w); metered as
            # device-busy either way
            ledger.device_wait(w, force=True)
            # the gathers are COLLECTIVES: every process must run them
            w_host = gather_to_host(w)
            p_host = gather_to_host(p)
            # … but only process 0 writes: rotation + sidecar are not
            # concurrent-writer-safe on a shared dir, and the resume
            # decision is read by process 0 alone anyway (broadcast).
            # durable.save_npz = atomic tmp+fsync+rename, BLAKE2b
            # sidecar, previous epoch rotated to <path>.1 — the
            # last-good fallback _read_checkpoint resumes from when the
            # newest save is later found corrupt
            t_save = _time.perf_counter()
            if jax.process_index() == 0:
                durable.save_npz(
                    path,
                    {
                        # host scalars: savez coerces — no device read
                        "epoch": e,
                        "w": w_host,
                        "p": p_host,
                        "problem": problem,
                    },
                    keep=2,
                )
            save_seconds = _time.perf_counter() - t_save
            metrics.observe("solver.checkpoint_save_seconds", save_seconds)
            if observe:
                ledger.solver_epoch(
                    "bcd.checkpointed",
                    epoch=e,
                    objective=float(np.asarray(_bcd_objective(yc, p, nf))),  # lint: allow-host-sync
                    epoch_seconds=_time.perf_counter() - t_epoch,
                    checkpoint_save_seconds=save_seconds,
                )
        return finish_block_model(
            w, xm, ym, x.shape[1], self.block_size, self.fit_intercept
        )


def finish_block_model(weights, xm, ym, d, block_size, fit_intercept):
    """Wrap fitted block weights into a BlockLinearMapper, computing the
    intercept from the (weighted) means when centering was used."""
    nb, bs, k = weights.shape
    if not fit_intercept:
        return BlockLinearMapper(weights, block_size)
    wflat = weights.reshape(nb * bs, k)[:d]
    intercept = ym - xm[:d] @ wflat
    pad = nb * bs - d
    return BlockLinearMapper(
        jnp.pad(wflat, ((0, pad), (0, 0))).reshape(nb, bs, k),
        block_size,
        intercept=intercept,
    )


# --------------------------------------------------------------------------
# Out-of-core block coordinate descent (features streamed from disk).
#
# The reference fits d≈200k-dim models by re-reading cached feature-block
# RDDs per (epoch, block) (nodes/learning/BlockLeastSquares.scala,
# SURVEY.md §3.2).  TPU analogue: blocks live in a FeatureBlockStore on
# host disk; HBM holds ONE (n × bs) staged block, the (n × k) residual P,
# labels, and the per-block weights — so the feature matrix can exceed
# device memory arbitrarily.  Disk reads prefetch on a worker thread and
# overlap the async-dispatched device step.
#
# One implementation serves both solvers: the unweighted case is the
# weighted case with α_i = 1 on valid rows (class_weights with
# mixture_weight=0), so `_oc_bcd_fit` is shared and the weighted math is
# exactly block_weighted_ls._weighted_bcd_fit's.
# --------------------------------------------------------------------------


@jax.jit
def _oc_wmean(alpha, a, wsum):
    return (alpha @ a) / wsum


@jax.jit
def _bcd_objective(yc, p, n):
    """Residual objective 0.5·‖Y−P‖²/n of a BCD carry — one tiny jitted
    reduction so obs-enabled host loops never pull the (n × k) residual
    to host just to norm it (sharded inputs reduce via collectives)."""
    r = yc - p
    return 0.5 * jnp.vdot(r, r) / n


@partial(jax.jit, donate_argnums=(5, 6))
def _oc_block_step(a_raw, xm_b, yc, sa, row_ok, p, wb, lam_n):
    """One out-of-core BCD block update (compiled once, reused for every
    (epoch, block) step — all blocks share one shape by construction).

    The carried state ``p``/``wb`` is DONATED (aliased onto the step's
    ``p_new``/``wb_new`` outputs): step N's residual and weights land in
    step N−1's HBM instead of allocating fresh — in the out-of-core
    regime HBM headroom is what bounds the block size, and without
    donation each step transiently holds two (n × k) residuals.  The
    staged block is NOT donated (no same-shape output to alias; its
    buffer frees by refcount when the loop drops it).  Callers must not
    touch a donated input after the call.

    The third output is a (1, 1) ``tick`` slice of the new weights:
    both real outputs are donated into LATER steps (p next step, wb next
    epoch), so neither can be waited on for flow control — the tick is
    never donated and gives the sweep a compute-completion handle to
    ``block_until_ready`` two steps behind, bounding how far the async
    dispatch queue (and the staged blocks its pending executions pin in
    HBM) can run ahead of the device."""
    a0 = (a_raw - xm_b) * row_ok[:, None]  # centered, padding re-zeroed
    a0 = constrain(a0, DATA_AXIS, None)
    a = a0 * sa[:, None]
    target = (yc - p) * sa[:, None] + a @ wb
    ata = sharded_gram(a)
    atr = sharded_matmul(a, target, out_spec=P(None, MODEL_AXIS))
    wb_new = solve_spd(ata, atr, reg=lam_n)
    p_new = constrain(p + a0 @ (wb_new - wb), DATA_AXIS, MODEL_AXIS)
    return wb_new, p_new, wb_new[:1, :1]


#: upper bound on the env-supplied read-ahead depth.  Each slot pins one
#: (n × block_size) host block, so an absurd depth (a stray
#: KEYSTONE_OC_PREFETCH=100000 in a job template) is an OOM sentence,
#: not a tuning choice — reject it up front.
_OC_PREFETCH_MAX = 64


def _oc_prefetch(explicit=None) -> int:
    """Resolved read-ahead depth for out-of-core block staging: the
    explicit caller value wins, else the ``KEYSTONE_OC_PREFETCH`` env
    override, else 2 (the measured default — one block transferring
    while one computes).  Deeper prefetch buys overlap on slow disks at
    the cost of pinned host memory: each slot holds an (n × block_size)
    f32/bf16 host block.

    The value is VALIDATED, not best-effort-coerced — on BOTH entry
    points (the same ``[1, _OC_PREFETCH_MAX]`` bound applies to the
    ``prefetch=`` fit argument and the env var): a non-integer or
    out-of-range depth raises ``ValueError`` naming its source — a
    silently-ignored typo ("KEYSTONE_OC_PREFETCH=eight") used to run
    the whole fit at the default depth while the operator believed the
    tuning was in effect."""
    import os

    if explicit is not None:
        return _check_prefetch_depth(int(explicit), "prefetch")
    raw = os.environ.get("KEYSTONE_OC_PREFETCH")
    if raw is None or raw == "":
        return 2
    try:
        depth = int(raw)
    except ValueError:
        raise ValueError(
            f"KEYSTONE_OC_PREFETCH={raw!r} is not an integer; expected a "
            f"block read-ahead depth in [1, {_OC_PREFETCH_MAX}]"
        ) from None
    return _check_prefetch_depth(depth, "KEYSTONE_OC_PREFETCH")


def _check_prefetch_depth(depth: int, source: str) -> int:
    if not 1 <= depth <= _OC_PREFETCH_MAX:
        raise ValueError(
            f"{source}={depth} is outside [1, {_OC_PREFETCH_MAX}]: each "
            "prefetch slot pins one (n × block_size) host block, so the "
            "depth must be a small positive integer"
        )
    return depth


def _check_store_rows(store, labels) -> None:
    """Single-process: store rows == label rows.  Multi-process: the
    per-process slices must jointly cover the global labels."""
    import jax

    procs = jax.process_count()
    if procs == 1:
        if labels.n != store.n:
            raise ValueError(f"labels n={labels.n} != store n={store.n}")
    elif store.n * procs < labels.n:
        raise ValueError(
            f"{procs} per-process stores of {store.n} rows cannot cover "
            f"{labels.n} global label rows"
        )


def _oc_bcd_fit(
    store,
    y,
    alpha,
    n,
    lam,
    num_iter,
    fit_intercept,
    checkpoint_dir=None,
    prefetch=None,
):
    """Stream feature blocks from ``store`` through BCD sweeps.

    ``y``: (n_rows, k) device labels, row-sharded; ``alpha``: (n_rows,)
    per-example weights with zeros on padding rows; ``prefetch``: block
    read-ahead depth (None → :func:`_oc_prefetch` resolution).  Returns
    ``(weights (nb, bs, k), xm (nb*bs,), ym (k,))``.

    Multi-process (pod) runs: ``store`` holds only THIS process's row
    slice on local disk (equal slices per host, the
    ``multihost.process_batch_slice`` convention) and blocks are staged
    as global row-sharded arrays via
    ``multihost.global_rows_from_local`` — no host ever materializes
    the full matrix, matching the reference's per-executor spilled
    feature partitions.

    With ``checkpoint_dir``, each completed epoch saves (epoch, W, P) and
    an interrupted fit resumes from the last epoch (fault-tolerance
    analogue of Spark lineage recompute, SURVEY.md §5).
    """
    import os

    import numpy as np


    from keystone_tpu.parallel import multihost as _mh

    nb, bs = store.num_blocks, store.block_size
    n_rows, k = y.shape
    prefetch = _oc_prefetch(prefetch)
    wsum = jnp.sum(alpha)
    sa = jnp.sqrt(alpha)
    row_ok = (alpha > 0).astype(jnp.float32)

    # Row-count validation, ONCE, against store metadata — every block
    # stages to the same padded shape by construction, so re-checking
    # inside the hot loop re-raised the identical comparison nb×num_iter
    # times per fit.  A 1-column probe resolves the mesh/process padding
    # without reading any feature block from disk.
    probe = _mh.global_rows_from_local(np.zeros((store.n, 1), np.float32))
    if probe.shape[0] != n_rows:
        raise ValueError(
            f"store rows pad to {probe.shape[0]} but labels have {n_rows}: "
            "store.n must equal the label Dataset's n (per-process "
            "row slice in multi-process runs)"
        )
    del probe

    def stage(blk):
        a = _mh.global_rows_from_local(blk)
        # bf16 stores cross the host→device wire at half width; solver
        # math stays f32 — cast on DEVICE, after the transfer
        if a.dtype != jnp.float32:
            a = a.astype(jnp.float32)
        return a

    import time as _time

    from keystone_tpu.obs import ledger, metrics

    def _ready(x):
        # compute backpressure: block until a step output from two
        # iterations back is READY (no device read, no host copy) so the
        # dispatch queue — and the staged blocks its pending executions
        # pin in HBM — never runs more than 2 steps ahead.  The staging
        # window only bounds in-flight TRANSFERS; transfers are not
        # ordered behind compute, so without this the Python loop races
        # the whole sweep into the queue.  The wait is device-busy time.
        ledger.device_wait(x, force=True)

    if fit_intercept:
        # double-buffered device feed: block b+1's host→device transfer
        # overlaps block b's weighted-mean reduction, and the bounded
        # staging window replaces the per-block real device read this
        # loop used to carry as backpressure
        xm_rows = []
        for _, a in store.iter_device_blocks(
            range(nb), prefetch=prefetch, stage=stage
        ):
            xm_rows.append(_oc_wmean(alpha, a, wsum))
            if len(xm_rows) > 2:
                _ready(xm_rows[-3])
        xm = jnp.stack(xm_rows)  # (nb, bs)
        ym = _oc_wmean(alpha, y, wsum)
    else:
        xm = jnp.zeros((nb, bs), jnp.float32)
        ym = jnp.zeros((k,), jnp.float32)
    yc = (y - ym) * row_ok[:, None]

    w = [jnp.zeros((bs, k), jnp.float32) for _ in range(nb)]
    p = jnp.zeros_like(yc)
    start = 0

    ckpt_path = problem = None
    if checkpoint_dir is not None:
        import hashlib

        os.makedirs(checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(checkpoint_dir, "oc_bcd_epoch.npz")
        # Content-based problem fingerprint: resuming with different data,
        # labels, weights (mixture), λ, or intercept setting must restart,
        # while a re-spill of IDENTICAL data to a new temp dir must still
        # resume — so hash content proxies, never the directory path.
        # Per-process-sharded stores hold DIFFERENT rows, so the local
        # store probe is allgathered (like fit_checkpointed's digests) —
        # every process must compute the SAME fingerprint or a shared-dir
        # checkpoint could only ever match on one of them.
        local_probe = np.frombuffer(
            hashlib.sha256(
                np.asarray(store.read_block(0)[0]).tobytes()
            ).digest()[:8],
            np.uint64,
        )
        probes = tuple(_mh.gather_to_host(local_probe).ravel().tolist())
        fp = hashlib.sha256()
        fp.update(
            repr(
                (
                    store.n,
                    store.d,
                    bs,
                    (n_rows, k),
                    float(lam),
                    n,
                    bool(fit_intercept),
                    probes,
                )
            ).encode()
        )
        # gather_to_host, not np.asarray: y/alpha rows are sharded and
        # a row's shard may be non-addressable from this process
        fp.update(_mh.gather_to_host(y[:1]).tobytes())
        fp.update(_mh.gather_to_host(alpha[: min(n_rows, 64)]).tobytes())
        problem = fp.hexdigest()

        from keystone_tpu.utils import durable

        def _read_oc_checkpoint():
            # newest→last-good scan (utils/durable): a corrupt newest
            # epoch falls back to the previous one instead of a scratch fit
            loaded = durable.load_npz(
                ckpt_path,
                validate=lambda z: str(z.get("problem")) == problem
                and z["w"].shape == (nb, bs, k),
            )
            if loaded is None:
                return 0, None, None
            z, _ = loaded
            return int(z["epoch"]) + 1, np.asarray(z["w"]), np.asarray(z["p"])

        if jax.process_count() > 1:
            # every sweep runs collectives, so processes must enter the
            # loop at the SAME iteration: process 0's resume decision is
            # broadcast, never decided per-process — a silent local read
            # failure would desynchronize and deadlock
            from jax.experimental import multihost_utils

            if jax.process_index() == 0:
                start, w_h, p_h = _read_oc_checkpoint()
            else:
                start, w_h, p_h = 0, None, None
            if w_h is None:
                w_h = np.zeros((nb, bs, k), np.float32)
                p_h = np.zeros(yc.shape, np.float32)
                start = int(start)
            start, w_h, p_h = multihost_utils.broadcast_one_to_all(
                (np.int32(start), np.asarray(w_h), np.asarray(p_h))
            )
            start = int(start)
            if start > 0:
                w = [jnp.asarray(w_h[b]) for b in range(nb)]
                p = _mh.global_from_host(p_h[: yc.shape[0]], yc.sharding)
        else:
            start, w_h, p_h = _read_oc_checkpoint()
            if start > 0:
                w = [jnp.asarray(w_h[b]) for b in range(nb)]
                p = _mh.global_from_host(
                    p_h[: yc.shape[0]], yc.sharding
                )

    lam_n = jnp.float32(lam * n)
    order = [b for _ in range(start, num_iter) for b in range(nb)]
    epoch = start
    # Dataflow: iter_device_blocks dispatches block b+1's host→device
    # transfer while block b computes, waiting (block_until_ready, no
    # device READ) on the transfer of the block two behind before
    # yielding — so staged HOST buffers stay bounded.  The step donates
    # only the carried p and w[b] (epoch N's state reuses epoch N−1's
    # HBM; the staged block itself is NOT donated — it frees by
    # refcount).  Compute flow control is separate: a ready-wait on the
    # step's non-donated tick output from two steps back (see _ready),
    # replacing the real 4-byte device read the loop used to carry.
    from collections import deque

    observe = ledger.solver_obs()
    t_epoch = _time.perf_counter()
    pending: deque = deque()
    for i, (b, a) in enumerate(
        store.iter_device_blocks(order, prefetch=prefetch, stage=stage)
    ):
        w[b], p, tick = _oc_block_step(
            a, xm[b], yc, sa, row_ok, p, w[b], lam_n
        )
        pending.append(tick)
        if len(pending) > 2:
            _ready(pending.popleft())
        if (i + 1) % nb == 0:
            # epoch boundary: abort collectively if a peer host went
            # sick mid-sweep (see fit_checkpointed's barrier) — the
            # checkpoint gathers below are collectives every process
            # must enter, and a dead peer would park them forever
            _mh.maybe_health_barrier("oc_bcd.epoch")
            save_seconds = None
            if ckpt_path is not None:
                # required sync (the gathers below read p); metered as
                # device-busy either way
                ledger.device_wait(p, force=True)
                # collectives first (every process participates) …
                w_host = np.stack([_mh.gather_to_host(x) for x in w])
                p_host = _mh.gather_to_host(p)
                # … then ONE writer: rotation + sidecar are not
                # concurrent-writer-safe, and resume reads are process-0
                # + broadcast anyway.  durable.save_npz = atomic
                # tmp+fsync+rename + checksum sidecar + previous epoch
                # rotated to <path>.1 (the resume scan's last-good
                # fallback)
                t_save = _time.perf_counter()
                if jax.process_index() == 0:
                    durable.save_npz(
                        ckpt_path,
                        {
                            # host scalars: savez coerces — no device read
                            "epoch": epoch,
                            "w": w_host,
                            "p": p_host,
                            "problem": problem,
                        },
                        keep=2,
                    )
                save_seconds = _time.perf_counter() - t_save
                metrics.observe("solver.checkpoint_save_seconds", save_seconds)
            if observe:
                # per-epoch objective is a real device read — charge the
                # wait to the device-busy account (obs-gated: the inert
                # sweep carries no sync at all)
                t_dev = _time.perf_counter()
                obj = float(np.asarray(_bcd_objective(yc, p, n)))  # lint: allow-host-sync
                metrics.observe(
                    "device.busy_seconds", _time.perf_counter() - t_dev
                )
                ledger.solver_epoch(
                    "bcd.out_of_core",
                    epoch=epoch,
                    objective=obj,
                    epoch_seconds=_time.perf_counter() - t_epoch,
                    checkpoint_save_seconds=save_seconds,
                )
            t_epoch = _time.perf_counter()
            epoch += 1
    weights = ledger.device_wait(jnp.stack(w))
    return weights, xm.reshape(-1), ym


def _spill_dir(hint=None):
    """A fresh directory for spilled feature blocks: the explicit hint,
    else the PipelineEnv state dir, else the system temp dir."""
    import os
    import tempfile

    from keystone_tpu.workflow.pipeline import PipelineEnv

    base = hint or PipelineEnv.state_dir
    if base is not None:
        os.makedirs(base, exist_ok=True)
    return tempfile.mkdtemp(prefix="kst_spill_", dir=base)


def _bcd_epoch_body(xb, y, n, lam, carry):
    """One Gauss–Seidel sweep over all blocks."""
    nb = xb.shape[0]

    def block_step(b, carry):
        w, p = carry
        a = xb[b]  # (n_rows, bs)
        wb = w[b]
        # residual with this block's contribution restored
        target = y - p + a @ wb
        # per-partition gemm + treeReduce == sharded contraction + psum
        ata = sharded_gram(a)
        atr = sharded_matmul(a, target, out_spec=P(None, MODEL_AXIS))
        wb_new = solve_spd(ata, atr, reg=lam * n)
        p_new = constrain(p + a @ (wb_new - wb), DATA_AXIS, MODEL_AXIS)
        return w.at[b].set(wb_new), p_new

    return lax.fori_loop(0, nb, block_step, carry)


@partial(jax.jit, donate_argnums=(4, 5))
def _bcd_epoch(xb, y, n, lam, w, p):
    """Single checkpointable epoch (used by fit_checkpointed's host
    loop).  The carried ``(w, p)`` is DONATED: epoch N's state lands in
    epoch N−1's HBM instead of doubling the live weight+residual
    footprint across every epoch boundary.  The caller's old bindings
    are invalid after the call (they are rebound to the outputs, and the
    checkpoint gathers read the NEW state)."""
    xb = constrain(xb, None, DATA_AXIS, None)
    y = constrain(y, DATA_AXIS, MODEL_AXIS)
    return _bcd_epoch_body(xb, y, n, lam, (w, p))


@partial(jax.jit, static_argnames=("num_iter", "obs"))
def _bcd_fit(xb, y, n, lam, num_iter, obs=False):
    """The hot loop (SURVEY.md §3.2) as one XLA program.

    xb: (nb, n_rows, bs) row-sharded; y: (n_rows, k).

    ``obs`` (static): emit a per-epoch ``solver.epoch`` convergence
    point (residual objective) to the active run ledger via
    ``jax.debug.callback``.  Same math either way — the flag only adds
    the host callback, and is resolved at trace time so the inert
    program carries no callbacks at all.
    """
    nb, n_rows, bs = xb.shape
    k = y.shape[1]
    xb = constrain(xb, None, DATA_AXIS, None)
    y = constrain(y, DATA_AXIS, MODEL_AXIS)
    w0 = jnp.zeros((nb, bs, k), jnp.float32)
    p0 = jnp.zeros_like(y)

    def epoch(carry, e):
        carry = _bcd_epoch_body(xb, y, n, lam, carry)
        if obs:
            from keystone_tpu.obs import ledger

            _, p = carry
            r = y - p
            jax.debug.callback(
                ledger.solver_callback("bcd", "epoch", "objective"),
                e,
                0.5 * jnp.vdot(r, r) / n,
            )
        return carry, None

    # xs only when observing — the inert program stays byte-identical
    # to the pre-obs one (see models/kmeans.py)
    if obs:
        (w, _), _ = lax.scan(epoch, (w0, p0), jnp.arange(num_iter))
    else:
        (w, _), _ = lax.scan(epoch, (w0, p0), None, length=num_iter)
    return w
