"""Block coordinate descent ridge regression — the north-star solver.

Reference: nodes/learning/BlockLeastSquares.scala §
BlockLeastSquaresEstimator and BlockLinearMapper.scala: features are split
into fixed-size blocks (VectorSplitter); each epoch sweeps the blocks
Gauss–Seidel style — recompute the residual, form the block's normal
equations via per-partition gemm + treeReduce, solve on the driver with
Cholesky + λI, broadcast.  This is how d≈200k-dim Fisher-vector models
fit in memory.

TPU design: the entire multi-epoch sweep is ONE jitted
``lax.scan``-over-epochs of a ``lax.fori_loop``-over-blocks program.

  - X is laid out pre-blocked as (num_blocks, n, block_size), rows sharded
    over the mesh 'data' axis.  Block Gramians contract over rows → XLA
    all-reduce over ICI (the treeReduce).
  - The running prediction P = Σ_b X_b W_b (n, k) stays row-sharded; the
    class axis k is sharded over 'model', so the per-block multi-class
    solve is itself tensor-parallel (the reference's driver solve,
    eliminated).
  - Weights (num_blocks, block_size, k) are replicated over 'data'
    (broadcast analogue) and sharded over 'model' on k.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.models.common import constrain, solve_spd
from keystone_tpu.parallel.collectives import sharded_gram, sharded_matmul
from jax.sharding import PartitionSpec as P
from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import LabelEstimator
from keystone_tpu.workflow.transformer import Transformer


def blockify(x: jnp.ndarray, block_size: int):
    """(n, d) -> (num_blocks, n, block_size), zero-padding d if needed
    (the VectorSplitter analogue, nodes/util/VectorSplitter.scala)."""
    n, d = x.shape
    nb = -(-d // block_size)
    if nb * block_size != d:
        x = jnp.pad(x, ((0, 0), (0, nb * block_size - d)))
    return x.reshape(n, nb, block_size).transpose(1, 0, 2)


class BlockLinearMapper(Transformer):
    """Applies per-block weights and sums partial predictions
    (nodes/learning/BlockLinearMapper.scala).  ``weights`` is
    (num_blocks, block_size, k)."""

    def __init__(
        self,
        weights: jnp.ndarray,
        block_size: int,
        intercept: Optional[jnp.ndarray] = None,
        feature_mean: Optional[jnp.ndarray] = None,
    ):
        self.weights = weights
        self.block_size = int(block_size)
        self.intercept = intercept
        self.feature_mean = feature_mean

    @property
    def flat_weights(self) -> jnp.ndarray:
        nb, bs, k = self.weights.shape
        return self.weights.reshape(nb * bs, k)

    def apply_batch(self, xs, mask=None):
        return _block_predict(
            xs, self.weights, self.block_size, self.intercept, self.feature_mean
        )

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]

    def apply_and_evaluate(self, xs, eval_fn):
        """Stream per-block partial prediction sums to an eval callback
        (BlockLinearMapper.applyAndEvaluate) — used to watch convergence
        per block without materializing all partials."""
        xb = blockify(jnp.asarray(xs), self.block_size)
        acc = jnp.zeros((xs.shape[0], self.weights.shape[-1]), jnp.float32)
        results = []
        for b in range(self.weights.shape[0]):
            acc = acc + xb[b] @ self.weights[b]
            out = acc
            if self.feature_mean is not None or self.intercept is not None:
                out = acc + _offset(self.weights, self.feature_mean, self.intercept)
            results.append(eval_fn(out))
        return results


def _offset(weights, feature_mean, intercept):
    off = 0.0
    if feature_mean is not None:
        nb, bs, k = weights.shape
        pad = nb * bs - feature_mean.shape[0]
        if pad > 0:  # mean given at true d; weights are block-padded
            feature_mean = jnp.pad(feature_mean, (0, pad))
        off = off - feature_mean @ weights.reshape(nb * bs, k)
    if intercept is not None:
        off = off + intercept
    return off


@partial(jax.jit, static_argnames=("block_size",))
def _block_predict(xs, weights, block_size, intercept, feature_mean):
    xs = xs.astype(jnp.float32)
    nb, bs, k = weights.shape
    xb = blockify(xs, block_size)  # (nb, n, bs)
    out = jnp.einsum("bni,bik->nk", xb, weights, preferred_element_type=jnp.float32)
    out = out + _offset(weights, feature_mean, intercept)
    return out


class BlockLeastSquaresEstimator(LabelEstimator):
    """Gauss–Seidel block coordinate descent ridge
    (nodes/learning/BlockLeastSquares.scala § BlockLeastSquaresEstimator).

    Math per (epoch, block):  W_b ← (X_bᵀX_b + nλI)⁻¹ X_bᵀ(Y − P + X_bW_b)
    where P = Σ_b X_b W_b is the running prediction.
    """

    def __init__(
        self,
        block_size: int = 4096,
        num_iter: int = 1,
        lam: float = 0.0,
        fit_intercept: bool = True,
    ):
        self.block_size = int(block_size)
        self.num_iter = int(num_iter)
        self.lam = float(lam)
        self.fit_intercept = fit_intercept

    def params(self):
        return (self.block_size, self.num_iter, self.lam, self.fit_intercept)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None):
        if labels is None:
            raise ValueError("BlockLeastSquaresEstimator requires labels")
        return self._fit(data.array, labels.array, data.n)

    def fit_arrays(self, x, y=None):
        x = jnp.asarray(x)
        return self._fit(x, jnp.asarray(y), x.shape[0])

    def _fit(self, x, y, n) -> BlockLinearMapper:
        x = x.astype(jnp.float32)
        y = y.astype(jnp.float32)
        nf = jnp.float32(n)
        xm = jnp.sum(x, axis=0) / nf if self.fit_intercept else None
        ym = jnp.sum(y, axis=0) / nf if self.fit_intercept else None
        # Center on padded arrays: pad rows become (−x̄), which would
        # corrupt Gramians — so mask them back to zero explicitly.
        if self.fit_intercept:
            row_ok = (jnp.arange(x.shape[0]) < n)[:, None].astype(jnp.float32)
            xc = (x - xm) * row_ok
            yc = (y - ym) * row_ok
        else:
            xc, yc = x, y
        weights = _bcd_fit(
            blockify(xc, self.block_size), yc, nf, self.lam, self.num_iter
        )
        if self.fit_intercept:
            nb, bs, k = weights.shape
            d = x.shape[1]
            wflat = weights.reshape(nb * bs, k)[:d]
            intercept = ym - xm @ wflat
            pad = nb * bs - d
            return BlockLinearMapper(
                jnp.pad(wflat, ((0, pad), (0, 0))).reshape(nb, bs, k),
                self.block_size,
                intercept=intercept,
            )
        return BlockLinearMapper(weights, self.block_size)

    def fit_checkpointed(self, data, labels, checkpoint_dir: str):
        """Fit with per-epoch state checkpointing and resume.

        The reference has no mid-solver checkpointing (models are only
        saveable after fit — SURVEY.md §5); this closes that gap: each
        epoch's (W, P) lands in ``checkpoint_dir/bcd_epoch.npz``, and an
        interrupted fit resumes from the last completed epoch.
        """
        import os

        import numpy as np

        from keystone_tpu.workflow.dataset import Dataset, as_dataset

        data = as_dataset(data)
        labels = as_dataset(labels)
        x = data.array.astype(jnp.float32)
        y = labels.array.astype(jnp.float32)
        n = data.n
        nf = jnp.float32(n)
        if self.fit_intercept:
            xm = jnp.sum(x, axis=0) / nf
            ym = jnp.sum(y, axis=0) / nf
            row_ok = (jnp.arange(x.shape[0]) < n)[:, None].astype(jnp.float32)
            xc = (x - xm) * row_ok
            yc = (y - ym) * row_ok
        else:
            xm = ym = None
            xc, yc = x, y
        xb = blockify(xc, self.block_size)
        nb, _, bs = xb.shape
        k = yc.shape[1]

        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "bcd_epoch.npz")
        # fingerprint the problem: resuming a checkpoint from different
        # data/labels/λ would silently break the P = Σ X_b W_b invariant
        import hashlib

        fp = hashlib.sha256()
        fp.update(repr((x.shape, y.shape, int(n), self.lam, self.block_size)).encode())
        fp.update(np.asarray(x[0]).tobytes())
        fp.update(np.asarray(y[0]).tobytes())
        problem = fp.hexdigest()

        start = 0
        w = jnp.zeros((nb, bs, k), jnp.float32)
        p = jnp.zeros_like(yc)
        if os.path.exists(path):
            try:
                with np.load(path) as z:
                    if str(z["problem"]) == problem:
                        start = int(z["epoch"]) + 1
                        w = jnp.asarray(z["w"])
                        p = jnp.asarray(z["p"])
            except Exception:
                pass  # unreadable/corrupt checkpoint: fit from scratch
        for e in range(start, self.num_iter):
            w, p = _bcd_epoch(xb, yc, nf, self.lam, w, p)
            jax.block_until_ready(w)
            # atomic write: a crash mid-save must not destroy the checkpoint
            tmp = path + ".tmp.npz"  # np.savez appends .npz to bare names
            np.savez(tmp, epoch=e, w=np.asarray(w), p=np.asarray(p), problem=problem)
            os.replace(tmp, path)
        if self.fit_intercept:
            d = x.shape[1]
            wflat = w.reshape(nb * bs, k)[:d]
            intercept = ym - xm @ wflat
            pad = nb * bs - d
            return BlockLinearMapper(
                jnp.pad(wflat, ((0, pad), (0, 0))).reshape(nb, bs, k),
                self.block_size,
                intercept=intercept,
            )
        return BlockLinearMapper(w, self.block_size)


def _bcd_epoch_body(xb, y, n, lam, carry):
    """One Gauss–Seidel sweep over all blocks."""
    nb = xb.shape[0]

    def block_step(b, carry):
        w, p = carry
        a = xb[b]  # (n_rows, bs)
        wb = w[b]
        # residual with this block's contribution restored
        target = y - p + a @ wb
        # per-partition gemm + treeReduce == sharded contraction + psum
        ata = sharded_gram(a)
        atr = sharded_matmul(a, target, out_spec=P(None, MODEL_AXIS))
        wb_new = solve_spd(ata, atr, reg=lam * n)
        p_new = constrain(p + a @ (wb_new - wb), DATA_AXIS, MODEL_AXIS)
        return w.at[b].set(wb_new), p_new

    return lax.fori_loop(0, nb, block_step, carry)


@jax.jit
def _bcd_epoch(xb, y, n, lam, w, p):
    """Single checkpointable epoch (used by fit_checkpointed's host loop)."""
    xb = constrain(xb, None, DATA_AXIS, None)
    y = constrain(y, DATA_AXIS, MODEL_AXIS)
    return _bcd_epoch_body(xb, y, n, lam, (w, p))


@partial(jax.jit, static_argnames=("num_iter",))
def _bcd_fit(xb, y, n, lam, num_iter):
    """The hot loop (SURVEY.md §3.2) as one XLA program.

    xb: (nb, n_rows, bs) row-sharded; y: (n_rows, k).
    """
    nb, n_rows, bs = xb.shape
    k = y.shape[1]
    xb = constrain(xb, None, DATA_AXIS, None)
    y = constrain(y, DATA_AXIS, MODEL_AXIS)
    w0 = jnp.zeros((nb, bs, k), jnp.float32)
    p0 = jnp.zeros_like(y)

    def epoch(carry, _):
        return _bcd_epoch_body(xb, y, n, lam, carry), None

    (w, _), _ = lax.scan(epoch, (w0, p0), None, length=num_iter)
    return w
