"""PCA.

Reference: nodes/learning/PCA.scala § PCAEstimator (local: gather sample →
LAPACK gesvd), DistributedPCAEstimator (covariance via treeReduce + local
eig), PCATransformer.  Used to project SIFT descriptors 128→64 in the
ImageNet pipeline.

TPU form: the "local" variant SVDs on device; the "distributed" variant
forms the covariance as a sharded Gramian (all-reduce over ICI) and eigh's
it replicated — both are single jitted programs.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.common import constrain
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils.precision import fcast, sdot


class PCATransformer(Transformer):
    """Projects onto the top-k principal directions: x ↦ (x − μ)·C."""

    # fitted arrays ride as traced jit arguments: both branch PCAs share
    # one compiled program per shape, and lowering never reads the
    # components back over the tunnel (Transformer.traced_attrs)
    traced_attrs = ("components", "mean")

    def __init__(self, components: jnp.ndarray, mean: Optional[jnp.ndarray] = None):
        self.components = components  # (d, k)
        self.mean = mean

    def apply_batch(self, xs, mask=None):
        if self.mean is not None:
            xs = xs - self.mean
        xs_c, comp_c = fcast(xs, self.components)
        out = jnp.matmul(xs_c, comp_c, preferred_element_type=jnp.float32)
        return (out, mask) if mask is not None else out

    def apply_one(self, x):
        if self.mean is not None:
            x = x - self.mean
        return x @ self.components


class PCAEstimator(Estimator):
    """SVD-based PCA on gathered data (PCA.scala § PCAEstimator)."""

    def __init__(self, dims: int, center: bool = True):
        self.dims = int(dims)
        self.center = center

    def params(self):
        return (self.dims, self.center)

    def fit_dataset(self, data: Dataset) -> PCATransformer:
        x = data.array
        if data.mask is not None:
            # ragged descriptor sets: (n, max_k, d) -> valid rows only
            # (flatten + mask threshold live inside the jit: eager they
            # were 2 extra compiled programs per fit)
            comp, mean = _pca_masked(x, data.mask, self.dims, self.center)
            return PCATransformer(comp, mean if self.center else None)
        comp, mean = _pca_fit(x, float(data.n), self.dims, self.center)
        return PCATransformer(comp, mean if self.center else None)

    def fit_arrays(self, x) -> PCATransformer:
        x = jnp.asarray(x, jnp.float32)
        comp, mean = _pca_fit(x, float(x.shape[0]), self.dims, self.center)
        return PCATransformer(comp, mean if self.center else None)


class DistributedPCAEstimator(PCAEstimator):
    """Covariance via sharded Gramian + replicated eigh
    (PCA.scala § DistributedPCAEstimator).  Preferable when n ≫ d."""

    def fit_arrays(self, x) -> PCATransformer:
        x = jnp.asarray(x, jnp.float32)
        comp, mean = _pca_cov_fit(x, float(x.shape[0]), self.dims, self.center)
        return PCATransformer(comp, mean if self.center else None)

    def fit_dataset(self, data: Dataset) -> PCATransformer:
        x = data.array
        if data.mask is not None:
            return super().fit_dataset(data)
        comp, mean = _pca_cov_fit(x, float(data.n), self.dims, self.center)
        return PCATransformer(comp, mean if self.center else None)


@partial(jax.jit, static_argnames=("dims", "center"))
def _pca_fit(x, n, dims, center):
    mean = jnp.sum(x, axis=0) / n
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
    xc = (x - mean) * row_ok if center else x
    _, _, vt = jnp.linalg.svd(xc, full_matrices=False)
    return vt[:dims].T, mean


@partial(jax.jit, static_argnames=("dims", "center"))
def _pca_cov_fit(x, n, dims, center):
    x = constrain(x, DATA_AXIS)
    mean = jnp.sum(x, axis=0) / n
    # center explicitly (pad rows re-masked to zero): the gram/n − x̄x̄ᵀ
    # shortcut cancels catastrophically in f32 at large feature magnitudes
    if center:
        row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
        x = (x - mean) * row_ok
    cov = constrain(sdot(x.T, x)) / n  # treeReduce analogue
    evals, evecs = jnp.linalg.eigh(cov)
    comp = evecs[:, ::-1][:, :dims]  # descending eigenvalue order
    return comp, mean


@partial(jax.jit, static_argnames=("dims", "center"))
def _pca_masked(x, mask, dims, center):
    if x.ndim == 3:  # ragged (n, max_k, d) + (n, max_k) mask
        x = x.reshape(-1, x.shape[-1])
        mask = mask.reshape(-1)
    valid = mask > 0
    w = valid.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(w), 1.0)
    mean = (w @ x) / n
    xc = (x - mean) * w[:, None] if center else x * w[:, None]
    cov = sdot(xc.T, xc) / n
    evals, evecs = jnp.linalg.eigh(cov)
    return evecs[:, ::-1][:, :dims], mean
