"""Collective helpers — the treeReduce/treeAggregate replacements.

The reference's only "collectives" are Spark ``treeReduce``/``treeAggregate``
(logarithmic aggregation of per-partition Gramians / gradients / moments to
the driver) and ``broadcast`` (SURVEY.md §2.9).  Here:

  - Inside ``shard_map``-decorated code, :func:`psum` is a literal
    all-reduce over ICI.
  - In jit-with-sharding code, :func:`sharded_gram` / :func:`sharded_matmul`
    express the per-partition-gemm + treeReduce pair as one einsum whose
    contraction over the row-sharded axis XLA lowers to a
    reduce-scatter/all-reduce — the idiomatic TPU form of call stack
    SURVEY.md §3.2.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as _mesh


def psum(x, axis_name: str = _mesh.DATA_AXIS):
    """All-reduce sum over a mesh axis (use inside shard_map/pmap)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str = _mesh.DATA_AXIS):
    return lax.pmean(x, axis_name)


def tree_psum(tree, axis_name: str = _mesh.DATA_AXIS):
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def sharded_matmul(a, b, out_spec: Optional[P] = None, mesh=None):
    """``a.T @ b`` with rows of a/b sharded over 'data'.

    This is the single communication pattern behind every reference solver
    (per-partition ``AᵀB`` gemm + treeReduce; e.g.
    nodes/learning/LinearMapper.scala § LinearMapEstimator): contraction
    over the sharded row axis; XLA inserts the all-reduce.  The result is
    constrained replicated (or ``out_spec``) — the broadcast analogue.

    Solver contractions request TRUE f32 MXU passes: XLA:TPU's *default*
    matmul precision truncates f32 inputs to bf16-grade passes (measured
    on v5 lite: default ≈ 2× the throughput of precision='float32'),
    which is fine for the featurize path but silently degrades normal
    equations — the reference computes these in f64 (netlib BLAS).  See
    utils/precision.py § solver_precision.
    """
    from keystone_tpu.utils.precision import solver_precision

    mesh = mesh or _mesh.current_mesh()
    out = jnp.matmul(
        a.T, b, precision=solver_precision(), preferred_element_type=jnp.float32
    )
    return lax.with_sharding_constraint(
        out, NamedSharding(mesh, out_spec if out_spec is not None else P())
    )


def sharded_gram(a, mesh=None):
    """``a.T @ a`` (Gramian) over row-sharded ``a``, replicated result."""
    return sharded_matmul(a, a, mesh=mesh)
