"""Device mesh, shardings, and collectives.

This module is the single owner of distribution concerns, mirroring how
everything in the reference bottoms out in Spark ``treeReduce`` /
``treeAggregate`` / ``broadcast`` (SURVEY.md §2.9).  The TPU-native
translation:

  ====================================  =====================================
  reference (Spark)                     keystone_tpu (JAX/XLA)
  ====================================  =====================================
  RDD partitions across executors       batch axis sharded over mesh 'data'
  treeReduce / treeAggregate            lax.psum / jnp.einsum + auto all-reduce
  broadcast of weights                  replicated sharding (free over ICI)
  driver-side solve                     replicated on-device solve
  feature blocks solved in time         feature axis sharded over mesh 'model'
  ====================================  =====================================

Everything above this module uses only this API.
"""

from keystone_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    MeshContext,
    current_mesh,
    data_sharding,
    default_mesh,
    device_count,
    local_mesh,
    replicated,
    set_mesh,
    shard_batch,
    use_mesh,
)
from keystone_tpu.parallel.collectives import (  # noqa: F401
    pmean,
    psum,
    sharded_gram,
    sharded_matmul,
    tree_psum,
)
from keystone_tpu.parallel.multihost import (  # noqa: F401
    SickHostError,
    health_barrier,
    maybe_health_barrier,
)
