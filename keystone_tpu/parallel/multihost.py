"""Multi-host distribution: ICI within a slice, DCN across slices.

The reference scales by adding Spark executors over ethernet; the TPU
equivalent is multi-process JAX — one process per host, chips linked by
ICI inside a slice and hosts by DCN across slices (SURVEY.md §2.9).

Usage on each host of a pod/multislice job:

    from keystone_tpu.parallel import multihost
    multihost.initialize(coordinator_address="host0:1234",
                         num_processes=N, process_id=i)
    mesh = multihost.hybrid_mesh(model_parallelism=4)
    set_mesh(mesh)

After that every solver in keystone_tpu runs unchanged: batch-axis
contractions all-reduce over ICI within a slice and DCN across slices,
exactly where XLA places them.  Data loading is per-host: each process
feeds its addressable shard (``process_batch_slice``).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np

from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

logger = logging.getLogger(__name__)

ENV_HEALTH_TIMEOUT = "KEYSTONE_HEALTH_TIMEOUT"


class SickHostError(RuntimeError):
    """A peer host reported unhealthy at a :func:`health_barrier` — the
    job must abort *together* (collectives are SPMD; continuing without
    the sick host would deadlock the healthy ones).  Deliberately not an
    ``OSError``: in-process retry cannot heal a dead peer, job-level
    restart (with checkpoint resume) owns recovery."""


#: substrings marking a RuntimeError as connection-shaped, i.e. worth
#: the retry/backoff budget.  jax's distributed runtime surfaces both
#: transient coordinator races and deterministic config errors as bare
#: RuntimeError — only the former should burn backoff time.
_TRANSIENT_INIT_MARKERS = (
    "connect",
    "connection",
    "unavailable",
    "timed out",
    "timeout",
    "deadline",
    "refused",
    "reset",
    "barrier",
    "coordinator",
    "heartbeat",
    "grpc",
    "socket",
    "temporar",  # temporary/temporarily
    "again",  # EAGAIN-style "try again"
)


def _transient_init_error(e: BaseException) -> bool:
    """Should the init retry loop absorb ``e``?  OSErrors (including
    injected faults) and ConnectionErrors: always.  RuntimeErrors: only
    when the message looks connection-shaped — a deterministic config
    error (mismatched ``num_processes``, bad process id) must fail
    fast instead of burning the full backoff budget before surfacing
    (tests/test_regressions.py pins both directions)."""
    if isinstance(e, (OSError, ConnectionError)):
        return True
    msg = str(e).lower()
    return any(m in msg for m in _TRANSIENT_INIT_MARKERS)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    retries: Optional[int] = None,
    initialization_timeout: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with TPU auto-detection when args are None.

    MUST run before any other JAX call that touches a backend (even
    ``jax.process_count()`` initializes XLA, after which distributed init
    is impossible — so this function inspects jax's distributed state
    directly instead of calling backend-touching APIs).  No-op when
    already initialized, or when no coordinator is configured (plain
    single-process use).

    Hardened: connecting to the coordinator retries with exponential
    backoff + jitter (``retries=None`` resolves KEYSTONE_INIT_RETRIES,
    default 2 — restarted jobs routinely race their coordinator coming
    back up), ``initialization_timeout`` forwards to jax's barrier
    timeout, and the attempt carries the ``multihost.init`` fault site
    so chaos plans can exercise exactly this path.  Only
    connection-shaped errors are retried (``_transient_init_error``): a
    deterministic config error — e.g. mismatched ``num_processes`` —
    fails fast instead of burning the backoff budget before surfacing.
    """
    import os

    from jax._src import distributed as _dist

    from keystone_tpu.faults import fault_point
    from keystone_tpu.utils import durable

    if getattr(_dist.global_state, "client", None) is not None:
        return  # already initialized
    if (
        coordinator_address is None
        and num_processes is None
        and os.environ.get("JAX_COORDINATOR_ADDRESS") is None
        and os.environ.get("COORDINATOR_ADDRESS") is None
        and os.environ.get("TPU_WORKER_HOSTNAMES") is None
    ):
        logger.debug("no coordinator configured; staying single-process")
        return
    if retries is None:
        retries = durable._env_int("KEYSTONE_INIT_RETRIES", 2)
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = int(initialization_timeout)

    import time as _time

    from keystone_tpu.obs import metrics

    t0 = _time.perf_counter()

    def _init():
        metrics.inc("multihost.init_attempts")
        fault_point("multihost.init")
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except Exception:
            # jax assigns global client/service BEFORE connecting, so a
            # failed connect leaves them set and every retry would hit
            # "initialize should only be called once" — clear the
            # partial state so the retry actually reconnects, and the
            # surfaced error stays the real one
            try:
                jax.distributed.shutdown()
            except Exception:
                _dist.global_state.client = None
                _dist.global_state.service = None
            raise

    durable.with_retries(
        _init,
        retries=retries,
        base_delay=0.5,
        max_delay=10.0,
        retry_on=(OSError, ConnectionError, RuntimeError),
        retry_if=_transient_init_error,
        description="distributed init",
    )
    dt = _time.perf_counter() - t0
    metrics.observe("multihost.init_seconds", dt)
    from keystone_tpu.obs import ledger

    ledger.event("multihost.init", seconds=dt)


def health_barrier(
    ok: bool = True, timeout: Optional[float] = None, tag: str = "health"
) -> bool:
    """All-gather one ok-bit per host, under a watchdog.

    The multi-process failure mode stage retry cannot cover: one host
    goes sick (OOM-killed fit thread, wedged local disk) while its peers
    park forever inside the next collective.  Calling this at natural
    sync points (epoch boundaries, restart attempts) converts that
    deadlock into a clean, *collective* abort:

    - every healthy host sees the sick host's 0-bit and raises
      :class:`SickHostError` — all processes abort together, and
      job-level restart resumes from durable checkpoints;
    - a host that is too dead to even join the gather trips the
      ``timeout`` watchdog instead
      (``utils.guard.DeadlineExceeded``).

    Single-process: an immediate no-op ``True`` (the inert path — CPU
    tests and laptops never pay for a collective).  Pass ``ok=False``
    on a host that knows it is failing so peers abort deterministically
    at the same barrier."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    from keystone_tpu.obs import ledger, metrics
    from keystone_tpu.utils import guard

    arr = np.asarray([1 if ok else 0], np.int32)

    def gather():
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    deadline = None if timeout is None else guard.Deadline.after(float(timeout))
    bits = guard.run_with_deadline(
        gather, deadline, site="multihost.health", tag=tag
    )
    sick = [i for i, b in enumerate(bits.reshape(-1).tolist()) if not b]
    if sick:
        metrics.inc("multihost.sick_hosts", tag=tag)
        ledger.event("multihost.sick_host", tag=tag, sick=sick)
        raise SickHostError(
            f"host(s) {sick} reported unhealthy at the {tag!r} barrier; "
            "aborting collectively (restart the job to resume from "
            "checkpoints)"
        )
    return True


def maybe_health_barrier(tag: str, ok: bool = True) -> bool:
    """Env-gated :func:`health_barrier` for hook sites (epoch drivers,
    recovery attempts): inert unless ``KEYSTONE_HEALTH_TIMEOUT`` is set
    to a positive number AND the job is multi-process — single-process
    callers pay one env lookup, nothing else.  ``guard.env_float`` owns
    the parse, so ``0`` means "disabled" here exactly as it does for
    every other guard knob (not a zero-second deadline)."""
    from keystone_tpu.utils.guard import env_float

    timeout = env_float(ENV_HEALTH_TIMEOUT)
    if timeout is None or jax.process_count() == 1:
        return True
    return health_barrier(ok=ok, timeout=timeout, tag=tag)


def hybrid_mesh(model_parallelism: int = 1):
    """('data', 'model') mesh laid out so 'model' stays inside a slice.

    Model/feature-parallel collectives (the per-block solves' class-axis
    sharding) are latency-sensitive → keep them on ICI; data-parallel
    all-reduces tolerate DCN.  Uses mesh_utils' hybrid construction when
    multiple slices are present, plain mesh otherwise.
    """
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = jax.devices()
    n = len(devices)
    if n % model_parallelism != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallelism={model_parallelism}"
        )
    num_slices = getattr(devices[0], "num_slices", 1) or 1
    if num_slices > 1:
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(n // num_slices // model_parallelism, model_parallelism),
            dcn_mesh_shape=(num_slices, 1),
            devices=devices,
        )
    else:
        arr = np.asarray(devices).reshape(n // model_parallelism, model_parallelism)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def process_batch_slice(global_n: int) -> slice:
    """The [start, stop) of the global batch this host should load."""
    per = -(-global_n // jax.process_count())
    start = jax.process_index() * per
    return slice(start, min(start + per, global_n))


def gather_to_host(arr) -> np.ndarray:
    """Full host copy of a (possibly multi-process global) array.

    Single-process: plain ``np.asarray``.  Multi-process the semantics
    fork by input type, and both are load-bearing:

    - a global ``jax.Array`` → every host gets ONE full copy of the
      global value (the checkpoint-save path for sharded solver state,
      where a bare ``np.asarray`` would raise on non-addressable shards);
    - a host ``np.ndarray`` (or other host value) → the per-process
      values are CONCATENATED along axis 0, i.e. a P-process call with a
      (k,)-shaped input returns (P·k,) — the cross-process digest in
      ``models/block_ls.py`` relies on this to compare per-host hashes.

    Callers holding a host array that is already identical on every
    process should NOT round-trip it through here expecting a no-op."""
    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def global_from_host(host_array, sharding):
    """Place a full host copy (present on EVERY process) as a global
    array with the given sharding — the checkpoint-restore inverse of
    :func:`gather_to_host`."""
    host_array = np.asarray(host_array)
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )


def global_rows_from_local(x_local):
    """Global row-sharded array from THIS process's row slice.

    Every process contributes an equal-length slice (the
    :func:`process_batch_slice` convention, padded identically); the
    result is one global array whose row axis is sharded over 'data'
    across all hosts.  Single-process: plain ``shard_batch``.  This is
    the staging primitive for per-process-sharded out-of-core stores —
    on a pod each host spills only ITS rows to local disk instead of
    every host holding the full matrix."""
    from keystone_tpu.parallel.mesh import current_mesh, data_sharding, shard_batch

    if jax.process_count() == 1:
        return shard_batch(x_local)
    x_local = np.asarray(x_local)
    mesh = current_mesh()
    return jax.make_array_from_process_local_data(
        data_sharding(mesh, x_local.ndim), x_local
    )


def make_global_dataset(host_array, global_n: Optional[int] = None):
    """Assemble a globally-sharded Dataset from per-host shards via
    jax.make_array_from_process_local_data (multi-host path), or a plain
    Dataset in single-process mode."""
    from keystone_tpu.parallel.mesh import current_mesh, data_sharding
    from keystone_tpu.workflow.dataset import Dataset

    if jax.process_count() == 1:
        return Dataset(host_array)
    mesh = current_mesh()
    sharding = data_sharding(mesh, np.ndim(host_array))
    garr = jax.make_array_from_process_local_data(sharding, np.asarray(host_array))
    d = Dataset.__new__(Dataset)
    d._host = None
    d._array = garr
    d.n = global_n if global_n is not None else garr.shape[0]
    d.mask = None
    d.name = None
    return d
