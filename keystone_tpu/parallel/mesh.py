"""Mesh management.

The framework uses a 2-D logical mesh:

  - ``'data'``  — data parallelism: the batch/example axis.  Replaces the
    reference's RDD partitioning (SURVEY.md §2.9 "Data parallelism").
  - ``'model'`` — feature/model parallelism: the feature axis of wide
    models.  The reference scales model dimension *in time* (block
    coordinate descent over 4096-column feature blocks,
    nodes/learning/BlockLeastSquares.scala); we additionally scale it
    *in space* by sharding the feature axis across devices.

A process-global mesh (set with :func:`set_mesh` / :func:`use_mesh`)
keeps user code free of distribution plumbing, analogous to the
reference's process-global ``PipelineEnv`` holding the SparkContext.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass
class MeshContext:
    """Holder for the process-global mesh (cf. workflow/PipelineEnv.scala)."""

    mesh: Optional[Mesh] = None


_CTX = MeshContext()
_LOCK = threading.Lock()


def device_count() -> int:
    return len(jax.devices())


def default_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallelism: int = 1,
) -> Mesh:
    """Build a ('data', 'model') mesh over the given (default: all) devices.

    ``model_parallelism`` devices are assigned to the 'model' axis; the
    remainder to 'data'.  With a single device both axes have size 1 and
    all collectives are no-ops, which is how single-chip runs work
    unchanged (the reference's "local mode").
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n % model_parallelism != 0:
        raise ValueError(
            f"device count {n} not divisible by model_parallelism {model_parallelism}"
        )
    arr = np.asarray(devs).reshape(n // model_parallelism, model_parallelism)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def local_mesh() -> Mesh:
    """A trivial 1x1 mesh on the first device (single-datum / debug path)."""
    return default_mesh(jax.devices()[:1])


def set_mesh(mesh: Optional[Mesh]) -> None:
    with _LOCK:
        _CTX.mesh = mesh


def current_mesh() -> Mesh:
    """The active mesh, creating the all-device default on first use."""
    with _LOCK:
        if _CTX.mesh is None:
            _CTX.mesh = default_mesh()
        return _CTX.mesh


def active_mesh() -> Optional[Mesh]:
    """The active mesh if one was set, WITHOUT creating the default —
    for callers that only want to inspect (e.g. which platform the
    computation targets) and must not instantiate device state."""
    with _LOCK:
        return _CTX.mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh) -> Iterator[Mesh]:
    prev = _CTX.mesh
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully-replicated sharding — the analogue of Spark broadcast."""
    return NamedSharding(mesh or current_mesh(), P())


def data_sharding(
    mesh: Optional[Mesh] = None, ndim: int = 2, feature_axis: Optional[int] = None
) -> NamedSharding:
    """Rows over 'data'; optionally one axis over 'model' (feature sharding)."""
    spec = [None] * ndim
    spec[0] = DATA_AXIS
    if feature_axis is not None:
        spec[feature_axis] = MODEL_AXIS
    return NamedSharding(mesh or current_mesh(), P(*spec))


def pad_to_multiple(n: int, m: int) -> int:
    return int(math.ceil(n / m) * m) if m > 1 else n


def shard_batch(x, mesh: Optional[Mesh] = None, feature_axis: Optional[int] = None):
    """Place a host array on the mesh, batch axis over 'data'.

    If the leading axis is not divisible by the data-axis size the array is
    zero-padded (callers that care track true length separately; the
    framework's Dataset does).  This is the moral equivalent of
    ``sc.parallelize(data, numPartitions)``.
    """
    import jax.numpy as jnp

    mesh = mesh or current_mesh()
    x = jnp.asarray(x)
    dsize = mesh.shape[DATA_AXIS]
    n = x.shape[0]
    padded = pad_to_multiple(n, dsize)
    if padded != n:
        pad_widths = [(0, padded - n)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, pad_widths)
    return jax.device_put(x, data_sharding(mesh, x.ndim, feature_axis))
