"""One-replica serve loop: the worker-process side of the process fleet.

``worker_main`` is the spawn target of
:class:`~keystone_tpu.serve.procfleet.WorkerHandle`: it loads the
deploy payload (fitted pipeline + optional AOT artifact bundle) from
the path the router staged, builds the frozen applier, installs the
pre-lowered bucket programs, primes every padding bucket (the PR-11
ladder — artifact, persistent compile cache, fresh compile), beats a
shared-memory heartbeat, and then serves ``apply`` frames until the
router says ``bye`` (or the control pipe dies with the router).

The worker owns the accelerator runtime for its replica: the parent
router process never imports a device backend on the hot path, so N
workers compute on N cores/devices in true parallel — the whole point
of the promotion (ROADMAP 4: stop measuring the GIL).

Protocol (see ``serve/wire.py``; strict request/response, one in
flight):

- ``{"op": "apply", "ref": <slab ref>, "n": k, "deadline_s": t|null}``
  → ``{"op": "result", "ref": <slab ref>,
  "seconds": dt}`` — the input reference names a slab in the ROUTER's
  pool; the result reference names one in THIS worker's response pool
  (each side owns and unlinks its own slabs).
- apply failures answer ``{"op": "error", "kind", "etype", "emsg"}``
  where ``kind`` preserves the repo's error taxonomy across the
  process boundary — ``deadline`` (a shed-typed
  ``guard.DeadlineExceeded``), ``oserror`` (infrastructure),
  ``memory``, or ``content`` (the bisectable family) — so poison
  isolation and breaker charging behave exactly as they do in-process.
- ``{"op": "ping"}`` → ``{"op": "pong", "pid": ...}``;
  ``{"op": "bye"}`` ends the loop.

Spawn discipline: workers are ALWAYS started via the ``spawn`` start
method (``procfleet`` enforces it) — a forked JAX runtime inherits
locked mutexes and wedges on first dispatch; ``tools/lint.py``'s
``proc-spawn`` rule keeps ``multiprocessing`` use fenced into these
modules.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time

import contextlib

import numpy as np

from keystone_tpu.serve import wire
from keystone_tpu.serve.telemetry import WorkerTelemetry

logger = logging.getLogger(__name__)

#: how often the worker refreshes its shared heartbeat slot.
#: ``time.monotonic`` is CLOCK_MONOTONIC on Linux — one system-wide
#: clock, comparable across the router and its workers.
HEARTBEAT_INTERVAL_S = 0.25


def _classify(exc: BaseException) -> str:
    """The cross-process error taxonomy (the ``_poison_suspect``
    contract from serve/service.py, serialized): infrastructure rides
    ``oserror``, capacity rides ``memory``, shed rides ``deadline``,
    and everything else is ``content`` — the bisectable family."""
    from keystone_tpu.utils import guard

    if isinstance(exc, wire.PayloadTooLarge):
        # an oversized RESULT (the request fit; the output overflowed
        # the slab cap): relayed as its own kind so the router raises
        # the same typed PayloadTooLarge a request-side overflow gets —
        # NOT a generic content error masquerading as model poison
        return "too_large"
    if isinstance(exc, guard.DeadlineExceeded):
        return "deadline"
    if isinstance(exc, guard.CircuitOpenError):
        return "circuit"
    if isinstance(exc, MemoryError):
        return "memory"
    if isinstance(exc, OSError):
        return "oserror"
    return "content"


def _load_payload(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f)


def _build_applier(payload: dict):
    """Freeze the staged pipeline and install its artifact bundle (a
    failed install degrades to the compile ladder, mirroring
    ``ReplicaPool._install_artifacts`` — a worker must come up serving
    even off a damaged bundle)."""
    from keystone_tpu.serve.fleet import _as_applier
    from keystone_tpu.utils.hashing import pipeline_fingerprint
    from keystone_tpu.workflow.pipeline import FrozenApplier

    pipeline = payload["pipeline"]
    applier = _as_applier(pipeline)
    artifacts = payload.get("artifacts")
    installed = 0
    if artifacts:
        try:
            if isinstance(pipeline, FrozenApplier):
                sig = pipeline.fingerprint()
            else:
                sig = pipeline_fingerprint(pipeline)
            installed = applier.install_artifacts(
                artifacts, device=None, signature=sig, program_cache={}
            )
        except Exception as e:
            logger.warning(
                "worker artifact install failed (%s: %s); compiling",
                type(e).__name__,
                e,
            )
    plan = getattr(applier, "plan", None)
    if plan is not None:
        # artifact installs re-install the shipped plan themselves; an
        # artifact-less (or rejected-bundle) spawn still carries the plan
        # in the pickled applier — install it so this worker process
        # serves the planned physical configuration
        try:
            from keystone_tpu import planner

            if planner.current_plan() is None:
                planner.install_plan(plan, source="spawn")
        except Exception as e:
            logger.warning("worker plan install failed (%s)", e)
    return applier, installed


def _prime(applier, buckets, item_shape, dtype) -> int:
    """Warm every padding bucket's program — exactly the shapes the
    router will dispatch.  Degradation-declaring pipelines also warm
    the deadline-carrying executor walk (the same double-prime the
    in-process service does)."""
    from keystone_tpu.utils import guard
    from keystone_tpu.workflow.dataset import Dataset

    if not buckets or item_shape is None:
        return 0
    n = 0
    for b in buckets:
        zeros = np.zeros((int(b),) + tuple(item_shape), np.dtype(dtype))
        applier(Dataset(zeros, n=int(b)))
        n += 1
        if getattr(applier, "_degradable", False) and getattr(
            applier, "installed_buckets", lambda: 0
        )():
            applier(
                Dataset(zeros, n=int(b)),
                deadline=guard.Deadline.after(86400.0),
            )
            n += 1
    return n


def build_from_payload(payload: dict, spec: dict, tel=None):
    """The full cold-start ladder shared by BOTH worker transports (the
    pipe-spawned process worker and the TCP worker of ``serve/net.py``):
    freeze the pipeline, install AOT artifacts (degrading to the
    compile ladder on a damaged bundle), and prime every padding
    bucket.  Returns ``(applier, installed, primed)``.  ``tel``: a
    :class:`~keystone_tpu.serve.telemetry.WorkerTelemetry` that records
    ``worker.build`` / ``worker.prime`` spans for shipping on the ready
    frame — cold-start time becomes visible from the router's ops
    surface, not just worker logs."""
    span = tel.span if tel is not None else (
        lambda _name, **_a: contextlib.nullcontext()
    )
    with span("worker.build"):
        applier, installed = _build_applier(payload)
    with span("worker.prime"):
        primed = _prime(
            applier,
            spec.get("buckets"),
            spec.get("item_shape"),
            spec.get("dtype") or "float32",
        )
    return applier, installed, primed


#: public name for the cross-process error taxonomy (the TCP worker
#: relays its apply failures through the same classifier)
classify_error = _classify


def _artifact_keys(applier) -> list:
    """The (shape, dtype) keys of installed AOT bucket programs — the
    ready frame ships them so the router's prime loop can label its
    ``serve.prime_seconds{source=}`` samples honestly for a remote
    replica."""
    progs = getattr(applier, "_bucket_programs", None) or {}
    out = []
    for key in progs:
        try:
            shape, dtype = key
            out.append([list(shape), np.dtype(dtype).str])
        except (TypeError, ValueError):
            continue
    return out


def worker_main(conn, spec: dict) -> None:
    """The worker process entry point (spawned by ``WorkerHandle``).

    ``conn``: the worker end of the control pipe.  ``spec``: plain-data
    worker configuration — ``name``/``index`` (labels), ``payload_path``
    (the staged deploy payload), ``buckets``/``item_shape``/``dtype``
    (the prime set; item_shape None skips priming), ``heartbeat`` (a
    shared ``multiprocessing.Value('d')`` this loop refreshes).
    """
    import os

    from keystone_tpu.utils import guard
    from keystone_tpu.workflow.dataset import Dataset

    hb = spec.get("heartbeat")
    stop_beating = threading.Event()

    def beat_loop():
        while not stop_beating.wait(HEARTBEAT_INTERVAL_S):
            if hb is not None:
                hb.value = time.monotonic()

    if hb is not None:
        hb.value = time.monotonic()
        threading.Thread(target=beat_loop, daemon=True, name="hb").start()

    # the response pool honors the SAME slab cap as the router's
    # request pool: a result wider than the default cap must not turn
    # into a bisectable "content" error when the operator raised the
    # cap for exactly that workload
    pool = wire.SlabPool(
        prefix=f"{spec.get('name', 'serve')}-w",
        max_slab_bytes=int(
            spec.get("max_slab_bytes") or wire.DEFAULT_MAX_SLAB_BYTES
        ),
    )
    attacher = wire.SlabAttacher()
    #: worker-side telemetry: load/prime/attach/apply spans plus
    #: metrics-registry deltas, shipped by piggybacking on the frames
    #: this loop already answers (ready, result, error) — bounded,
    #: dropped-not-queued, and invisible to an old router (optional
    #: body key)
    tel = WorkerTelemetry()
    t0 = time.monotonic()
    try:
        with tel.span("worker.load"):
            payload = _load_payload(spec["payload_path"])
        applier, installed, primed = build_from_payload(payload, spec, tel=tel)
    except BaseException as e:
        try:
            wire.send_frame(
                conn,
                {
                    "op": "fatal",
                    "etype": type(e).__name__,
                    "emsg": str(e)[:800],
                },
            )
        except (OSError, ValueError):
            pass
        pool.close()
        return
    wire.send_frame(
        conn,
        {
            "op": "ready",
            "pid": os.getpid(),
            "primed": primed,
            "artifact_buckets": installed,
            "artifact_keys": _artifact_keys(applier),
            "startup_seconds": round(time.monotonic() - t0, 3),
            "telemetry": tel.ship(t_rx=t0),
        },
    )

    held: list = []  # response slabs reusable once the NEXT frame lands
    try:
        while True:
            try:
                msg = wire.recv_frame(conn)
            except (EOFError, OSError):
                return  # the router died; nothing to serve for
            # the previous response has been fully read by the router
            # (strict request/response: it sent this frame after), so
            # its slab can rejoin the free list now
            while held:
                pool.release(held.pop())
            op = msg.get("op")
            if op == "bye":
                try:
                    wire.send_frame(conn, {"op": "bye_ack"})
                except (OSError, ValueError):
                    pass
                return
            if op == "ping":
                wire.send_frame(conn, {"op": "pong", "pid": os.getpid()})
                continue
            if op != "apply":
                wire.send_frame(
                    conn,
                    {
                        "op": "error",
                        "kind": "content",
                        "etype": "WireError",
                        "emsg": f"unknown op {op!r}",
                    },
                )
                continue
            t_apply = time.monotonic()
            try:
                with tel.span("worker.attach"):
                    arr = attacher.read(msg["ref"])
                n = int(msg.get("n", arr.shape[0]))
                deadline_s = msg.get("deadline_s")
                deadline = (
                    None
                    if deadline_s is None
                    else guard.Deadline.after(float(deadline_s))
                )
                with tel.span("worker.apply", n=n):
                    out = applier(Dataset(arr, n=n), deadline=deadline)
                result = np.asarray(out.array)
                slab, ref = wire.write_array(pool, result)
            except BaseException as e:
                wire.send_frame(
                    conn,
                    {
                        "op": "error",
                        "kind": _classify(e),
                        "etype": type(e).__name__,
                        "emsg": str(e)[:800],
                        "seconds": round(time.monotonic() - t_apply, 6),
                        "telemetry": tel.ship(t_rx=t_apply),
                    },
                )
                continue
            held.append(slab)
            wire.send_frame(
                conn,
                {
                    "op": "result",
                    "ref": ref,
                    "seconds": round(time.monotonic() - t_apply, 6),
                    "telemetry": tel.ship(t_rx=t_apply),
                },
            )
    finally:
        stop_beating.set()
        attacher.close()
        pool.close()
        try:
            conn.close()
        except OSError:
            pass
