"""Stdlib-only HTTP front end for a :class:`PipelineService`.

Endpoints (JSON unless noted):

- ``POST /predict`` — body ``{"instances": [<datum>, ...]}`` (or
  ``{"instance": <datum>}``), optional ``"deadline_ms"`` and
  ``"tenant"`` (multi-tenant services — ``serve/tenants.py`` — route
  by it; single-tenant services answer 400; a tenant whose own
  admission breaker is open answers 429).  Replies
  ``{"predictions": [...]}``.  Status codes carry the admission/deadline
  contract: **429** when admission control rejects (``Overloaded``,
  with a ``Retry-After`` hint), **504** when the request was shed past
  its deadline (``DeadlineExceeded``; a request that COMPLETES late
  still answers 200 — the ``serve.deadline_miss`` counter records it),
  **400** on malformed bodies, **422** when the request's CONTENT
  breaks the model (``PoisonRequest`` — bisection-isolated or
  quarantine-cache matched; retrying it unchanged will fail again),
  **503** on service shutdown AND on a fleet with no serving replica
  (``FleetUnavailable`` — every replica quarantined/dead/breaker-open;
  carries a ``Retry-After`` derived from the soonest breaker probe).
- ``GET /healthz`` — liveness + queue depth + the live model version +
  per-replica status (version, breaker state, outstanding flushes,
  dead/quarantined/restart counts), so a load balancer can see a
  HALF-sick fleet — one replica's breaker open, a replica still
  serving the old version mid-swap — not just process liveness.
  Answers **503** (with ``Retry-After``) while the fleet is
  unavailable, so the process leaves rotation until the supervisor's
  first successful restart re-admits traffic.
- ``GET /replicas`` — the per-replica status list alone.
- ``POST /swap`` — admin: blue/green hot-swap the serving model from
  the attached :class:`~keystone_tpu.serve.registry.ModelRegistry`
  (``serve_http(svc, registry=...)``; without one the endpoint answers
  409).  Body ``{"version": "v0007"}`` picks a version; empty body
  deploys the registry's best candidate (``CURRENT``, with corrupt
  fallback).  A successful swap also moves ``CURRENT`` to the served
  version — the registry stays the source of truth, so a ``--watch``
  poller (or a restart) agrees with an admin rollback instead of
  reverting it.  Replies with the swap info dict (version, pause,
  prime seconds, replicas).  An optional ``{"canary": 0.1,
  "bake_s": 30, ...}`` body routes the swap through the guarded
  rollout (``serve/rollout.py``): the staged version serves that
  traffic fraction, is judged against the SLO/error guardrails, and
  commits or auto-rolls-back — the reply's ``verdict``/``reason``
  say which (a rollback answers 200 with ``"verdict":
  "rolled_back"``; the old version never stopped serving).
  ``{"clear_bad": true}`` lifts a quarantine mark on the named
  version first (the explicit admin override).
- ``POST /rollback`` — admin: revert to the newest prior version in
  the service's swap history that is still published and not
  quarantined; moves ``CURRENT`` with it.  409 when there is no
  registry attached or no viable prior version.
- ``GET /rolloutz`` — guarded-rollout status
  (``PipelineService.rollout_status``): the live canary/bake phase,
  recent episode verdicts, swap history, and the windowed SLO burn
  detail the judge reads.
- ``GET /metrics`` — the process metrics registry in Prometheus text
  exposition format (``obs.metrics.to_prometheus_text``): queue depth,
  batch occupancy, latency histograms, shed/rejected counters — the
  whole registry, so serving metrics land next to everything else.
- ``GET /statusz`` — the rolling-window ops view
  (``PipelineService.status``): p50/p95/p99 latency over the last
  window (not process lifetime), per-replica occupancy/breaker
  statuses, outcome counters, recorder stats, and the SLO error-budget
  burn rate when a latency objective is configured.
- ``GET /tracez`` — recent request traces from the flight recorder
  (newest first; shed/error/slow traces pinned past the happy-path
  ring).  Query: ``?filter=slow|shed|error|rejected|degraded|completed``,
  ``?limit=N``, ``?full=1`` for the complete dump (events + batch
  records + ops spans — the ``tools/trace_report.py`` input).  409 when
  the service runs with ``recorder=False``.
- ``GET /requestz/<id>`` — one request's full causal chain (its trace
  events joined with the batch records it rode), 404 for an unknown or
  long-evicted id.

**Request ids** — ``POST /predict`` honors an ``X-Request-Id`` header
(else generates an id) and echoes it in EVERY response — the 200 body,
the 429/503/504/400/500 error bodies, and an ``X-Request-Id`` response
header alike — so a client can always quote the exact id that
``/requestz/<id>`` resolves.  Multi-instance bodies fan sub-ids
``<id>/0``, ``<id>/1``, ... (listed in the response as
``request_ids``).

A 429's ``Retry-After`` is derived from the batcher's EWMA
flush-completion estimate (``PipelineService.retry_after_hint``) —
integer-ceiled for the header (delta-seconds), exact in the JSON body —
instead of a hard-coded constant.

``ThreadingHTTPServer`` (one thread per connection; HTTP/1.1
keep-alive, so a client's request stream reuses its thread AND its TCP
handshake) is the COMPATIBLE shape here: handler threads block on their
futures while the single batcher thread does the device work, which is
exactly the micro-batching contract.  It is also now the *slow path*:
``serve/ingress.py`` runs a selector-driven front end that speaks a
binary batch protocol and delegates sniffed HTTP connections to THIS
handler (:func:`handle_http_connection`), so the JSON surface stays
identical whichever front end accepted the socket.  Bind ``port=0`` to
get an ephemeral port (tests).

Usage::

    front = serve_http(svc, port=8000)   # started, background thread
    ...
    front.stop(); svc.close()

or foreground (the CLI does this)::

    HttpFrontend(svc, port=8000).serve_forever()
"""

from __future__ import annotations

import json
import logging
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlsplit

import numpy as np

from keystone_tpu.obs import metrics
from keystone_tpu.obs.recorder import new_request_id
from keystone_tpu.serve.fleet import FleetUnavailable
from keystone_tpu.serve.service import (
    Overloaded,
    PipelineService,
    PoisonRequest,
    ServiceClosed,
)
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

#: per-request result wait: generous — the service's own deadline/shed
#: machinery is the real latency bound; this only stops a handler thread
#: leaking forever if the service is killed under it
_RESULT_TIMEOUT_S = 120.0


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 with Content-Length on every response => persistent
    # connections by default.  Under HTTP/1.0 every request paid a TCP
    # handshake (and slow-start) — at per-datum submit rates the
    # handshakes, not the service, were the measured latency.  One
    # handler THREAD now serves a whole connection's request stream,
    # which is still the threaded slow path next to serve/ingress.py.
    protocol_version = "HTTP/1.1"

    #: idle keep-alive bound: a silent persistent connection releases
    #: its thread after this (socketserver applies it via settimeout;
    #: handle_one_request maps the timeout to close_connection)
    timeout = 65.0

    # route access logs to logging (debug), not stderr
    def log_message(self, fmt, *args):
        logger.debug("http: " + fmt, *args)

    @property
    def service(self) -> PipelineService:
        return self.server.service  # type: ignore[attr-defined]

    def _send(self, code: int, payload, content_type="application/json", headers=()):
        body = (
            payload
            if isinstance(payload, bytes)
            else json.dumps(payload).encode("utf-8")
        )
        # a client that hung up mid-exchange (impatient curl, a load
        # balancer health probe, a bencher's ^C) must not crash the
        # handler thread with an uncaught BrokenPipeError — the
        # response has no one to go to; drop it and close our side
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError, TimeoutError) as e:
            self.close_connection = True
            logger.debug("http: client disconnected mid-response: %s", e)

    def do_GET(self):
        parts = urlsplit(self.path)
        path, query = parts.path, parse_qs(parts.query)
        if path == "/healthz":
            svc = self.service
            # an unavailable fleet (every replica quarantined/dead/
            # breaker-open) answers non-200 so a load balancer takes the
            # process out of rotation; the supervisor's first successful
            # restart flips it back
            available = svc.available
            code = 200 if available or svc.closed else 503
            self._send(
                code,
                {
                    "status": (
                        "closed"
                        if svc.closed
                        else ("ok" if available else "unavailable")
                    ),
                    "queue_depth": svc.queue_depth,
                    "queue_bound": svc.queue_bound,
                    "max_batch": svc.max_batch,
                    "buckets": list(svc.buckets),
                    "version": svc.version,
                    # process-fleet visibility: backend + worker count,
                    # so a balancer (or operator curl) sees the fleet
                    # shape without parsing the per-replica list
                    "backend": svc._pool.backend,
                    "workers": svc.replicas,
                    "replicas": svc.replica_statuses(),
                },
                headers=(
                    ()
                    if code == 200
                    else (
                        (
                            "Retry-After",
                            str(
                                max(
                                    1,
                                    math.ceil(svc.unavailable_retry_after()),
                                )
                            ),
                        ),
                    )
                ),
            )
        elif path == "/replicas":
            self._send(200, {"replicas": self.service.replica_statuses()})
        elif path == "/statusz":
            self._send(200, self.service.status())
        elif path == "/rolloutz":
            self._send(200, self.service.rollout_status())
        elif path == "/tracez":
            self._do_tracez(query)
        elif path.startswith("/requestz/"):
            # unquote: a client-supplied X-Request-Id may need
            # percent-encoding in the URL; the trace is stored under
            # the raw id
            self._do_requestz(unquote(path[len("/requestz/"):]))
        elif path == "/metrics":
            self._send(
                200,
                metrics.REGISTRY.to_prometheus_text().encode("utf-8"),
                content_type="text/plain; version=0.0.4",
            )
        else:
            self._send(404, {"error": f"no such path {self.path!r}"})

    def _recorder_or_409(self):
        rec = self.service.recorder
        if rec is None:
            self._send(
                409,
                {
                    "error": "flight recorder disabled; start the service "
                    "with recorder=True (the default) to trace requests"
                },
            )
        return rec

    def _do_tracez(self, query):
        rec = self._recorder_or_409()
        if rec is None:
            return
        flt = (query.get("filter") or [None])[0]
        try:
            limit = int((query.get("limit") or ["50"])[0])
        except ValueError:
            self._send(400, {"error": "limit must be an integer"})
            return
        full = (query.get("full") or ["0"])[0] not in ("", "0", "false")
        if full:
            out = rec.dump()
            if flt:
                out["traces"] = [
                    t
                    for t in out["traces"]
                    if (t["slow"] if flt == "slow" else t["outcome"] == flt)
                ]
            self._send(200, out)
            return
        self._send(
            200,
            {
                "traces": rec.tracez(filter=flt, limit=limit),
                "ops": rec.ops_spans(limit=limit),
                "stats": rec.stats(),
            },
        )

    def _do_requestz(self, request_id: str):
        rec = self._recorder_or_409()
        if rec is None:
            return
        trace = rec.request(request_id)
        if trace is None:
            self._send(
                404,
                {
                    "error": f"no trace for request id {request_id!r} "
                    "(unknown, or evicted from the ring — shed/error/slow "
                    "traces are retained longest)"
                },
            )
            return
        self._send(200, trace)

    def do_POST(self):
        if self.path == "/swap":
            self._do_swap()
            return
        if self.path == "/rollback":
            self._do_rollback()
            return
        if self.path == "/tracez/dump":
            self._do_trace_dump()
            return
        if self.path != "/predict":
            self._send(404, {"error": f"no such path {self.path!r}"})
            return
        # the trace identity: honor the client's X-Request-Id, else mint
        # one — resolved BEFORE parsing so even a 400 echoes an id, and
        # echoed in every response body + X-Request-Id header so the
        # client can always quote the id /requestz/<id> resolves
        rid = (self.headers.get("X-Request-Id") or "").strip() or new_request_id()
        hdrs = (("X-Request-Id", rid),)
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            if "instances" in body:
                instances = body["instances"]
            elif "instance" in body:
                instances = [body["instance"]]
            else:
                raise ValueError('body needs "instances" or "instance"')
            arr = np.asarray(instances, dtype=np.float32)
            # the JSON slow path materializes every payload byte at
            # least once (text → floats → array); the binary ingress
            # charges zero here — the counter IS the zero-copy claim
            metrics.inc("ingress.bytes_copied", int(arr.nbytes))
            deadline_ms = body.get("deadline_ms")
            deadline = None if deadline_ms is None else float(deadline_ms) / 1000.0
            # multi-tenant routing: the body names its tenant; a
            # single-tenant service refuses a tenant (TypeError → 400)
            tenant = body.get("tenant")
            tenant = None if tenant is None else str(tenant)
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._send(
                400, {"error": f"bad request: {e}", "request_id": rid}, headers=hdrs
            )
            return
        # one HTTP request = one trace id; a multi-instance body fans
        # out sub-ids so each datum's causal chain resolves individually
        ids = [rid] if len(arr) == 1 else [f"{rid}/{i}" for i in range(len(arr))]
        rec = self.service.recorder
        if rec is not None:
            for i in ids:
                rec.annotate(i, "http.ingress", path="/predict", instances=len(arr))
        id_body = {"request_id": rid}
        if len(ids) > 1:
            id_body["request_ids"] = ids
        try:
            futs = self.service.submit_many(
                arr, deadline=deadline, request_ids=ids, tenant=tenant
            )
        except Overloaded as e:
            # Retry-After from the EWMA flush-completion estimate the
            # shedding path maintains: the header is delta-seconds (an
            # integer, so ceiled, >= 1); the body carries the exact hint
            hint = self.service.retry_after_hint()
            self._send(
                429,
                {"error": str(e), "retry_after_seconds": hint, **id_body},
                headers=hdrs + (("Retry-After", str(max(1, math.ceil(hint)))),),
            )
            return
        except PoisonRequest as e:
            # the request's CONTENT breaks the model (bisection-isolated
            # or quarantine-cache matched): the client's fault — 422,
            # not 500, and retrying it unchanged will fail again
            self._send_poison(e, id_body, hdrs)
            return
        except FleetUnavailable as e:
            # no replica can serve: fail fast with the derived retry
            # hint (breaker probe ETA / supervisor restart)
            self._send_unavailable(e, id_body, hdrs)
            return
        except ServiceClosed as e:
            self._send(503, {"error": str(e), **id_body}, headers=hdrs)
            return
        except guard.CircuitOpenError as e:
            # THIS tenant's admission breaker is open (repeated
            # failures): back off — co-served tenants are unaffected
            self._send(
                429,
                {"error": str(e), "retry_after_seconds": 1.0, **id_body},
                headers=hdrs + (("Retry-After", "1"),),
            )
            return
        except TypeError as e:  # shape mismatch / bad tenant: CLIENT fault
            self._send(
                400, {"error": f"bad request: {e}", **id_body}, headers=hdrs
            )
            return
        except Exception as e:  # e.g. injected fault
            self._send(
                500,
                {"error": f"{type(e).__name__}: {e}", **id_body},
                headers=hdrs,
            )
            return
        try:
            preds = [
                np.asarray(f.result(timeout=_RESULT_TIMEOUT_S)).tolist()
                for f in futs
            ]
        except guard.DeadlineExceeded as e:
            self._send(504, {"error": str(e), **id_body}, headers=hdrs)
            return
        except PoisonRequest as e:  # isolated mid-flight by bisection
            self._send_poison(e, id_body, hdrs)
            return
        except FleetUnavailable as e:  # batch failed fast after admission
            self._send_unavailable(e, id_body, hdrs)
            return
        except Exception as e:
            self._send(
                500,
                {"error": f"{type(e).__name__}: {e}", **id_body},
                headers=hdrs,
            )
            return
        self._send(200, {"predictions": preds, **id_body}, headers=hdrs)

    def _send_poison(self, e, id_body, hdrs):
        """422: the request's content breaks the model (PoisonRequest,
        at admission via the quarantine cache or mid-flight via
        bisection) — one response shape for both paths."""
        self._send(422, {"error": str(e), **id_body}, headers=hdrs)

    def _send_unavailable(self, e, id_body, hdrs):
        """503 + derived Retry-After for FleetUnavailable, whether it
        was raised at admission or delivered through the future."""
        self._send(
            503,
            {
                "error": str(e),
                "retry_after_seconds": e.retry_after_seconds,
                **id_body,
            },
            headers=hdrs
            + (
                (
                    "Retry-After",
                    str(max(1, math.ceil(e.retry_after_seconds))),
                ),
            ),
        )

    def _do_trace_dump(self):
        """Write the flight recorder's state durably to disk (the
        incident-time snapshot ``tools/trace_report.py`` reads offline).
        Directory: the request body's ``dir`` key, else the configured
        ``--trace-dump`` directory.  Codes: 200 with the written path,
        409 when tracing is off or no directory is known, 400 bad body,
        500 the write itself failed."""
        rec = self._recorder_or_409()
        if rec is None:
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}") or {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
            return
        dir_path = body.get("dir") or getattr(
            self.server, "trace_dump_dir", None
        )
        if not dir_path:
            self._send(
                409,
                {
                    "error": "no trace-dump directory configured; start "
                    'with `keystone serve --trace-dump DIR` or POST '
                    '{"dir": "..."}'
                },
            )
            return
        try:
            path = self.service.dump_trace(str(dir_path))
        except OSError as e:
            self._send(500, {"error": f"trace dump failed: {e}"})
            return
        self._send(200, {"path": path, "stats": rec.stats()})

    def _do_swap(self):
        """Admin blue/green swap from the attached registry.  Codes:
        200 swapped, 409 no registry configured, 404 unknown version,
        503 service closed, 502 the load/swap itself failed (bad
        publish, injected fault) — the old version keeps serving."""
        registry = getattr(self.server, "registry", None)
        if registry is None:
            self._send(
                409,
                {
                    "error": "no model registry attached; start the "
                    "frontend with serve_http(svc, registry=...) or "
                    "`cli serve --model-dir`"
                },
            )
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}") or {}
            if not isinstance(body, dict):
                # valid JSON but not an object ('"v0002"', '[1]'): a
                # client error, not a handler crash
                raise ValueError("body must be a JSON object")
            version = body.get("version")
            # the guarded-rollout body keys ("canary" fraction et al):
            # parsed here so a malformed guard config is a 400, not a
            # 502 from deep inside the episode
            rollout_cfg = None
            if body.get("canary") is not None:
                from keystone_tpu.serve.rollout import RolloutConfig

                rollout_cfg = RolloutConfig.from_request(body)
        except (ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad request: {e}"})
            return
        from keystone_tpu.serve.registry import RegistryError

        try:
            if body.get("clear_bad") and version:
                # explicit admin override of a rollout quarantine: the
                # operator says THIS version is deployable after all
                registry.clear_quarantine(version)
            fitted, ver = registry.load(version)
            # ship the version's AOT artifacts like the watcher does:
            # an admin swap must not silently drop the pool's artifact
            # tier (the commit moves the bundle with the generation, so
            # a None here would also cost every later supervisor heal)
            arts = registry.load_artifacts(ver)
            if rollout_cfg is not None:
                # the guarded path: the controller owns the CURRENT
                # pointer move (commit) / quarantine + restore
                # (rollback), so the plain path's pointer block below
                # must not run — a rolled-back version must not become
                # CURRENT
                from keystone_tpu.serve.rollout import guarded_swap

                info = guarded_swap(
                    self.service,
                    fitted,
                    version=ver,
                    artifacts=arts,
                    config=rollout_cfg,
                    registry=registry,
                )
                self._send(200, info)
                return
            info = self.service.swap(fitted, version=ver, artifacts=arts)
        except RegistryError as e:
            self._send(404, {"error": str(e)})
            return
        except ServiceClosed as e:
            self._send(503, {"error": str(e)})
            return
        except Exception as e:
            logger.warning("admin swap failed: %s: %s", type(e).__name__, e)
            self._send(502, {"error": f"swap failed: {type(e).__name__}: {e}"})
            return
        # the registry is the source of truth: move CURRENT to what the
        # fleet now serves, else a --watch poller (or a process restart)
        # would silently revert an admin rollback to the stale pointer
        # within one poll interval
        try:
            if registry.current() != ver:
                registry.set_current(ver)
        except Exception as e:
            logger.warning(
                "swap to %s succeeded but CURRENT update failed: %s", ver, e
            )
            info = dict(info)
            info["current_pointer_error"] = f"{type(e).__name__}: {e}"
        self._send(200, info)

    def _do_rollback(self):
        """Admin revert: swap back to the newest version in the
        service's swap history that is published in the registry and
        not quarantined, and move ``CURRENT`` with it.  Codes: 200
        reverted (the swap info dict plus ``rolled_back_to`` /
        ``rolled_back_from``), 409 no registry attached or no viable
        prior version, 503 service closed, 502 the load/swap failed."""
        registry = getattr(self.server, "registry", None)
        if registry is None:
            self._send(
                409,
                {
                    "error": "no model registry attached; start the "
                    "frontend with serve_http(svc, registry=...) or "
                    "`cli serve --model-dir`"
                },
            )
            return
        svc = self.service
        from keystone_tpu.serve.registry import RegistryError

        history = getattr(svc, "_version_history", [])
        published = set(registry.versions())
        target = None
        target_idx = None
        for idx in range(len(history) - 1, -1, -1):
            cand = history[idx]
            if cand == svc.version or cand not in published:
                continue
            if registry.quarantined(cand) is not None:
                continue
            target, target_idx = cand, idx
            break
        if target is None:
            self._send(
                409,
                {
                    "error": "no viable prior version in swap history "
                    "(nothing swapped yet, or every prior version is "
                    "unpublished/quarantined)",
                    "history": list(history),
                },
            )
            return
        from_version = svc.version
        try:
            fitted, ver = registry.load(target)
            arts = registry.load_artifacts(ver)
            info = svc.swap(fitted, version=ver, artifacts=arts)
        except RegistryError as e:
            self._send(404, {"error": str(e)})
            return
        except ServiceClosed as e:
            self._send(503, {"error": str(e)})
            return
        except Exception as e:
            logger.warning("admin rollback failed: %s: %s", type(e).__name__, e)
            self._send(
                502, {"error": f"rollback failed: {type(e).__name__}: {e}"}
            )
            return
        # truncate the walked-past suffix (including the entry swap()
        # just appended for the version we reverted FROM): a repeated
        # /rollback walks further back, never ping-pongs
        del history[target_idx:]
        metrics.inc("serve.rollout.manual_rollbacks")
        rec = svc.recorder
        if rec is not None:
            rec.ops(
                "serve.rollout",
                from_version=from_version,
                to_version=ver,
                verdict="rolled_back",
                reason="manual",
            )
        try:
            if registry.current() != ver:
                registry.set_current(ver)
        except Exception as e:
            logger.warning(
                "rollback to %s succeeded but CURRENT update failed: %s",
                ver,
                e,
            )
            info = dict(info)
            info["current_pointer_error"] = f"{type(e).__name__}: {e}"
        info = dict(info)
        info["rolled_back_to"] = ver
        info["rolled_back_from"] = from_version
        self._send(200, info)


class HttpFrontend:
    """A :class:`ThreadingHTTPServer` bound to a service.  ``start()``
    runs it on a background thread (tests, embedding); ``serve_forever``
    runs it on the caller's thread (the CLI).  ``port=0`` binds an
    ephemeral port, readable from :attr:`port` after construction."""

    def __init__(
        self,
        service: PipelineService,
        host: str = "127.0.0.1",
        port: int = 8000,
        registry=None,
        trace_dump_dir: Optional[str] = None,
    ):
        self.server = ThreadingHTTPServer((host, port), _Handler)
        self.server.service = service  # type: ignore[attr-defined]
        #: ModelRegistry backing POST /swap (None: endpoint answers 409)
        self.server.registry = registry  # type: ignore[attr-defined]
        #: default directory for POST /tracez/dump (None: the endpoint
        #: needs an explicit "dir" in its body)
        self.server.trace_dump_dir = trace_dump_dir  # type: ignore[attr-defined]
        self.server.daemon_threads = True
        self.host = host
        self._thread: Optional[threading.Thread] = None
        self._started = False

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    def start(self) -> "HttpFrontend":
        self._started = True
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._started = True
        self.server.serve_forever()

    def stop(self) -> None:
        # shutdown() blocks on an event only serve_forever sets — on a
        # never-started frontend it would wait forever; just close the
        # socket in that case
        if self._started:
            self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self) -> "HttpFrontend":
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


class _DelegateServer:
    """The duck-typed ``server`` object :class:`_Handler` needs when a
    connection arrives from OUTSIDE a ``ThreadingHTTPServer`` — the
    async ingress (``serve/ingress.py``) sniffs a non-binary client
    and hands the accepted socket here, so every HTTP endpoint keeps
    one implementation while the event loop keeps the fast path."""

    def __init__(
        self, service: PipelineService, registry=None, trace_dump_dir=None
    ):
        self.service = service
        self.registry = registry
        self.trace_dump_dir = trace_dump_dir


def handle_http_connection(
    sock, client_address, service: PipelineService, registry=None,
    trace_dump_dir=None,
) -> None:
    """Serve one already-accepted connection with the stdlib handler
    (blocking; run it on its own thread).  The HTTP/1.1 keep-alive loop
    inside ``BaseHTTPRequestHandler.handle`` serves the connection's
    whole request stream; the socket is closed on return."""
    try:
        _Handler(
            sock,
            client_address,
            _DelegateServer(service, registry, trace_dump_dir),
        )
    except (BrokenPipeError, ConnectionResetError, TimeoutError, OSError) as e:
        logger.debug("http: delegated connection died: %s", e)
    finally:
        try:
            sock.close()
        except OSError:
            pass


def serve_http(
    service: PipelineService,
    host: str = "127.0.0.1",
    port: int = 8000,
    registry=None,
    trace_dump_dir: Optional[str] = None,
) -> HttpFrontend:
    """Stand up (and start) the HTTP front end for ``service`` on a
    background thread; returns the :class:`HttpFrontend` (``.port`` for
    ephemeral binds, ``.stop()`` to shut down).  ``registry``: a
    :class:`~keystone_tpu.serve.registry.ModelRegistry` enabling the
    ``POST /swap`` admin endpoint.  ``trace_dump_dir``: default
    directory for ``POST /tracez/dump`` snapshots."""
    return HttpFrontend(
        service,
        host=host,
        port=port,
        registry=registry,
        trace_dump_dir=trace_dump_dir,
    ).start()
