"""Versioned model registry on the durable layer, plus the poll-watcher
that turns a registry publish into a live fleet hot-swap.

Layout (everything published through ``utils/durable.atomic_write`` —
tmp + fsync + rename + BLAKE2b sidecar, so a crash mid-publish never
destroys the previous good version and readers never observe a torn
one)::

    <root>/
      v0001/model.pkl     (+ model.pkl.b2 sidecar)
      v0002/model.pkl     (+ sidecar)
      v0002/BAD           (+ sidecar)  — rollout-rollback quarantine mark
      CURRENT             (+ sidecar)  — the version id serving traffic

``publish`` writes the model blob FIRST and flips ``CURRENT`` last, so
a watcher that observes the new pointer always finds a fully-published
payload behind it.  ``load(None)`` (the deploy path) scans
current → newest → oldest and skips corrupt/unreadable candidates — a
damaged newest version degrades to the previous one instead of taking
the fleet down; ``load(version)`` (the forensic path) is strict.

:class:`RegistryWatcher` is what ``cli.py serve --watch`` runs: poll
``current()`` every N seconds, and when it moves, load the new version
and :meth:`~keystone_tpu.serve.service.PipelineService.swap` it into
the serving fleet (prime in the background, commit at the flush
boundary).  Failures are logged-and-counted, never fatal: a bad publish
must not kill the process serving the good version.
"""

from __future__ import annotations

import logging
import os
import pickle
import random
import re
import threading
from typing import List, Optional, Tuple

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import metrics
from keystone_tpu.utils import durable

logger = logging.getLogger(__name__)

CURRENT = "CURRENT"
MODEL_FILE = "model.pkl"
ARTIFACTS_DIR = "artifacts"
MANIFEST_FILE = "MANIFEST.json"
#: the quarantine sidecar (ISSUE 19): an automatic rollout rollback
#: durably marks the condemned version with ``<vdir>/BAD`` (checksummed
#: like every other registry file), so the watcher and the ``load(None)``
#: deploy walk skip it instead of re-rolling into the same bad publish.
#: Re-publishing the version id (or an explicit admin clear) removes it.
BAD_FILE = "BAD"

_VERSION_RE = re.compile(r"^v(\d+)$")


class RegistryError(RuntimeError):
    """A registry operation failed structurally (unknown version,
    empty registry, malformed version id) — as opposed to transient I/O
    (retried) or corruption (:class:`~keystone_tpu.utils.durable.CorruptStateError`)."""


def write_artifact_bundle(
    adir: str, bundle: dict, describe: str = "artifact bundle"
) -> None:
    """Write an AOT artifact bundle into ``adir`` in the registry
    layout: one checksummed blob per entry, ``MANIFEST.json`` LAST (a
    crash mid-write leaves blobs without a manifest, which
    ``load_artifacts`` reads as "no artifact tier") — every file via
    ``durable.atomic_write`` + BLAKE2b sidecar, transient errors
    retried.  The single writer behind ``ModelRegistry.publish(...,
    artifacts=)`` and ``keystone export --out``, so the two layouts
    cannot drift."""
    import json

    os.makedirs(adir, exist_ok=True)
    manifest = bundle.get("manifest") or {}
    blobs = bundle.get("blobs") or {}

    def _blob_writer(data: bytes):
        def _w(tmp: str) -> None:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())

        return _w

    for key, ent in (manifest.get("entries") or {}).items():
        data = blobs.get(key)
        if data is None:
            raise RegistryError(f"artifact bundle entry {key!r} has no blob")
        durable.with_retries(
            lambda p=os.path.join(adir, ent["file"]), d=data: (
                durable.atomic_write(p, _blob_writer(d))
            ),
            description=f"{describe}/{key}",
        )
    mtext = json.dumps(manifest, indent=2, sort_keys=True).encode()
    durable.with_retries(
        lambda: durable.atomic_write(
            os.path.join(adir, MANIFEST_FILE), _blob_writer(mtext)
        ),
        description=f"{describe} manifest",
    )


class ModelRegistry:
    """Filesystem-backed versioned store of fitted pipelines."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------ paths
    def version_dir(self, version: str) -> str:
        return os.path.join(self.root, version)

    def model_path(self, version: str) -> str:
        return os.path.join(self.version_dir(version), MODEL_FILE)

    def artifacts_dir(self, version: str) -> str:
        return os.path.join(self.version_dir(version), ARTIFACTS_DIR)

    def _current_path(self) -> str:
        return os.path.join(self.root, CURRENT)

    def bad_path(self, version: str) -> str:
        return os.path.join(self.version_dir(version), BAD_FILE)

    # ------------------------------------------------------------ reads
    def versions(self) -> List[str]:
        """Published version ids, oldest → newest (numeric order)."""
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return []
        for name in entries:
            m = _VERSION_RE.match(name)
            if m and os.path.exists(self.model_path(name)):
                out.append((int(m.group(1)), name))
        return [name for _, name in sorted(out)]

    def quarantined(self, version: str) -> Optional[str]:
        """The quarantine reason when ``version`` carries a ``BAD``
        mark, else None.  Fail-safe: an unreadable/corrupt mark still
        counts as quarantined — a half-written condemnation must not
        re-admit the version it condemns."""
        path = self.bad_path(version)
        if not os.path.exists(path):
            return None
        try:
            durable.verify_checksum(path)
            with open(path) as f:
                return f.read().strip() or "quarantined"
        except (OSError, durable.CorruptStateError):
            return "quarantined (mark unreadable)"

    def quarantine(self, version: str, reason: str = "") -> None:
        """Durably mark ``version`` bad: an automatic rollout rollback
        (serve/rollout.py) calls this so the watcher's next poll — and
        the ``load(None)`` deploy walk — skip the version instead of
        re-deploying the publish the guard just condemned.  Same
        atomic-write + BLAKE2b-sidecar discipline as every other
        registry file.  Cleared by re-publishing the version id
        (:meth:`publish`) or :meth:`clear_quarantine`."""
        if not os.path.exists(self.model_path(version)):
            raise RegistryError(
                f"cannot quarantine unpublished version {version!r}"
            )
        text = (reason or "quarantined").strip() + "\n"

        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())

        durable.with_retries(
            lambda: durable.atomic_write(self.bad_path(version), _write),
            description=f"registry quarantine {version}",
        )
        metrics.inc("serve.registry_quarantines")
        logger.warning(
            "quarantined %s in registry %s: %s",
            version,
            self.root,
            text.strip(),
        )

    def clear_quarantine(self, version: str) -> bool:
        """Remove ``version``'s quarantine mark (the explicit operator
        override — ``keystone publish`` of the same id does this
        implicitly).  Returns True when a mark was removed."""
        path = self.bad_path(version)
        removed = False
        for p in (path, path + durable.CHECKSUM_SUFFIX):
            try:
                os.unlink(p)
                removed = True
            except OSError:
                pass
        if removed:
            logger.info(
                "cleared quarantine on %s in registry %s", version, self.root
            )
        return removed

    def current(self, strict: bool = False) -> Optional[str]:
        """The version id ``CURRENT`` points at (None: nothing
        published).  An unreadable/corrupt pointer is "no news" by
        default; ``strict=True`` re-raises it instead — the watcher
        uses strict mode so a corrupt registry counts as a poll ERROR
        (and backs off) rather than being silently polled forever."""
        path = self._current_path()
        if not os.path.exists(path):
            return None
        try:
            durable.verify_checksum(path)
            with open(path) as f:
                v = f.read().strip()
        except (OSError, durable.CorruptStateError) as e:
            if strict:
                raise
            logger.warning("unreadable CURRENT pointer in %s: %s", self.root, e)
            return None
        return v or None

    def _read_model(self, version: str):
        path = self.model_path(version)

        def _read():
            durable.verify_checksum(path)
            with open(path, "rb") as f:
                return pickle.load(f)

        return durable.with_retries(
            _read, description=f"registry load {version}"
        )

    def load(self, version: Optional[str] = None) -> Tuple[object, str]:
        """Load a fitted pipeline; returns ``(fitted, version)``.

        Explicit ``version``: strict — corruption raises.  ``None``:
        the deploy path — try ``current()``, then every published
        version newest → oldest, skipping corrupt/unreadable candidates
        (counted as ``serve.registry_fallback``)."""
        if version is not None:
            if version not in self.versions():
                raise RegistryError(
                    f"version {version!r} not in registry {self.root} "
                    f"(have: {self.versions()})"
                )
            fitted = self._read_model(version)
            metrics.inc("serve.registry_loads")
            return fitted, version
        candidates = []
        cur = self.current()
        if cur:
            candidates.append(cur)
        candidates.extend(
            v for v in reversed(self.versions()) if v not in candidates
        )
        if not candidates:
            raise RegistryError(f"registry {self.root} has no versions")
        for i, cand in enumerate(candidates):
            why_bad = self.quarantined(cand)
            if why_bad is not None:
                # a rollout-condemned version is as undeployable as a
                # corrupt one: the walk degrades to the next candidate
                # (but the explicit load(version) forensic path still
                # reads it — an operator debugging the bad publish must
                # be able to load exactly what failed)
                metrics.inc("serve.registry_quarantine_skips")
                logger.warning(
                    "skipping quarantined registry version %s: %s",
                    cand,
                    why_bad,
                )
                continue
            try:
                fitted = self._read_model(cand)
            except Exception as e:
                logger.warning(
                    "skipping unreadable registry version %s: %s", cand, e
                )
                continue
            metrics.inc("serve.registry_loads")
            if i > 0:
                metrics.inc("serve.registry_fallback")
                logger.warning(
                    "serving fallback version %s (newer candidates invalid)",
                    cand,
                )
            return fitted, cand
        raise RegistryError(
            f"registry {self.root}: no loadable version among {candidates}"
        )

    # ----------------------------------------------------------- writes
    def next_version(self) -> str:
        vs = self.versions()
        n = int(_VERSION_RE.match(vs[-1]).group(1)) + 1 if vs else 1
        return f"v{n:04d}"

    def publish(
        self,
        fitted,
        version: Optional[str] = None,
        set_current: bool = True,
        artifacts: Optional[dict] = None,
    ) -> str:
        """Durably publish a fitted pipeline as a new version and
        (default) flip ``CURRENT`` to it.  Model blob lands before the
        pointer moves, so watchers never race a half-published version.

        ``artifacts``: an AOT artifact bundle
        (``FrozenApplier.export_artifacts``) stored under the version
        dir next to the model — every blob and the manifest ride the
        same atomic-write + BLAKE2b-sidecar discipline, and they land
        BEFORE the model blob (which lands before the pointer), so a
        watcher that sees the new version always finds its artifacts
        fully published (or absent as a unit, never torn)."""
        version = version or self.next_version()
        if not _VERSION_RE.match(version):
            raise RegistryError(
                f"version ids must look like v0001, got {version!r}"
            )
        vdir = self.version_dir(version)
        os.makedirs(vdir, exist_ok=True)
        blob = pickle.dumps(fitted)
        if artifacts:
            self._write_artifacts(version, artifacts)

        def _write(tmp: str) -> None:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())

        durable.with_retries(
            lambda: durable.atomic_write(self.model_path(version), _write),
            description=f"registry publish {version}",
        )
        # re-publishing a version id is the operator's explicit word
        # that the content is good again: lift any quarantine BEFORE
        # the pointer moves, or set_current would re-point at a version
        # the watcher still refuses
        self.clear_quarantine(version)
        if set_current:
            self.set_current(version)
        metrics.inc("serve.registry_published")
        logger.info("published %s to registry %s", version, self.root)
        return version

    def publish_artifacts(self, version: str, bundle: dict) -> None:
        """Attach an AOT artifact bundle to an ALREADY-published version
        (``keystone export --model-dir`` retrofits the current version
        this way).  Same durable-write discipline as :meth:`publish`."""
        if not os.path.exists(self.model_path(version)):
            raise RegistryError(
                f"cannot attach artifacts to unpublished version {version!r}"
            )
        self._write_artifacts(version, bundle)

    def _write_artifacts(self, version: str, bundle: dict) -> None:
        write_artifact_bundle(
            self.artifacts_dir(version),
            bundle,
            describe=f"registry artifact {version}",
        )

    def load_artifacts(self, version: str) -> Optional[dict]:
        """The AOT artifact bundle published with ``version``, or None
        when the version has none (or its manifest is unreadable).

        Corrupt-tolerant, mirroring ``load(None)``'s discipline: a bad
        manifest drops the whole tier, a bad individual blob drops just
        that bucket — both counted as ``serve.artifact_fallbacks`` and
        logged, NEVER raised: a damaged artifact must degrade a deploy
        to recompilation, not fail it.  The ``serve.artifact_load``
        fault site fires per file read (chaos plans corrupt/fail
        exactly this)."""
        import json

        adir = self.artifacts_dir(version)
        mpath = os.path.join(adir, MANIFEST_FILE)
        if not os.path.exists(mpath):
            return None
        try:
            fault_point("serve.artifact_load", path=mpath)
            durable.verify_checksum(mpath)
            with open(mpath, "rb") as f:
                manifest = json.loads(f.read().decode())
        except Exception as e:
            metrics.inc("serve.artifact_fallbacks")
            logger.warning(
                "unreadable artifact manifest for %s (%s: %s); version "
                "will compile",
                version,
                type(e).__name__,
                e,
            )
            return None
        blobs = {}
        for key, ent in (manifest.get("entries") or {}).items():
            path = os.path.join(adir, str(ent.get("file", "")))
            try:
                fault_point("serve.artifact_load", path=path)
                durable.verify_checksum(path)
                with open(path, "rb") as f:
                    blobs[key] = f.read()
            except Exception as e:
                metrics.inc("serve.artifact_fallbacks")
                logger.warning(
                    "skipping unreadable artifact %s/%s (%s: %s); that "
                    "bucket will compile",
                    version,
                    key,
                    type(e).__name__,
                    e,
                )
        if not blobs:
            return None
        return {"manifest": manifest, "blobs": blobs}

    def set_current(self, version: str) -> None:
        if not os.path.exists(self.model_path(version)):
            raise RegistryError(
                f"cannot point CURRENT at unpublished version {version!r}"
            )

        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                f.write(version + "\n")
                f.flush()
                os.fsync(f.fileno())

        durable.with_retries(
            lambda: durable.atomic_write(self._current_path(), _write),
            description="registry CURRENT update",
        )


class RegistryWatcher:
    """Poll a registry and hot-swap the service when ``CURRENT`` moves.

    ``cli.py serve --watch N`` runs one of these; tests drive it with a
    sub-second interval.  One failed poll/load/swap is logged and
    counted (``serve.watch_errors``) — the fleet keeps serving the
    version it has.  CONSECUTIVE failures back off exponentially
    (jittered ±50%, capped at ``max_backoff_seconds``) instead of
    hammering a corrupt registry at the fixed interval — and so a
    thundering herd of watchers over shared storage decorrelates; the
    live wait is exported as the ``serve.watch_backoff_seconds`` gauge
    (0 while healthy).  The first successful poll resets the cadence."""

    def __init__(
        self,
        service,
        registry: ModelRegistry,
        poll_seconds: float = 5.0,
        on_swap=None,
        max_backoff_seconds: float = 300.0,
        rollout=None,
    ):
        self.service = service
        self.registry = registry
        self.poll_seconds = max(0.05, float(poll_seconds))
        self.max_backoff_seconds = max(
            self.poll_seconds, float(max_backoff_seconds)
        )
        self.on_swap = on_swap
        #: a :class:`~keystone_tpu.serve.rollout.RolloutConfig` (with a
        #: canary fraction) routes every watcher swap through the
        #: guarded-rollout path (``cli serve --watch --canary``): a bad
        #: publish canaries, rolls back, and is quarantined instead of
        #: taking the fleet.  None = the plain swap path, unchanged.
        self.rollout = rollout
        #: once-per-version log damper for quarantined-CURRENT skips
        self._last_quarantine_skip: Optional[str] = None
        self._consecutive_errors = 0
        self._rng = random.Random()  # jitter only; no determinism contract
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-registry-watch"
        )

    def start(self) -> "RegistryWatcher":
        self._thread.start()
        return self

    def next_wait(self) -> float:
        """The wait before the next poll: the configured interval while
        healthy, ``min(cap, interval·2^errors)`` jittered to 50–150%
        after consecutive failures (never below the base interval)."""
        if self._consecutive_errors <= 0:
            metrics.set_gauge("serve.watch_backoff_seconds", 0.0)
            return self.poll_seconds
        # exponent clamped: 2.0**1024 raises OverflowError, and a
        # registry broken for days would otherwise kill the watcher
        # thread from next_wait (outside the loop's try)
        backoff = min(
            self.max_backoff_seconds,
            self.poll_seconds * (2.0 ** min(self._consecutive_errors, 32)),
        )
        wait = min(
            self.max_backoff_seconds,
            max(self.poll_seconds, backoff * (0.5 + self._rng.random())),
        )
        metrics.set_gauge("serve.watch_backoff_seconds", wait)
        return wait

    def _loop(self) -> None:
        while not self._stop.wait(self.next_wait()):
            try:
                self._poll_once()
                self._consecutive_errors = 0
            except Exception as e:
                self._consecutive_errors += 1
                metrics.inc("serve.watch_errors")
                logger.warning(
                    "registry watch iteration failed (%d consecutive): %s",
                    self._consecutive_errors,
                    e,
                )
                rec = getattr(self.service, "recorder", None)
                if rec is not None:
                    rec.ops(
                        "serve.watch_error",
                        error=f"{type(e).__name__}: {e}",
                        n=self._consecutive_errors,
                    )

    def _poll_once(self) -> None:
        # strict: a corrupt CURRENT pointer is a poll error (backoff),
        # not silent "no news" forever
        cur = self.registry.current(strict=True)
        if not cur or cur == self.service.version:
            return
        why_bad = self.registry.quarantined(cur)
        if why_bad is not None:
            # a quarantined CURRENT is "no news", not an error: a
            # rollout rollback condemned exactly this version, and
            # re-deploying it every poll would undo the rollback.
            # Logged once per version (the poll loop is hot).
            metrics.inc("serve.watch_quarantine_skips")
            if cur != self._last_quarantine_skip:
                self._last_quarantine_skip = cur
                logger.warning(
                    "watcher skipping quarantined CURRENT %s: %s",
                    cur,
                    why_bad,
                )
            return
        fitted, ver = self.registry.load(cur)
        # best-effort AOT tier: a version published without artifacts
        # (or with damaged ones) swaps in via the compile ladder
        arts = self.registry.load_artifacts(ver)
        if self.rollout is not None and self.rollout.canary is not None:
            from keystone_tpu.serve.rollout import CanaryController

            info = CanaryController(
                self.service, self.rollout, registry=self.registry
            ).run(fitted, version=ver, artifacts=arts)
            if info.get("verdict") != "committed":
                metrics.inc("serve.watch_rollbacks")
                logger.warning(
                    "watcher canary of %s rolled back (%s); version "
                    "quarantined",
                    ver,
                    info.get("reason"),
                )
                if self.on_swap is not None:
                    self.on_swap(info)
                return
        else:
            info = self.service.swap(fitted, version=ver, artifacts=arts)
        metrics.inc("serve.watch_swaps")
        logger.info(
            "watcher swapped in %s (pause %.1f ms)",
            ver,
            1000.0 * info.get("pause_seconds", 0.0),
        )
        rec = getattr(self.service, "recorder", None)
        if rec is not None:
            # control-plane moment in the flight recorder: a
            # watcher-driven rollout shows up in /tracez between
            # the request traces it interleaved with
            rec.ops(
                "serve.watch_swap",
                version=ver,
                pause_seconds=info.get("pause_seconds", 0.0),
            )
        if self.on_swap is not None:
            self.on_swap(info)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
