"""Online inference service: dynamic micro-batching over a frozen
pipeline, with admission control and deadline-aware shedding.

KeystoneML pipelines are trained once and then applied to a stream of
requests; the reference served that stream through Velox/Spark batch
jobs, and Clipper-style systems (Crankshaw et al., NSDI 2017) showed the
serving win is a thin layer over the frozen model: micro-batch requests
to saturate the accelerator, bound the queue so tail latency stays
bounded, and shed work that cannot meet its deadline.  This module is
that layer for ``keystone_tpu``:

- **Frozen apply** — :class:`~keystone_tpu.workflow.FrozenApplier` runs
  the whole-pipeline optimizer once at service construction; each flush
  binds one padded batch to the pre-optimized graph.
- **Padding buckets** — every flush is padded UP to a fixed bucket size
  (``iter_row_chunks``, the same pad discipline as chunked offline
  applies), so the set of compiled program shapes is finite and
  cache-hot: a single-datum request rides the smallest bucket's batch
  program instead of tracing a per-datum one.
- **Dynamic micro-batching** — a background worker drains the bounded
  FIFO queue, flushing when ``max_batch`` requests are waiting or the
  oldest has waited ``max_wait_ms``, whichever first.
- **Admission control** — ``submit`` past ``queue_bound`` raises
  :class:`Overloaded` (backpressure to the caller); requests whose
  :class:`~keystone_tpu.utils.guard.Deadline` would expire before the
  batch completes (EWMA-predicted) are shed with
  :class:`~keystone_tpu.utils.guard.DeadlineExceeded` instead of
  wasting device time on an answer nobody is waiting for.
- **Degradation** — when every rider carries a deadline, the batch's
  LOOSEST one plumbs into the
  :class:`~keystone_tpu.workflow.GraphExecutor`, so ``optional`` /
  ``with_fallback`` stages degrade on the serve path exactly as they do
  in fits (loosest, not tightest: one near-expiry straggler must never
  deadline-fail a flush its co-riders could comfortably complete).

Observability (``keystone_tpu.obs``): ``serve.queue_depth`` gauge,
``serve.batch_rows``/``serve.batch_seconds``/``serve.latency_seconds``
histograms, ``serve.submitted``/``completed``/``shed``/``rejected``/
``batch_errors``/``deadline_miss`` counters, and one ``serve.batch``
ledger span per flush.  Fault injection (``keystone_tpu.faults``):
sites ``serve.enqueue`` (admission path) and ``serve.batch`` (worker
flush) — chaos plans exercise overload and hang scenarios.

Usage::

    svc = serve(fitted, max_batch=32, max_wait_ms=5, queue_bound=256,
                deadline_ms=100, example=x0)
    fut = svc.submit(x)            # concurrent.futures.Future
    y = fut.result()
    svc.close()                    # drains in-flight requests

**Replica fleet (PR 8)** — the batcher no longer applies flushes
inline: it forms batches and hands them to a
:class:`~keystone_tpu.serve.fleet.ReplicaPool` router, which dispatches
each flush to the least-loaded of N per-device replicas (falling past
replicas whose breaker is open).  ``replicas=1`` with no explicit
devices is the PR-5 single-device behavior bit-for-bit — the pool wraps
the given applier directly.  :meth:`PipelineService.swap` performs a
blue/green model hot-swap: stage a new generation of replicas, prime
their padding-bucket programs while the old generation keeps serving,
then commit at the flush boundary — queued requests never drop.  The
versioned model store feeding swaps is
``keystone_tpu/serve/registry.py``.

**Request-scoped tracing (ISSUE 9)** — every request carries a
``request_id`` (honored from the caller / ``X-Request-Id``, else
generated) from ingress through enqueue → batch flush → replica apply
to its terminal outcome (``completed`` / ``shed`` / ``rejected`` /
``degraded`` / ``error``).  The trace lands in an always-on in-memory
:class:`~keystone_tpu.obs.recorder.FlightRecorder` (bounded, tail-based
retention — shed/error/slow traces pinned) that is independent of the
JSONL ledger, so a shed request is explainable live via
``GET /requestz/<id>`` even with the ledger off.  When a ledger IS
active, ``serve.batch`` spans additionally record their rider request
ids as span links and each terminal outcome emits a ``serve.request``
event, so ``tools/trace_report.py`` reconstructs the same chains from
either source.  Span parenting survives the batcher and replica worker
threads via the PR-4 ``ledger.capture_context``/``restore_context``
machinery (captured at service construction, restored in every worker).
``serve(recorder=False)`` disables all of it — the PR-5 single-batcher
path and solver HLO are byte-identical with the recorder off (pinned).

``GET /statusz`` reads rolling-window latency percentiles from
:class:`~keystone_tpu.obs.metrics.WindowedHistogram` wrappers (ring of
per-interval histograms merged on read, ms-resolution buckets) that
also feed the cumulative ``/metrics`` series, plus an SLO error-budget
burn rate against a configurable latency objective (``slo_ms``,
defaulting to the service deadline).

**Self-healing (ISSUE 10)** — three mechanisms close the loop between
detection and recovery without an operator: (1) the
:class:`~keystone_tpu.serve.fleet.ReplicaSupervisor` restarts dead or
wedged replica workers in place (re-clone from the pool's source,
re-prime, rejoin the router) and quarantines a slot that keeps dying;
(2) a flush failing with a request-attributable error is **bisected**
— recursively halved over the same padding buckets — until the poison
request is isolated: it alone fails (typed :class:`PoisonRequest`,
HTTP 422, recorder-pinned trace), innocent riders complete, and a
content-keyed quarantine cache refuses the same payload at admission
thereafter; (3) **hedged dispatch** (opt-in ``hedge_ms``) re-enqueues
a batch still stuck in a straggling replica's queue onto a second
replica — first claim wins, the loser is cancelled without device work
and charged breaker-neutral.  When the WHOLE fleet is down (every
replica quarantined/dead/breaker-open) the service fails fast instead
of force-routing: submits raise
:class:`~keystone_tpu.serve.fleet.FleetUnavailable` (503 + derived
``Retry-After`` at HTTP, non-200 ``/healthz``) until the supervisor's
first successful restart — or a breaker's half-open probe — re-admits
traffic.

**Multi-tenant serving (ISSUE 14)** — ``serve/tenants.py`` subclasses
this service to co-serve N pipelines behind one batcher + fleet:
per-tenant admission queues/quotas/deadlines/breakers, deficit-round-
robin combined flushes, and the cross-pipeline shared stage pool
(``workflow/stage_pool.py``) computing shared featurization prefixes
once per flush.  The tenant hooks below (``_resolve_tenant``,
``_check_bound_locked``, ``_push_locked``, ``_account_tenant``, ...)
are inert on this base class — the single-tenant path is unchanged.

**Process fleet + autoscaling (ISSUE 15)** — ``workers=N`` promotes
replica COMPUTE into worker processes (``serve/procfleet.py`` over the
``serve/wire.py`` shared-memory protocol) behind this same control
plane, so a multi-core host's throughput is bounded by cores, not the
GIL; a worker death mid-flush raises :class:`WorkerCrashed`, the flush
is un-claimed and requeued, and the supervisor's replacement serves it
— zero lost futures.  ``autoscale={...}`` starts a
:class:`~keystone_tpu.serve.autoscale.Autoscaler` control thread that
resizes the fleet (``scale_to``) and retunes the dispatch window from
windowed occupancy, queue depth, SLO burn, and the shared-pool hit
rate.  ``workers=0`` (default) is the threaded path, byte-for-byte.

The HTTP front end is ``keystone_tpu/serve/http.py``; the CLI entry is
``python -m keystone_tpu.cli serve``; the load generator is
``tools/serve_bench.py``.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import logging
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.obs.recorder import FlightRecorder, new_request_id
from keystone_tpu.serve.fleet import (
    FleetUnavailable,
    ReplicaPool,
    ReplicaSupervisor,
)
from keystone_tpu.serve.procfleet import WorkerCrashed
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

# millisecond-resolution histogram bounds for the serve-path latencies:
# the registry defaults alias every sub-millisecond flush into one
# bucket, which makes windowed p99 estimates (and Prometheus
# histogram_quantile) useless at serving timescales.  Registered at
# import, before any service records a sample.
metrics.register_buckets("serve.latency_seconds", metrics.LATENCY_MS_BUCKETS)
metrics.register_buckets("serve.batch_seconds", metrics.LATENCY_MS_BUCKETS)
metrics.register_buckets("serve.failed_wait_seconds", metrics.LATENCY_MS_BUCKETS)

#: EWMA smoothing for the per-batch latency predictor the shed decision
#: uses: new = (1-ALPHA)*old + ALPHA*sample.  0.3 tracks load shifts
#: within a few batches without letting one outlier batch (a compile, a
#: GC pause) shed everything behind it.
_EWMA_ALPHA = 0.3


class Overloaded(RuntimeError):
    """Admission control refused the request: the queue is at its bound.
    Backpressure is the caller's signal to retry later or route away —
    deliberately NOT an ``OSError``, so generic transient-I/O retry
    loops don't hammer an already-overloaded service."""


class ServiceClosed(RuntimeError):
    """The service is shut down (or shutting down) and accepts no new
    requests."""


class PoisonRequest(ValueError):
    """THIS request's content makes the model fail — isolated by batch
    bisection (the request alone reproduces the error), or matched
    against the quarantine cache of previously-isolated content.  A
    ``ValueError`` on purpose: it is the CLIENT's fault (the HTTP layer
    answers 422, and it does not burn the server's SLO error budget),
    and retrying it unchanged will fail again."""


#: bound on the content-keyed poison quarantine cache (LRU eviction)
_POISON_CACHE_CAP = 512

#: quarantine entries expire after this long: _poison_suspect is a
#: type-level heuristic, and a transient third-party RuntimeError
#: (e.g. an XLA RESOURCE_EXHAUSTED during the singleton re-run) could
#: misclassify an innocent payload — a TTL bounds that blast radius to
#: minutes (a real poison resubmitted later just re-bisects, one extra
#: isolation per TTL window)
_POISON_TTL_S = 600.0

#: hedge delay = max(configured floor, this multiple of the EWMA batch
#: time) — the cheap stand-in for a tail quantile: for exponential-ish
#: flush times 3× the mean sits near p95, so hedges fire on genuine
#: stragglers, not on every flush
_HEDGE_EWMA_MULT = 3.0


def _content_key(arr: np.ndarray) -> bytes:
    """The quarantine-cache key: a BLAKE2b digest of the request's
    dtype + shape + bytes.  Content-keyed, not id-keyed: the same bad
    payload resubmitted (or replayed by a retrying client) short-
    circuits at admission without touching a device."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.digest()


def _poison_suspect(exc: BaseException) -> bool:
    """Is this apply failure plausibly caused by a request's CONTENT
    (worth bisecting), as opposed to infrastructure?  The repo-wide
    convention makes this a type test: every infrastructure failure
    rides ``OSError`` (``FaultInjected``, ``DeadlineExceeded``, real
    I/O), breaker refusals are ``CircuitOpenError``, and resource
    exhaustion is ``MemoryError`` — everything else (the ``ValueError``
    /``FloatingPointError``/XLA-check family) is content-shaped."""
    return not isinstance(
        exc, (OSError, MemoryError, guard.CircuitOpenError)
    )


#: distinguishes "caller said nothing" from an explicit None for knobs
#: where None is itself a meaningful setting (hedge_ms=None = hedging
#: OFF must stay OFF even when a plan carries a hedge)
_UNSET = object()


def _planned_knob(name: str):
    """The installed PhysicalPlan's value for a serving knob, or None —
    the third tier of the precedence ladder (explicit arg > env > plan >
    static default).  Guarded import: with no planner in play this is a
    cheap no-op and the legacy path stays byte-identical."""
    try:
        from keystone_tpu.planner import registry as _plans

        return _plans.planned_knob(name)
    except Exception:
        return None


def _plan_status_safe():
    """The installed plan's ``/statusz`` section, or None (guarded the
    same way as :func:`_planned_knob`)."""
    try:
        from keystone_tpu.planner import registry as _plans

        return _plans.plan_status()
    except Exception:
        return None


def default_buckets(max_batch: int, min_bucket: int = 8) -> Tuple[int, ...]:
    """Power-of-two padding buckets up to (and including) ``max_batch``.
    The smallest bucket bounds single-datum padding waste; the largest
    equals ``max_batch`` so a full flush pads nothing."""
    max_batch = max(1, int(max_batch))
    b = min(int(min_bucket), max_batch)
    out = []
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


class _Request:
    __slots__ = (
        "x",
        "deadline",
        "future",
        "t_submit",
        "request_id",
        "tenant",
        "block",
        "row",
        "gen",
    )

    def __init__(
        self,
        x,
        deadline: Optional[guard.Deadline],
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ):
        self.x = x
        self.deadline = deadline
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        #: trace identity; None when tracing is off for this request —
        #: every trace hook takes the None id as its inert no-op
        self.request_id = request_id
        #: multi-tenant routing label (serve/tenants.py); None on the
        #: single-tenant service — every tenant hook is inert then
        self.tenant = tenant
        #: slab-direct admission (serve/ingress.py): when set, ``x`` is
        #: row ``row`` of admission block ``block`` (a zero-copy view of
        #: a shared-memory slab).  A flush formed of exactly one block's
        #: rows in order skips the stack+pad copies (_apply_reqs) and —
        #: on a process fleet — ships the slab by REFERENCE to the
        #: worker.  None on every other submit path.
        self.block = None
        self.row = 0
        #: rollout generation tag (serve/rollout.py): "canary" when the
        #: flush carrying this request was routed to a staged canary
        #: generation, "live" when a canary window explicitly kept it on
        #: the serving generation; None outside any canary window —
        #: every rollout hook treats None as "live"
        self.gen: Optional[str] = None


def _block_of(reqs) -> Optional[object]:
    """The admission block a flush is a complete in-order image of, or
    None.  The preformed-flush fast path requires EXACTLY the block's
    rows 0..count-1 in order: a shed/cancelled rider, a flush mixing two
    submits, or a block spanning flushes all fall back to the stack+pad
    copy path (which remains correct for views)."""
    blk = getattr(reqs[0], "block", None)
    if blk is None or not getattr(blk, "admission_block", False):
        return None
    if len(reqs) != blk.count:
        return None
    for i, r in enumerate(reqs):
        if r.block is not blk or r.row != i:
            return None
    return blk


class _Flush:
    """One formed micro-batch in flight through the router.

    The claim state machine is what makes hedging and worker-crash
    requeues safe: a flush may sit in TWO replica queues (hedged) or be
    re-run after a crash requeue, but ``claim()`` admits exactly ONE
    runner — every other popper sees the claim spent and skips without
    device work (the hedge loser's "cancellation").  ``abort()`` stops a
    never-claimed flush from running at all (a wedged worker's in-hand
    batch whose riders the supervisor already failed)."""

    QUEUED, RUNNING, DONE, ABORTED = "queued", "running", "done", "aborted"

    __slots__ = ("riders", "bid", "primary", "hedged", "_state", "_lock")

    def __init__(self, riders: list, bid: str):
        self.riders = riders
        self.bid = bid
        #: index of the replica the router first dispatched to (set by
        #: ReplicaPool.dispatch under the router lock)
        self.primary: Optional[int] = None
        self.hedged = False
        self._state = _Flush.QUEUED
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        return self._state

    def unflushed(self) -> bool:
        """Still waiting in a queue — the hedge monitor's fire test."""
        return self._state == _Flush.QUEUED

    def claim(self) -> bool:
        """First caller wins the right to run this flush."""
        with self._lock:
            if self._state != _Flush.QUEUED:
                return False
            self._state = _Flush.RUNNING
            return True

    def done(self) -> None:
        with self._lock:
            if self._state == _Flush.RUNNING:
                self._state = _Flush.DONE

    def abort(self) -> bool:
        """Spend the claim without running (supervisor abandonment).
        True when the flush had never been claimed — its riders can be
        failed knowing no result will ever race the failure."""
        with self._lock:
            if self._state == _Flush.QUEUED:
                self._state = _Flush.ABORTED
                return True
            return False

    def unclaim(self) -> bool:
        """Return a RUNNING flush to QUEUED — the process-death path
        ONLY: the claiming runner's worker died before any result was
        produced or delivered, so a front-requeue plus a fresh claim on
        the supervisor's replacement re-runs it safely (already-resolved
        riders are skipped by the delivery paths).  True when the claim
        was actually returned."""
        with self._lock:
            if self._state == _Flush.RUNNING:
                self._state = _Flush.QUEUED
                return True
            return False


class _HedgeMonitor:
    """A single timer thread watching dispatched-but-unflushed flushes:
    when one is still queued after its hedge delay, re-enqueue it on a
    second replica (``ReplicaPool.hedge_dispatch``).  First popper wins
    the claim; the loser skips without device work and is charged
    breaker-NEUTRAL.  One heap, one thread, regardless of QPS."""

    def __init__(self, service: "PipelineService"):
        self._svc = service
        self._heap: list = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._stopping = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"{service.name}-hedge"
        )
        self._thread.start()

    def schedule(self, flush: _Flush, delay_s: float) -> None:
        with self._cond:
            heapq.heappush(
                self._heap,
                (time.monotonic() + max(0.0, delay_s), next(self._seq), flush),
            )
            self._cond.notify()

    def stop(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stopping:
                    if not self._heap:
                        self._cond.wait()
                    else:
                        wait = self._heap[0][0] - time.monotonic()
                        if wait <= 0.0:
                            break
                        self._cond.wait(wait)
                if self._stopping:
                    return
                _, _, flush = heapq.heappop(self._heap)
            try:
                self._svc._hedge_fire(flush)
            except Exception:  # a failed hedge must never kill the timer
                logger.exception("hedge dispatch failed")


class PipelineService:
    """A frozen fitted pipeline behind a micro-batching request queue.

    Construct via :func:`serve`.  ``submit``/``submit_many`` return
    ``concurrent.futures.Future`` objects resolved by the background
    batcher thread; ``close`` drains in-flight work.  Thread-safe: any
    number of client threads may submit concurrently (the HTTP front
    end's handler threads do)."""

    def __init__(
        self,
        pipeline,
        max_batch: int = 32,
        max_wait_ms: Optional[float] = None,
        queue_bound: int = 128,
        buckets: Optional[Sequence[int]] = None,
        deadline_ms: Optional[float] = None,
        example=None,
        degrade: bool = True,
        name: str = "serve",
        replicas: int = 1,
        devices: Optional[Sequence] = None,
        version: str = "v0",
        recorder=True,
        slo_ms: Optional[float] = None,
        slo_target: float = 0.99,
        slo_window_s: Optional[float] = None,
        supervise: bool = True,
        heartbeat_s: float = 30.0,
        supervise_interval_s: float = 0.5,
        restart_limit: int = 3,
        restart_window_s: float = 60.0,
        hedge_ms=_UNSET,
        bisect: bool = True,
        artifacts: Optional[dict] = None,
        workers: int = 0,
        worker_opts: Optional[dict] = None,
        autoscale: Optional[dict] = None,
        hosts=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        workers = int(workers or 0)
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        if workers > 0 and replicas != 1:
            raise ValueError(
                "workers= (process fleet) and replicas= (thread fleet) "
                "are exclusive; pass exactly one"
            )
        if workers > 0 and devices is not None:
            raise ValueError(
                "workers= owns device placement in the worker processes; "
                "devices= applies to the thread fleet only"
            )
        if hosts is not None and workers < 1:
            raise ValueError(
                "hosts= selects the cross-host TCP fleet and needs "
                "workers>=1 to size it; local workers=N without hosts "
                "stays on the shared-memory transport"
            )
        # the persistent-compile-cache tier of the prime fallback ladder
        # (artifact → cache → compile): auto-enabled for library callers
        # too, not just the CLI entry points.  Env-gated
        # (KEYSTONE_COMPILE_CACHE=0 disables) and never clobbers an
        # already-configured cache dir.
        from keystone_tpu.utils.compile_cache import (
            ensure_compilation_cache,
            seed_compile_cache,
        )

        ensure_compilation_cache()
        if artifacts:
            # the bundle may ship persistent-compile-cache entries
            # (export's pre-seeded rung): install them BEFORE any
            # replica primes, so the first deploy on a fresh host skips
            # the backend compile of the deserialized modules too
            seed_compile_cache(artifacts)
        # cost-based PhysicalPlan (keystone_tpu.planner): the artifact
        # manifest or the frozen applier may ship one.  Installed BEFORE
        # any serving knob resolves, so buckets / max_wait / dispatch
        # window / hedge read the planned values through the one
        # precedence ladder (explicit arg > env > plan > static default)
        self._plan = None
        try:
            from keystone_tpu import planner as _planner

            plan_dict = ((artifacts or {}).get("manifest") or {}).get("plan")
            if plan_dict is not None:
                self._plan = _planner.PhysicalPlan.from_dict(plan_dict)
            else:
                self._plan = getattr(pipeline, "plan", None)
            if self._plan is not None:
                _planner.install_plan(self._plan, source="serve")
        except Exception:
            self._plan = None
        # the bucket/shape contract is resolved BEFORE the pool builds:
        # process workers prime their padding buckets at spawn, so the
        # worker_opts must carry the final bucket set and item shape
        self.max_batch = int(max_batch)
        planned_buckets = None if buckets else _planned_knob("buckets")
        self.buckets = (
            tuple(sorted({int(b) for b in buckets}))
            if buckets
            else (
                tuple(sorted({int(b) for b in planned_buckets}))
                if planned_buckets
                else default_buckets(self.max_batch)
            )
        )
        if self.buckets[-1] < self.max_batch:
            # a flush larger than every bucket would have nowhere to pad
            self.buckets = self.buckets + (self.max_batch,)
        #: admission-time shape/dtype contract, learned from ``example``
        #: (or the first request): a mismatched request fails ITS submit,
        #: never the whole batch it would have ridden in
        self._item_shape: Optional[tuple] = None
        self._dtype = None
        if example is not None:
            ex = np.asarray(example)
            self._item_shape = tuple(ex.shape)
            self._dtype = ex.dtype
        #: process fleet (workers > 0): replicas are worker PROCESSES
        #: behind the same router — multi-core compute stops measuring
        #: the GIL.  workers == 0 is the PR-14 threaded path, untouched.
        self.workers = workers
        if workers > 0:
            # hosts= promotes the fleet onto the TCP transport
            # (serve/net.py): workers register over a socket and beat a
            # heartbeat lease instead of sharing memory.  Without hosts
            # the shared-memory process path is byte-for-byte untouched.
            pool_backend = "net" if hosts is not None else "process"
            replicas = workers
            pool_worker_opts = dict(worker_opts or {})
            if hosts is not None:
                pool_worker_opts.setdefault("hosts", hosts)
            pool_worker_opts.setdefault("buckets", list(self.buckets))
            pool_worker_opts.setdefault("item_shape", self._item_shape)
            pool_worker_opts.setdefault(
                "dtype",
                None if self._dtype is None else np.dtype(self._dtype).str,
            )
        else:
            pool_backend = "thread"
            pool_worker_opts = None
        #: fleet telemetry (workers > 0): the one sink every worker
        #: handle ships spans/metric-deltas into.  Built BEFORE the pool
        #: (handles attach at construction); its recorder reference is
        #: wired after the recorder itself exists below.  Thread fleets
        #: have no wire to account for — no sink.
        self._telemetry = None
        self._trace_ctx_cap = 0
        if workers > 0:
            from keystone_tpu.serve.telemetry import (
                MAX_TRACE_REQUEST_IDS,
                FleetTelemetry,
            )

            self._telemetry = FleetTelemetry()
            self._trace_ctx_cap = MAX_TRACE_REQUEST_IDS
        self._pool = ReplicaPool(
            pipeline,
            replicas=replicas,
            devices=devices,
            version=version,
            name=name,
            heartbeat_s=heartbeat_s,
            artifacts=artifacts,
            backend=pool_backend,
            worker_opts=pool_worker_opts,
            telemetry=self._telemetry,
        )
        # planned dispatch window: the pool's starting point (the
        # autoscaler / PlanTuner may retune it live from here)
        planned_window = _planned_knob("dispatch_window")
        if planned_window is not None and int(planned_window) != self._pool.window:
            self._pool.set_window(int(planned_window))
        #: the flight recorder: True (default) = a fresh bounded
        #: recorder, False/None = tracing fully off (request ids stay
        #: None, no trace hook runs — the PR-5 path, pinned), or a
        #: caller-provided FlightRecorder instance
        if recorder is True:
            self.recorder: Optional[FlightRecorder] = FlightRecorder()
        elif recorder:
            self.recorder = recorder
        else:
            self.recorder = None
        if self._telemetry is not None:
            # shipped worker spans stitch into /requestz via the
            # recorder; with the recorder off the sink still aggregates
            # fleet METRICS (trace contexts are never sent at all)
            self._telemetry.recorder = self.recorder
        #: thread-local trace context: set by _run_batch around a
        #: dispatch (recorder on + remote fleet only), read by
        #: _apply_rows' remote branch — threaded out-of-band because
        #: _apply_reqs is an override point (serve/tenants.py)
        self._trace_tls = threading.local()
        #: rolling-window latency/batch instruments backing /statusz
        #: percentiles; every observe also feeds the cumulative
        #: registry series of the same name (/metrics)
        #: ``slo_window_s`` resizes the SLO observation window (burn
        #: rate, /statusz percentiles, the rollout judge) — short
        #: windows make a canary/bake verdict reflect NOW, long ones
        #: smooth bursts.  Only the request-outcome windows resize:
        #: ``serve.batch_seconds`` keeps the default window because
        #: occupancy() divides by window_seconds × replicas and the
        #: autoscaler's thresholds are tuned against that default.
        slo_window = (
            max(1.0, float(slo_window_s)) if slo_window_s else 60.0
        )
        self._lat_win = metrics.WindowedHistogram(
            "serve.latency_seconds", window_seconds=slo_window
        )
        self._batch_win = metrics.WindowedHistogram("serve.batch_seconds")
        #: time failed requests (shed/rejected/errored) spent waiting
        #: before their terminal — and, for the SLO burn rate, the
        #: windowed COUNT of failures: a shed flood must drain the
        #: error budget, not hide from a completed-only latency window
        self._fail_win = metrics.WindowedHistogram(
            "serve.failed_wait_seconds", window_seconds=slo_window
        )
        #: SLO latency objective (seconds): explicit slo_ms, else the
        #: service deadline, else no SLO section in /statusz
        self._slo_s = (
            float(slo_ms) / 1000.0
            if slo_ms
            else (float(deadline_ms) / 1000.0 if deadline_ms else None)
        )
        self._slo_target = min(1.0, max(0.0, float(slo_target)))
        self._batch_seq = itertools.count(1)
        self._trace_dump_seq = itertools.count(1)
        #: span-parenting context captured where the service was built:
        #: restored in the batcher and every replica worker, so ledger
        #: spans emitted there nest under the constructor's open span
        self._obs_ctx = ledger.capture_context()
        # flush wait: explicit arg > plan > the historical 5 ms default
        if max_wait_ms is None:
            max_wait_ms = _planned_knob("max_wait_ms")
        if max_wait_ms is None:
            max_wait_ms = 5.0
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.queue_bound = int(queue_bound)
        self.default_deadline_s = (
            None if not deadline_ms else float(deadline_ms) / 1000.0
        )
        self._degrade = bool(degrade)
        self.name = name
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closing = False
        self._closed = False
        self._ewma_batch_s = 0.0
        #: EWMA writes now race across replica workers; keep them atomic
        self._ewma_lock = threading.Lock()
        #: serializes concurrent swap() calls (watcher + admin endpoint)
        self._swap_lock = threading.Lock()
        self._swap_seq = 0
        #: batch-failure bisection (poison-request isolation) on the
        #: flush error path; the quarantine cache short-circuits repeat
        #: offenders at admission (content-keyed, LRU-bounded)
        self._bisect = bool(bisect)
        self._poison_cache: "OrderedDict[bytes, float]" = OrderedDict()
        self._poison_lock = threading.Lock()
        #: guarded-rollout hooks (serve/rollout.py).  ``_rollout``: the
        #: live CanaryController while a canary window is open — the
        #: batcher offers it every formed flush (take) and the request
        #: terminals report outcomes to it (observe); None outside a
        #: window, making every hook a single attribute read on the
        #: pinned path.  ``_rollout_guard``: the post-commit bake watch.
        #: ``_version_history``: prior version ids, newest last — what
        #: POST /rollback walks.  ``_rollout_history``: recent episode
        #: verdicts for /rolloutz.
        self._rollout = None
        self._rollout_guard = None
        self._rollout_state: Optional[dict] = None
        self._rollout_history: deque = deque(maxlen=16)
        self._version_history: list = []
        if example is not None:
            self.prime()
        self._pool.start(
            self._run_flush,
            obs_context=self._obs_ctx,
            on_stranded=self._handle_stranded_flush,
        )
        self._worker = threading.Thread(
            target=self._loop, daemon=True, name=f"{name}-batcher"
        )
        self._worker.start()
        #: hedged dispatch: re-enqueue a still-unflushed batch on a
        #: second replica after max(hedge_ms, 3×EWMA).  None (default)
        #: = off — no monitor thread, the PR-9 dispatch path unchanged.
        #: Needs a second replica to hedge onto.
        #: hedge_ms=0 is a MEANINGFUL floor (delay = pure 3×EWMA);
        #: only None disables hedging.  _UNSET (nothing passed) lets an
        #: installed plan's hedge_ms apply; an EXPLICIT None keeps
        #: hedging off regardless of the plan
        if hedge_ms is _UNSET:
            hedge_ms = _planned_knob("hedge_ms")
        self._hedge_floor_s = (
            None if hedge_ms is None else max(0.0, float(hedge_ms)) / 1000.0
        )
        self._hedge = (
            _HedgeMonitor(self)
            if self._hedge_floor_s is not None and self._pool.size > 1
            else None
        )
        #: the self-healing supervisor: detects dead/wedged replica
        #: workers, restarts them in place, quarantines repeat offenders
        self.supervisor = (
            ReplicaSupervisor(
                self,
                interval=supervise_interval_s,
                restart_limit=restart_limit,
                restart_window=restart_window_s,
            ).start()
            if supervise
            else None
        )
        #: SLO-driven autoscaling (default OFF): ``autoscale=`` is a
        #: config dict for :class:`~keystone_tpu.serve.autoscale.
        #: Autoscaler` (min_workers/max_workers/interval_s/...), whose
        #: control thread adds workers under queue/SLO pressure,
        #: retires idle ones, and retunes the dispatch window live
        self.autoscaler = None
        if autoscale:
            from keystone_tpu.serve.autoscale import Autoscaler

            try:
                self.autoscaler = Autoscaler(self, **dict(autoscale)).start()
            except BaseException:
                # a bad autoscale config must not leak the already-built
                # fleet (live worker PROCESSES for the process backend,
                # plus the batcher/supervisor threads) with no handle
                self.close(drain=False, timeout=10.0)
                raise
        metrics.set_gauge("serve.workers", float(self._pool.size))

    # ------------------------------------------------------------ priming
    def prime(self, replicas=None, have_artifacts: Optional[bool] = None) -> None:
        """Make the apply program at every bucket shape on every replica
        ready NOW, so no request ever pays a trace+compile against its
        deadline.  Requires the item shape (an ``example`` at
        construction, or a first request already served).
        ``replicas``: prime just these (the swap path primes a staged
        generation; default: the pool's live replicas).

        Each bucket rides the prime fallback ladder and is metered as
        ``serve.prime_seconds{source=artifact|cache|compile}``:
        **artifact** — an installed AOT bucket program (pre-lowered at
        publish; the first call only runs the backend compile of its
        serialized module); **cache** — a fresh trace whose executable
        the persistent XLA compilation cache may serve; **compile** —
        a fully cold trace+compile.  When a bundle was configured but a
        bucket has no installed program, that bucket counts as a
        ``serve.artifact_misses``.  ``have_artifacts``: whether the
        GENERATION being primed was given a bundle — the swap path
        passes the staged bundle's presence, because the pool's own
        flag still describes the LIVE generation mid-swap and would
        mislabel the staged primes; default None reads the pool (the
        construction and heal paths, where they agree)."""
        if self._item_shape is None:
            raise ValueError(
                "prime() needs the request item shape; construct the "
                "service with example=<one datum> (or serve a request first)"
            )
        from keystone_tpu.utils.compile_cache import cache_active

        have_bundle = (
            self._pool.has_artifacts
            if have_artifacts is None
            else bool(have_artifacts)
        )
        cache_tier = cache_active()
        t_all = time.monotonic()
        sources: dict = {}
        n_replicas = 0
        for replica in self._pool.replicas if replicas is None else replicas:
            n_replicas += 1
            for bucket in self.buckets:
                zeros = np.zeros((bucket,) + self._item_shape, self._dtype)
                t0 = time.monotonic()
                box: list = []
                self._apply_rows(
                    zeros,
                    deadline=None,
                    replica=replica,
                    prime=True,
                    source_box=box,
                )
                dt = time.monotonic() - t0
                if box and box[0] == "artifact":
                    source = "artifact"
                else:
                    if have_bundle:
                        metrics.inc("serve.artifact_misses")
                    source = "cache" if cache_tier else "compile"
                metrics.observe("serve.prime_seconds", dt, source=source)
                sources[source] = sources.get(source, 0) + 1
                if source == "artifact" and getattr(
                    replica.applier, "_degradable", False
                ):
                    # degradation-declaring pipelines route deadline-
                    # carrying live flushes to the executor WALK — warm
                    # it too, or the first such request pays the
                    # trace+compile in-band that priming exists to
                    # prevent (a far-future deadline selects the walk
                    # without ever firing a watchdog).  Timed and
                    # labeled as its OWN cache/compile-tier prime:
                    # charged to the artifact label, the per-source
                    # ladder timings would show the artifact tier as
                    # slow as the compile tier on degradable pipelines.
                    t1 = time.monotonic()
                    self._apply_rows(
                        zeros,
                        deadline=guard.Deadline.after(86400.0),
                        replica=replica,
                        prime=True,
                    )
                    walk_src = "cache" if cache_tier else "compile"
                    metrics.observe(
                        "serve.prime_seconds",
                        time.monotonic() - t1,
                        source=walk_src,
                    )
                    sources[walk_src] = sources.get(walk_src, 0) + 1
        took = time.monotonic() - t_all
        dominant = max(sources, key=sources.get) if sources else "compile"
        ledger.event(
            "serve.prime",
            seconds=round(took, 6),
            replicas=n_replicas,
            source=dominant,
            n=sum(sources.values()),
        )
        rec = self.recorder
        if rec is not None:
            # a prime is a control-plane moment (cold start, swap
            # staging, supervisor heal): visible in /tracez between the
            # request traces it delayed
            rec.ops(
                "serve.prime",
                seconds=round(took, 6),
                replicas=n_replicas,
                source=dominant,
                n=sum(sources.values()),
            )

    def prime_replacement(self, replica) -> None:
        """Prime one not-yet-routed replica's bucket programs — the
        supervisor's restart path (``prime()`` for a single replica,
        tolerating a service that has not yet learned its item shape)."""
        if self._item_shape is not None:
            self.prime(replicas=[replica])

    def fail_flush(self, flush, exc: BaseException) -> None:
        """Fail every still-unresolved rider of a flush (the supervisor's
        abandonment path, and the batcher's fleet-unavailable path)."""
        for req in flush.riders:
            self._fail(req, exc, batch=flush.bid)

    def _handle_stranded_flush(
        self, flush, why: str = "replica died"
    ) -> None:
        """THE stranded-work re-dispatch policy — one copy, shared by
        the crash-handler race path, scale-down leftovers, and the
        supervisor's heal/quarantine redistribution: a copy that is no
        longer QUEUED is skipped (its claimed winner owns delivery);
        otherwise re-dispatch onto a survivor, window ignored — extra
        queueing on a living replica beats failing admitted work; only
        with NO routable survivor do the riders fail typed, aborted
        FIRST so a pending hedge timer can never resurrect a flush
        whose riders were already answered."""
        if not getattr(flush, "unflushed", lambda: False)():
            return  # claimed/done/aborted elsewhere: not ours to place
        target = self._pool.hedge_dispatch(
            flush, exclude_index=None, respect_window=False
        )
        if target is None:
            getattr(flush, "abort", lambda: False)()
            self.fail_flush(
                flush,
                FleetUnavailable(
                    f"{why} and no routable survivor could absorb "
                    "its queue"
                ),
            )

    # ------------------------------------------------------------ hedging
    def _hedge_delay_s(self) -> float:
        """The re-dispatch delay: the configured floor, lifted to a
        ~p95-ish EWMA multiple once real batch samples exist."""
        return max(self._hedge_floor_s or 0.0, _HEDGE_EWMA_MULT * self._ewma_batch_s)

    def _hedge_fire(self, flush: _Flush) -> None:
        """Timer callback: the flush is still sitting in its primary
        replica's queue past the hedge delay — enqueue it on a second
        replica.  Whichever replica pops it first claims it; the other
        skips without device work."""
        if not flush.unflushed() or flush.hedged:
            return
        flush.hedged = True  # at most one hedge per flush
        rep = self._pool.hedge_dispatch(flush, exclude_index=flush.primary)
        if rep is None:
            return  # no second replica free: the hedge is skipped
        metrics.inc("serve.hedges")
        rec = self.recorder
        if rec is not None:
            rec.ops(
                "serve.hedge",
                batch=flush.bid,
                from_replica=flush.primary,
                to_replica=rep.index,
            )

    # ------------------------------------------------------------- submit
    def submit(
        self,
        x,
        deadline=None,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Future:
        """Enqueue one datum; returns a Future resolving to its result
        row (numpy).  ``deadline``: seconds or a ``guard.Deadline``
        (default: the service's ``deadline_ms``).  ``request_id``: the
        trace identity (default: generated when the flight recorder is
        on — resolve the outcome later via ``/requestz/<id>``).
        ``tenant``: multi-tenant routing label — refused (TypeError) on
        a single-tenant service; see ``serve/tenants.py``.  Raises
        :class:`Overloaded` when the queue is at bound and
        :class:`ServiceClosed` after shutdown began."""
        return self._submit_all(
            [x],
            deadline,
            None if request_id is None else [request_id],
            tenant=tenant,
        )[0]

    def submit_many(self, xs, deadline=None, request_ids=None, tenant=None) -> list:
        """Enqueue a sequence of datums; returns their Futures in order.
        One shared deadline resolution (all requests of the call carry
        the same absolute expiry) and ATOMIC admission: either every
        datum is enqueued or none is — a partial enqueue would leave
        orphaned requests executing for a caller that saw the error.
        ``request_ids``: per-datum trace identities (default: generated
        when the flight recorder is on).  ``tenant``: multi-tenant
        routing label (single-tenant services refuse it)."""
        return self._submit_all(list(xs), deadline, request_ids, tenant=tenant)

    # ------------------------------------------------------ tenant hooks
    # The multi-tenant service (serve/tenants.py) overrides these; on
    # the base single-tenant service every one is inert (or refuses),
    # so the PR-5..13 admission path is unchanged.
    def _resolve_tenant(self, tenant: Optional[str]) -> Optional[str]:
        if tenant is not None:
            raise TypeError(
                f"service {self.name!r} is single-tenant; tenant="
                f"{tenant!r} refused (serve_multi builds tenant routing)"
            )
        return None

    def _default_deadline_for(self, tenant: Optional[str]):
        return self.default_deadline_s

    def _check_bound_locked(self, n_new: int, tenant: Optional[str]) -> None:
        """Admission bound check; must hold ``self._cond``."""
        if len(self._q) + n_new > self.queue_bound:
            metrics.inc("serve.rejected", n_new)
            raise Overloaded(
                f"service {self.name!r} queue at bound "
                f"({self.queue_bound}); retry later"
            )

    def _push_locked(self, reqs: list, tenant: Optional[str]) -> int:
        """Enqueue admitted requests; must hold ``self._cond``.
        Returns the post-push queue depth (the enqueue annotation).
        The gauge is set under the lock: written outside it, a stale
        pre-flush depth could overwrite the batcher's newer value."""
        self._q.extend(reqs)
        depth = len(self._q)
        metrics.set_gauge("serve.queue_depth", depth)
        return depth

    def _account_admission(
        self, tenant: Optional[str], outcome: str, n: int
    ) -> None:
        """Per-tenant admission-terminal accounting hook (inert here)."""

    def _account_tenant(self, req, outcome: str, seconds: float) -> None:
        """Per-tenant request-terminal accounting hook (inert here)."""

    def _fail_queued_locked(self, make_exc) -> None:
        """Fail every queued request; must hold ``self._cond``.  The
        multi-tenant service overrides this to drain its per-tenant
        queues."""
        while self._q:
            self._fail(self._q.popleft(), make_exc())
        metrics.set_gauge("serve.queue_depth", 0)

    def _queue_depth_locked(self) -> int:
        return len(self._q)

    # ------------------------------------------------------- dedup hooks
    # In-flight request dedup (serve/tenants.py enables it): identical
    # concurrent payloads are computed once and fanned out.  Every hook
    # is inert on the base service — zero cost on the single-tenant
    # path.
    def _dedup_keys(self, arrs) -> Optional[list]:
        """Content keys for this submit (None = dedup off)."""
        return None

    def _dedup_match(self, tenant, keys) -> dict:
        """``{datum index: leader _Request}`` for already-in-flight
        identical payloads; must hold ``self._cond``."""
        return {}

    def _dedup_register(self, tenant, keys, reqs, followers) -> None:
        """Register the call's leaders in the in-flight map; must hold
        ``self._cond``."""

    def _dedup_attach(self, followers: dict, reqs: list) -> None:
        """Wire follower futures to their leaders (outside the lock)."""

    def _resolve_request_ids(self, n: int, request_ids) -> List[Optional[str]]:
        if request_ids is not None:
            rids = [None if r is None else str(r) for r in request_ids]
            if len(rids) != n:
                raise ValueError(
                    f"got {len(rids)} request_ids for {n} datums"
                )
            return rids
        if self.recorder is not None:
            return [new_request_id() for _ in range(n)]
        return [None] * n

    def submit_batch(
        self,
        block,
        deadline=None,
        request_ids=None,
        tenant: Optional[str] = None,
    ) -> list:
        """Admit a whole admission block (``serve/wire.py``
        ``SlabBlock`` — or any duck-typed ``admission_block`` carrier
        exposing ``count`` / ``rows()``) under ONE queue-lock round;
        returns one Future per row, in order.  Each request's payload is
        a zero-copy VIEW of the block, so when the block forms a flush
        by itself the router skips the stack+pad copies and a process
        worker attaches the same shared-memory slab by name.

        The caller keeps ownership of the block's lifetime: hold it
        (e.g. ``block.retain(n)`` + ``release_one`` done-callbacks)
        until every returned future resolves — the router may read the
        slab up to that point (hedges, crash requeues, bisection).
        Raises exactly what :meth:`submit_many` raises; on ANY raise no
        row was admitted (atomic, same as every submit path)."""
        if not getattr(block, "admission_block", False):
            raise TypeError(
                f"submit_batch wants an admission block (wire.SlabBlock); "
                f"got {type(block).__name__} — use submit_many for plain "
                "sequences"
            )
        return self._submit_all(
            list(block.rows()),
            deadline,
            request_ids,
            tenant=tenant,
            block=block,
        )

    def bucket_for(self, k: int) -> int:
        """The padding bucket a ``k``-row flush pads to (public so the
        ingress can pre-pad admission blocks to the exact flush shape)."""
        return self._bucket_for(int(k))

    def _submit_all(
        self, xs, deadline, request_ids=None, tenant=None, block=None
    ) -> list:
        if not xs:
            return []
        rids = self._resolve_request_ids(len(xs), request_ids)
        rec = self.recorder
        try:
            if self._closing:
                raise ServiceClosed(f"service {self.name!r} is closed")
            tenant = self._resolve_tenant(tenant)
            dl = guard.as_deadline(
                deadline
                if deadline is not None
                else self._default_deadline_for(tenant)
            )
            # ctx.tenant rides the fault site so chaos plans can target
            # ONE tenant's admission path (blast-radius isolation)
            tctx = {} if tenant is None else {"tenant": tenant}
            for _ in xs:
                fault_point("serve.enqueue", **tctx)
            arrs = [np.asarray(x) for x in xs]
            # content keys for in-flight dedup (None unless the service
            # enables dedup) — hashed OUTSIDE the lock, and SHARED with
            # the poison check below: both key on the same digest, and
            # hashing payloads is the expensive part of this path
            dd_keys = self._dedup_keys(arrs)
            # the poison quarantine cache: content previously isolated
            # by bisection is refused BEFORE it reaches a device (and
            # before it can fail a co-batched flush again).  Zero cost
            # until something has actually been quarantined.
            if self._poison_cache:
                keys = (
                    dd_keys
                    if dd_keys is not None
                    else [_content_key(a) for a in arrs]
                )
                now = time.monotonic()
                with self._poison_lock:
                    hit = False
                    for k in keys:
                        t = self._poison_cache.get(k)
                        if t is None:
                            continue
                        if now - t > _POISON_TTL_S:
                            del self._poison_cache[k]  # expired: amnesty
                        else:
                            hit = True
                            break
                if hit:
                    metrics.inc("serve.poison_blocked", len(arrs))
                    raise PoisonRequest(
                        "request content matches a previously-isolated "
                        "poison payload; refused at admission"
                    )
            # fleet-unavailable fail-fast: every replica quarantined/
            # dead/breaker-open answers 503 at once instead of queueing
            # work the router will refuse.  One attribute read while the
            # fleet is healthy.
            if not self._pool.available():
                metrics.inc("serve.unavailable", len(arrs))
                raise FleetUnavailable(
                    f"service {self.name!r}: no replica can serve",
                    retry_after_seconds=self._pool.retry_after_unavailable(),
                )
            followers: dict = {}
            with self._cond:
                if self._closing:
                    raise ServiceClosed(f"service {self.name!r} is closed")
                # the shape/dtype contract is learned and checked UNDER the
                # lock: concurrent first requests must agree on one item
                # shape, and a mismatched request must fail ITS OWN submit
                # (before anything is enqueued), never the batch it would
                # have ridden in.  Staged, committed only after admission:
                # a rejected (or internally-inconsistent) call must not fix
                # the contract for requests that were never served
                item_shape, dtype = self._item_shape, self._dtype
                for arr in arrs:
                    if item_shape is None:
                        item_shape, dtype = tuple(arr.shape), arr.dtype
                    elif tuple(arr.shape) != item_shape:
                        raise TypeError(
                            f"request shape {tuple(arr.shape)} != service item "
                            f"shape {item_shape}"
                        )
                if dd_keys is not None:
                    followers = self._dedup_match(tenant, dd_keys)
                # followers ride their leader's computation: they occupy
                # no queue slot, which is exactly the capacity win
                self._check_bound_locked(len(arrs) - len(followers), tenant)
                self._item_shape, self._dtype = item_shape, dtype
                reqs = []
                for i, (a, rid) in enumerate(zip(arrs, rids)):
                    xa = a if a.dtype == dtype else a.astype(dtype)
                    r = _Request(xa, dl, rid, tenant=tenant)
                    # slab-direct admission: tag the request with its
                    # block row ONLY when no conversion copied the view
                    # (a dtype-mismatched block silently rides the copy
                    # path — correct, just not zero-copy)
                    if block is not None and xa is a:
                        r.block, r.row = block, i
                    reqs.append(r)
                if dd_keys is not None:
                    self._dedup_register(tenant, dd_keys, reqs, followers)
                # push, then annotate — both UNDER the queue lock: the
                # batcher pops under this same lock, so once we
                # release, the flush path's finish() cannot run ahead
                # of the enqueue event (annotated after the lock, a
                # preempted submitter could lose the event — or
                # resurrect an evicted id as a phantom trace)
                push_reqs = (
                    reqs
                    if not followers
                    else [r for i, r in enumerate(reqs) if i not in followers]
                )
                depth = self._push_locked(push_reqs, tenant)
                if rec is not None:
                    # followers are never enqueued: their trace gets the
                    # serve.dedup annotation instead (a phantom enqueue
                    # event would misreport queue behavior for exactly
                    # the requests dedup diverts)
                    enqueued_rids = (
                        rids
                        if not followers
                        else [r.request_id for r in push_reqs]
                    )
                    for rid in enqueued_rids:
                        rec.annotate(
                            rid, "serve.enqueue", queue_depth=depth, **tctx
                        )
                self._cond.notify_all()
            if followers:
                self._dedup_attach(followers, reqs)
        except BaseException as e:
            # terminal outcome at admission: the trace (if any) must not
            # dangle open — a rejected request is as explainable as a
            # shed one.  Finished OUTSIDE the queue lock.
            if isinstance(e, PoisonRequest):
                outcome = "poison"
            elif isinstance(
                e,
                (
                    Overloaded,
                    ServiceClosed,
                    FleetUnavailable,
                    # a tenant breaker's refusal is backpressure (the
                    # HTTP layer answers 429 + Retry-After), not an
                    # error: charged to rejected counters/traces
                    guard.CircuitOpenError,
                ),
            ):
                outcome = "rejected"
            else:
                outcome = "error"
            # rejected/errored admissions burn the SLO error budget too
            # (waited ~0: admission answers immediately) — EXCEPT client
            # faults (shape mismatch, malformed payloads: the 400
            # family): a misbehaving client must not be able to page an
            # operator by draining the server's error budget
            if not isinstance(e, (TypeError, ValueError)):
                for _ in xs:
                    self._fail_win.observe(0.0)
            self._account_admission(tenant, outcome, len(xs))
            err = f"{type(e).__name__}: {e}"
            for rid in rids:
                if rid is not None:
                    if rec is not None:
                        rec.finish(rid, outcome, error=err)
                    ledger.event(
                        "serve.request",
                        request_id=rid,
                        outcome=outcome,
                        error=err,
                    )
            raise
        metrics.inc("serve.submitted", len(reqs))
        self._account_admission(tenant, "submitted", len(reqs))
        return [r.future for r in reqs]

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def version(self) -> str:
        """The model version the live replica generation serves."""
        return self._pool.version

    @property
    def replicas(self) -> int:
        return self._pool.size

    def replica_statuses(self) -> list:
        """Per-replica status dicts (index, device, model version,
        breaker state, outstanding flushes, dead/quarantined/restart
        supervision state) — the fleet view ``/healthz`` and
        ``/replicas`` expose so a load balancer can see a half-sick
        fleet, not just process liveness."""
        return self._pool.statuses()

    @property
    def available(self) -> bool:
        """False when NO replica can serve (all quarantined, dead, or
        breaker-open): submits raise :class:`FleetUnavailable`,
        ``/predict`` answers 503, and ``/healthz`` turns non-200 until
        a supervisor restart or half-open probe re-admits traffic.
        Runs the FULL scan (this backs low-rate health surfaces);
        the per-submit admission check stays one attribute read."""
        return not self._closed and self._pool.available_now()

    def unavailable_retry_after(self) -> float:
        """The ``Retry-After`` an unavailable 503 should carry: the
        soonest breaker half-open probe among routable replicas."""
        return self._pool.retry_after_unavailable()

    def retry_after_hint(self) -> float:
        """Estimated seconds until the queue drains — what a 429 should
        send as ``Retry-After`` instead of a constant.  Derived from the
        shedding path's EWMA flush-completion estimate: a full queue is
        ``ceil(depth / max_batch)`` flushes, spread across the fleet's
        replicas.  Falls back to 1 s before the first sample."""
        ewma = self._ewma_batch_s
        if ewma <= 0.0:
            return 1.0
        with self._cond:
            depth = self._queue_depth_locked()
        flushes = -(-max(1, depth) // self.max_batch)  # ceil division
        return ewma * flushes / max(1, self._pool.size)

    # ------------------------------------------------------------- scaling
    def occupancy(self) -> float:
        """Windowed fleet busy fraction: total batch-apply seconds over
        the last window divided by (window × replicas).  ~1.0 means
        every replica computed wall-to-wall; the autoscaler's primary
        utilization signal, and a ``/statusz`` field."""
        s = self._batch_win.summary()
        denom = s["window_seconds"] * max(1, self._pool.size)
        occ = min(1.0, (s["sum"] or 0.0) / denom) if denom > 0 else 0.0
        metrics.set_gauge("serve.occupancy", occ)
        return occ

    def slo_burn(self) -> Optional[dict]:
        """The windowed SLO burn detail (None when no objective is
        configured): ``burn_rate`` plus the ``window_requests`` /
        ``window_failed`` sample counts behind it, so a consumer — the
        rollout judge (serve/rollout.py), the bake guard, ``/statusz``
        — can refuse to read a near-empty window as a verdict instead
        of treating noise as signal.  ``bad`` counts completed-but-
        over-objective requests PLUS every failed terminal in the
        window (a shed flood is the worst latency violation there is
        and must drain the budget); ``burn_rate`` is None when the
        target leaves zero error budget."""
        if self._slo_s is None:
            return None
        lat = self._lat_win.summary()
        n_ok = lat["count"]
        n_fail = self._fail_win.summary()["count"]
        n = n_ok + n_fail
        bad = (
            0.0
            if n == 0
            else (self._lat_win.fraction_above(self._slo_s) * n_ok + n_fail)
            / n
        )
        budget = 1.0 - self._slo_target
        return {
            "objective_ms": round(1000.0 * self._slo_s, 3),
            "target": self._slo_target,
            "window_seconds": self._lat_win.window_seconds,
            "window_requests": n,
            "window_failed": n_fail,
            "bad_fraction": bad,
            "burn_rate": None if budget <= 0.0 else bad / budget,
        }

    def slo_burn_rate(self) -> Optional[float]:
        """The windowed SLO error-budget burn rate (None when no
        objective is configured, or the target leaves no budget) — the
        same number ``/statusz`` embeds, exposed directly for the
        autoscaler; :meth:`slo_burn` carries the sample counts."""
        detail = self.slo_burn()
        return None if detail is None else detail["burn_rate"]

    @property
    def host_capacity(self) -> Optional[int]:
        """Total worker slots across the cross-host fleet's host map
        (None off the net backend, or when any host is unbounded) — the
        autoscaler clamps its grow target here so a scale-up can never
        ask for workers no host has room to run."""
        return getattr(self._pool, "host_capacity", None)

    @property
    def listen_address(self) -> Optional[str]:
        """``host:port`` remote workers connect to (net backend only) —
        what ``keystone worker --connect`` takes on another box."""
        return getattr(self._pool, "listen_address", None)

    def scale_to(self, n: int, timeout: float = 60.0) -> int:
        """Resize the fleet to ``n`` replicas (grow: spawn → prime →
        admit; shrink: graceful retire-and-drain, leftovers
        re-dispatched).  Serialized under the swap lock so a concurrent
        blue/green swap never races a resize.  Returns the resulting
        size."""
        n = max(1, int(n))
        with self._swap_lock:
            if self._closing:
                raise ServiceClosed(f"service {self.name!r} is closed")
            while self._pool.size < n:
                t0 = time.monotonic()
                fresh = self._pool.add_replica(primer=self.prime_replacement)
                metrics.inc("serve.scale_ups")
                self._scale_event(
                    "up", fresh.index, time.monotonic() - t0
                )
            while self._pool.size > n:
                t0 = time.monotonic()
                left = self._pool.remove_replica(timeout=timeout)
                if left is None:
                    break  # at the floor
                metrics.inc("serve.scale_downs")
                for flush in left:
                    if getattr(flush, "unflushed", lambda: False)():
                        self._handle_stranded_flush(
                            flush, why="replica retired during scale-down"
                        )
                    else:
                        # a CLAIMED flush the victim never delivered (a
                        # wedged worker that outlived the drain
                        # timeout): fail its riders typed — late
                        # delivery into resolved futures is tolerated,
                        # exactly the supervisor's abandonment contract
                        getattr(flush, "abort", lambda: False)()
                        self.fail_flush(
                            flush,
                            FleetUnavailable(
                                "replica retired during scale-down with "
                                "a flush still in hand"
                            ),
                        )
                self._scale_event("down", None, time.monotonic() - t0)
        metrics.set_gauge("serve.workers", float(self._pool.size))
        return self._pool.size

    def _scale_event(self, action: str, replica, seconds: float) -> None:
        ledger.event(
            "serve.scale",
            action=action,
            replica=replica,
            workers=self._pool.size,
            seconds=round(seconds, 6),
        )
        rec = self.recorder
        if rec is not None:
            rec.ops(
                "serve.scale",
                action=action,
                replica=replica,
                workers=self._pool.size,
                seconds=round(seconds, 6),
            )
        logger.info(
            "scaled %s %r to %d replica(s) in %.2fs",
            action,
            self.name,
            self._pool.size,
            seconds,
        )

    def set_dispatch_window(self, n: int) -> int:
        """Retune the router's dispatch window live (autoscaler lever)."""
        return self._pool.set_window(n)

    def retune_buckets(self, buckets) -> Tuple[int, ...]:
        """Retune the padding-bucket ladder live (the PlanTuner lever).

        An atomic tuple swap: in-flight flushes already carry their
        bucket, queued requests pick from the new ladder at flush time,
        and an unprimed new bucket rides the existing prime fallback
        ladder on first use — padding changes, results never do, so no
        future is lost.  Thread fleets only: process workers bake their
        bucket set into spawned programs at startup."""
        if self.workers > 0:
            raise ValueError(
                "retune_buckets applies to thread fleets; process workers "
                "prime their bucket ladder at spawn"
            )
        from keystone_tpu.planner import registry as _plans

        ok, coerced, why = _plans.validate_knob("buckets", buckets)
        if not ok:
            raise ValueError(f"bad bucket retune: {why}")
        if coerced[-1] < self.max_batch:
            coerced = coerced + (self.max_batch,)
        self.buckets = coerced
        return self.buckets

    # ------------------------------------------------------------- statusz
    @classmethod
    def _ingress_ms(cls, reg, name: str) -> Optional[dict]:
        """One cumulative ingress histogram as a ms summary, or None
        when the front end never observed it (HTTP-only traffic has no
        binary parse samples)."""
        summary = reg.histogram_summary(name)
        return None if summary is None else cls._ms(summary)

    @staticmethod
    def _ms(window_summary: dict) -> dict:
        """A windowed summary in milliseconds (rounded for the wire)."""
        out = {"count": window_summary["count"]}
        for key in ("p50", "p95", "p99", "min", "max"):
            v = window_summary.get(key)
            out[key] = None if v is None else round(1000.0 * v, 3)
        return out

    def status(self) -> dict:
        """The live ops view ``GET /statusz`` serves: rolling-window
        latency/batch percentiles (from the windowed histograms — the
        last ``window_seconds``, not process lifetime), per-replica
        occupancy/breaker statuses, whole-process outcome counters, the
        flight-recorder stats, and — when a latency objective is
        configured — the SLO error-budget burn rate: the windowed
        fraction of requests over the objective divided by the allowed
        fraction (``1 - slo_target``); burn > 1 means the error budget
        is draining faster than it accrues."""
        lat = self._lat_win.summary()
        bat = self._batch_win.summary()
        reg = metrics.REGISTRY
        replica_stats = self.replica_statuses()
        rec = self.recorder
        out = {
            "name": self.name,
            "status": "closed" if self._closed else "ok",
            "version": self.version,
            "backend": self._pool.backend,
            "workers": self._pool.size,
            "dispatch_window": self._pool.window,
            "occupancy": round(self.occupancy(), 4),
            "queue_depth": self.queue_depth,
            "queue_bound": self.queue_bound,
            "max_batch": self.max_batch,
            "window_seconds": self._lat_win.window_seconds,
            "latency_ms": self._ms(lat),
            "batch_ms": self._ms(bat),
            "available": self.available,
            "counters": {
                name.split(".", 1)[1]: reg.counter_total(name)
                for name in (
                    "serve.submitted",
                    "serve.completed",
                    "serve.shed",
                    "serve.rejected",
                    "serve.deadline_miss",
                    "serve.batch_errors",
                    "serve.replica_restarts",
                    "serve.bisections",
                    "serve.poison",
                    "serve.poison_blocked",
                    "serve.hedges",
                    "serve.hedge_wins",
                    "serve.unavailable",
                    "serve.artifact_hits",
                    "serve.artifact_misses",
                    "serve.artifact_fallbacks",
                    "serve.worker_crashes",
                    "serve.scale_ups",
                    "serve.scale_downs",
                    "serve.dedup_hits",
                )
            },
            # the AOT tier at a glance: was a bundle configured, how
            # many bucket programs each live replica holds, and the
            # prime ladder's per-source timing totals
            "artifacts": {
                "configured": self._pool.has_artifacts,
                "installed_buckets": sum(
                    r.get("artifact_buckets", 0) for r in replica_stats
                ),
                "prime_seconds": {
                    src: reg.histogram_value(
                        "serve.prime_seconds", source=src
                    )
                    for src in ("artifact", "cache", "compile")
                },
            },
            "replicas": replica_stats,
            "supervisor": (
                None if self.supervisor is None else self.supervisor.status()
            ),
            "autoscaler": (
                None if self.autoscaler is None else self.autoscaler.status()
            ),
            "plan": _plan_status_safe(),
            "recorder": None if rec is None else rec.stats(),
        }
        # front-end ingress health (present once any front end has
        # served a connection — pure registry reads, so a library-only
        # service with no listener shows an all-zero block harmlessly
        # only if something registered the histograms; gate on traffic)
        ingress_conns = reg.counter_total(
            "ingress.bin_conns"
        ) + reg.counter_total("ingress.http_conns")
        if ingress_conns or reg.counter_total("ingress.accepts"):
            out["ingress"] = {
                "accepts": reg.counter_total("ingress.accepts"),
                "bin_conns": reg.counter_total("ingress.bin_conns"),
                "http_conns": reg.counter_total("ingress.http_conns"),
                "frames": reg.counter_total("ingress.frames"),
                "batch_rows": reg.counter_total("ingress.batch_rows"),
                "bytes_copied": reg.counter_total("ingress.bytes_copied"),
                "frame_errors": {
                    labels.get("kind", "?"): value
                    for labels, value in reg.counter_series(
                        "ingress.frame_errors"
                    )
                },
                "parse_ms": self._ingress_ms(reg, "ingress.parse_seconds"),
                "admit_ms": self._ingress_ms(reg, "ingress.admit_seconds"),
            }
        if self._telemetry is not None:
            # the fleet block: per-worker apply/wire percentiles and
            # clock-sync health, built from the spans/metric deltas
            # workers shipped over their existing reply/beat frames
            out["fleet"] = self._telemetry.fleet_status()
        if self._slo_s is not None:
            # slo_burn() carries the window sample counts next to the
            # rate — the same refuse-to-decide-on-noise detail the
            # rollout judge reads
            detail = self.slo_burn()
            bad = detail["bad_fraction"]
            out["slo"] = {
                "objective_ms": detail["objective_ms"],
                "target": detail["target"],
                "window_seconds": detail["window_seconds"],
                "window_requests": detail["window_requests"],
                "window_failed": detail["window_failed"],
                "bad_fraction": round(bad, 6),
                "compliance": round(1.0 - bad, 6),
                "burn_rate": (
                    None
                    if detail["burn_rate"] is None
                    else round(detail["burn_rate"], 3)
                ),
            }
        return out

    def rollout_status(self) -> dict:
        """The ``GET /rolloutz`` block: the live rollout phase (canary
        window or bake watch) when one is active, the recent episode
        verdicts, and the swap history ``POST /rollback`` would walk."""
        active = self._rollout_state
        guard_ = self._rollout_guard
        if guard_ is not None:
            active = guard_.status()
        rollout = self._rollout
        if rollout is not None and isinstance(active, dict):
            active = dict(active)
            active["canary"] = rollout.snapshot()
        return {
            "version": self.version,
            "active": active,
            "history": list(self._rollout_history),
            "prior_versions": list(self._version_history),
            "slo": self.slo_burn(),
        }

    def dump_trace(self, dir_path: str) -> Optional[str]:
        """Write the flight recorder's full state (the ``/tracez?full=1``
        payload) durably into ``dir_path`` and return the file path —
        the artifact ``tools/trace_report.py`` reads offline (its
        recorder-dump mode; the ``.json`` suffix is load()'s mode
        switch).  Returns None when tracing is off.  Published via
        ``utils.durable.atomic_write`` so a crash mid-dump never leaves
        a truncated file for the post-incident read."""
        import json
        import os

        rec = self.recorder
        if rec is None:
            return None
        os.makedirs(dir_path, exist_ok=True)
        seq = next(self._trace_dump_seq)
        path = os.path.join(
            dir_path,
            f"trace-{self.name}-{int(time.time())}-{seq}.json",  # lint: allow-wall-clock
        )
        payload = rec.dump()

        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(payload, f)

        from keystone_tpu.utils import durable

        durable.atomic_write(path, _write)
        return path

    # --------------------------------------------------------------- swap
    def swap(
        self,
        pipeline,
        version: Optional[str] = None,
        prime: bool = True,
        artifacts: Optional[dict] = None,
    ) -> dict:
        """Blue/green model hot-swap: stage a full replica generation
        for ``pipeline``, prime its padding-bucket programs while the
        OLD generation keeps serving, then atomically commit at the
        flush boundary.  Queued requests never drop — flushes already
        routed to an old replica resolve from the version that admitted
        them; everything dispatched after the commit runs on the new
        one.  Returns ``{"version", "pause_seconds", "prime_seconds",
        "replicas"}`` (``pause_seconds`` is the router-lock-held window:
        the only time no flush can be dispatched).

        Concurrent swaps serialize; a failed stage/prime leaves the old
        generation serving untouched (the ``serve.swap`` fault site
        injects exactly that).

        ``artifacts``: the new version's AOT artifact bundle (registry
        ``load_artifacts``): staged replicas install the pre-lowered
        bucket programs so the stage→prime window stops paying
        trace+lower time, and the bundle becomes the pool's for
        supervisor heals after the commit.  A damaged/skewed bundle
        degrades that swap to recompilation — it never fails it."""
        if self._closing:
            raise ServiceClosed(f"service {self.name!r} is closed")
        with self._swap_lock:
            # re-check under the lock: close() sets _closing and then
            # waits on this lock, so a swap that was queued behind
            # another swap (or raced close()'s first check) must not
            # stage a fresh generation into a shutting-down service
            if self._closing:
                raise ServiceClosed(f"service {self.name!r} is closed")
            self._swap_seq += 1
            version = version or f"swap{self._swap_seq}"
            prev_version = self.version
            with ledger.span("serve.swap", version=version):
                fault_point("serve.swap", version=version)
                t0 = time.monotonic()
                if artifacts:
                    # shipped compile-cache entries install before the
                    # staged generation primes (same rung as cold start)
                    from keystone_tpu.utils.compile_cache import (
                        seed_compile_cache,
                    )

                    seed_compile_cache(artifacts)
                staged = self._pool.stage(pipeline, version, artifacts=artifacts)
                try:
                    if prime and self._item_shape is not None:
                        self.prime(
                            replicas=staged,
                            have_artifacts=artifacts is not None,
                        )
                except BaseException:
                    # failed prime = failed swap: retire the staged
                    # workers instead of leaking them; the old
                    # generation never stopped serving
                    for r in staged:
                        r.retire()
                    raise
                prime_s = time.monotonic() - t0
                pause_s = self._pool.commit(staged, version)
                # the incoming version's PhysicalPlan replaces the old
                # one AT the commit (the plan ships with the model):
                # from the bundle manifest, or the pickled applier
                try:
                    from keystone_tpu import planner as _planner

                    plan_dict = (
                        (artifacts or {}).get("manifest") or {}
                    ).get("plan")
                    new_plan = (
                        _planner.PhysicalPlan.from_dict(plan_dict)
                        if plan_dict is not None
                        else getattr(pipeline, "plan", None)
                    )
                    if new_plan is not None:
                        self._plan = new_plan
                        _planner.install_plan(new_plan, source="swap")
                except Exception:
                    logger.warning(
                        "swap %s: shipped plan failed to install", version
                    )
            # swap-history bookkeeping for POST /rollback: the version
            # this commit displaced, newest last (internal — the pinned
            # swap return/ops surface is unchanged)
            self._version_history.append(prev_version)
            metrics.inc("serve.swaps")
            metrics.observe("serve.swap_pause_seconds", pause_s)
            metrics.observe("serve.swap_prime_seconds", prime_s)
            rec = self.recorder
            if rec is not None:
                # the swap is a control-plane span in the recorder, so
                # /tracez shows it BETWEEN the request traces it
                # interleaves with (riders routed to the retiring
                # generation before it, new-generation traffic after)
                rec.ops(
                    "serve.swap",
                    version=version,
                    pause_seconds=round(pause_s, 6),
                    prime_seconds=round(prime_s, 6),
                    replicas=len(staged),
                )
            logger.info(
                "hot-swapped %r to version %s (%d replicas, prime %.2fs, "
                "pause %.2fms)",
                self.name,
                version,
                len(staged),
                prime_s,
                1000.0 * pause_s,
            )
            return {
                "version": version,
                "pause_seconds": pause_s,
                "prime_seconds": prime_s,
                "replicas": len(staged),
            }

    # ----------------------------------------------------------- shutdown
    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting requests and shut the batcher down.  With
        ``drain=True`` (default) every already-queued request is flushed
        and resolved before the worker exits; with ``drain=False``
        queued requests fail with :class:`ServiceClosed`."""
        with self._cond:
            self._closing = True
            if not drain:
                self._fail_queued_locked(
                    lambda: ServiceClosed("service closed before execution")
                )
            self._cond.notify_all()
        # stop the healers first: a supervisor restarting (or a hedge
        # monitor re-enqueueing into) a pool that close() is tearing
        # down would race the retirement below — and the autoscaler
        # before both, so no resize races the drain
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self._hedge is not None:
            self._hedge.stop()
        # the bake guard is a healer too: a revert swap racing the
        # teardown below would stage a generation into a closing pool
        # (its loop also exits on _closing; this bounds the join)
        guard_ = self._rollout_guard
        if guard_ is not None:
            guard_.stop()
        # wait out an in-flight swap: with _closing set no NEW swap can
        # start, and an in-flight one either commits into the still-live
        # pool (its generation is then retired below) or fails on its
        # own.  Without this, a swap mid-prime would commit fresh worker
        # threads into a pool close() already tore down, leaking them.
        # Bounded: a wedged prime must not wedge close() — the pool's
        # _draining flag makes a late commit() refuse the install.
        if self._swap_lock.acquire(timeout=timeout):
            self._swap_lock.release()
        else:
            logger.warning(
                "service %r closing with a swap still in flight after "
                "%.1fs; a late commit will be refused",
                self.name,
                timeout,
            )
        # release a batcher blocked at the pool's dispatch window BEFORE
        # joining it: on a wedged fleet the batcher would otherwise burn
        # this whole join timeout, and its in-hand batch would be
        # dropped on the floor (in neither the service queue nor any
        # replica queue) with its futures never resolved.  Drained, it
        # dispatches the batch into a replica queue where the pool
        # close below hands it back as abandoned.
        self._pool.begin_drain()
        self._worker.join(timeout)
        if self._worker.is_alive():
            logger.warning(
                "service %r batcher did not exit within %.1fs", self.name, timeout
            )
            # the batcher is wedged (e.g. a hung apply with no deadline
            # configured): it will never drain the queue, so fail the
            # still-queued futures rather than leave their callers
            # blocked forever
            with self._cond:
                self._fail_queued_locked(
                    lambda: ServiceClosed(
                        "service closed with the batcher wedged; "
                        "request never executed"
                    )
                )
        # retire the replica workers: each drains its already-routed
        # flushes first, so drained == every admitted future resolved.
        # A wedged replica worker hands back its abandoned flushes
        # (already-delivered hedge-loser copies fail no one: _fail
        # skips resolved futures).
        for flush in self._pool.close(timeout=timeout):
            flush.abort()
            for req in flush.riders:
                self._fail(
                    req,
                    ServiceClosed(
                        "service closed with its replica wedged; "
                        "request never executed"
                    ),
                )
        self._closed = True

    def __enter__(self) -> "PipelineService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- worker
    def _loop(self) -> None:
        """The batcher: form flushes, route each onto a replica.  The
        dispatch is an enqueue — while replica 0 computes a flush, the
        batcher is already forming (and routing) the next one, which is
        what lets N replicas serve N flushes concurrently."""
        ledger.restore_context(self._obs_ctx)
        while True:
            flush = self._next_batch()
            if flush is None:
                return
            # the canary split (serve/rollout.py): while a guarded
            # rollout's judge window is open, the controller claims a
            # deterministic seeded-hash fraction of flushes for the
            # staged generation; everything else (and everything when
            # no window is open — one attribute read) routes normally.
            # A claimed flush is NOT hedged: hedging re-enqueues onto
            # the live generation, which would both pollute the canary
            # sample and mask a slow canary behind a fast live win.
            rollout = self._rollout
            if rollout is not None and rollout.take(flush):
                continue
            try:
                self._pool.dispatch(flush)
            except FleetUnavailable as e:
                # fail fast: no replica can take this flush — resolve
                # its riders NOW (503 at HTTP) instead of parking them
                # behind a pool the router refuses
                flush.abort()
                self.fail_flush(flush, e)
                continue
            hedge = self._hedge
            if hedge is not None:
                hedge.schedule(flush, self._hedge_delay_s())

    def _next_batch(self):
        """Block until a flush is due; pop and return it (None = shut
        down with an empty queue).  Flush condition: ``max_batch``
        requests waiting, the OLDEST has waited ``max_wait_s``, or the
        service is closing (drain)."""
        with self._cond:
            while not self._q:
                if self._closing:
                    return None
                # untimed: every producer path (submit, close) notifies
                # under this condition, so an idle service costs zero
                # wakeups
                self._cond.wait()
            flush_at = self._q[0].t_submit + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closing:
                timeout = flush_at - time.monotonic()
                if timeout <= 0:
                    break
                self._cond.wait(timeout)
            k = min(len(self._q), self.max_batch)
            batch = [self._q.popleft() for _ in range(k)]
            metrics.set_gauge("serve.queue_depth", len(self._q))
            return _Flush(batch, f"b{next(self._batch_seq)}")

    def _fail(self, req, exc, **attrs) -> None:
        """Deliver an exception to a request, tolerating a caller that
        already cancelled its future — an InvalidStateError here would
        kill the batcher thread and brick the whole service.  Also the
        trace terminal for failure paths: the outcome is ``shed`` for a
        deadline shed, ``poison`` for an isolated poison request,
        ``error`` otherwise, finished only if the trace is still live
        (an already-finalized id is left alone).  The trace is finalized
        BEFORE the future is delivered, so a caller woken by
        ``.result()`` can immediately resolve its id via ``/requestz``
        without racing the finalization.  An already-resolved future
        (a hedge loser's copy, a supervisor-abandoned flush whose hung
        runner delivered after all) is skipped entirely — no double
        terminal, no phantom SLO burn."""
        if req.future.done():
            return
        waited = time.monotonic() - req.t_submit
        # client faults (shape mismatch, poison content — the 4xx
        # family) do not burn the server's SLO error budget
        if not isinstance(exc, (TypeError, ValueError)):
            self._fail_win.observe(waited)
        if isinstance(exc, guard.DeadlineExceeded):
            outcome = "shed"
        elif isinstance(exc, PoisonRequest):
            outcome = "poison"
        else:
            outcome = "error"
        self._account_tenant(req, outcome, waited)
        rollout = self._rollout
        if rollout is not None:
            rollout.observe(req, outcome, waited)
        rid = req.request_id
        if rid is not None:
            rec = self.recorder
            if rec is not None:
                rec.finish(
                    rid,
                    outcome,
                    only_live=True,
                    error=f"{type(exc).__name__}: {exc}",
                    **attrs,
                )
            if ledger.active() is not None:
                ledger.event(
                    "serve.request", request_id=rid, outcome=outcome, **attrs
                )
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass

    def _run_flush(self, replica, flush) -> None:
        """One routed flush, on ``replica``'s worker thread: claim it
        (exactly one runner per flush — the hedging/crash-requeue
        guarantee), then shed, pad, apply, resolve futures, account the
        outcome to the router and the replica's breaker.  An unclaimed
        pop is a hedge loser (or a supervisor-aborted flush): cancelled
        without device work, charged breaker-NEUTRAL."""
        if not flush.claim():
            if flush.state != _Flush.ABORTED:
                # the other replica won the hedge race — this copy is
                # the cancelled loser (no device work was wasted)
                metrics.inc("serve.hedge_cancelled")
                rec = self.recorder
                if rec is not None:
                    rec.ops(
                        "serve.hedge",
                        batch=flush.bid,
                        replica=replica.index,
                        outcome="cancelled",
                    )
            self._pool.complete(replica, ok=None)
            return
        if flush.hedged and replica.index != flush.primary:
            metrics.inc("serve.hedge_wins")
        ok: Optional[bool] = False
        try:
            ok = self._run_batch(flush, replica)
        except WorkerCrashed:
            # the replica's worker PROCESS died under this flush: no
            # result was produced or delivered, so return the claim and
            # re-raise — the replica worker loop's crash handler
            # front-requeues the flush and marks the slot dead, and the
            # supervisor's replacement re-claims and serves it.  Zero
            # lost futures, same contract as a thread crash.
            flush.unclaim()
            metrics.inc("serve.worker_crashes", replica=replica.index)
            raise
        except BaseException as e:
            # an escape past _run_batch's own containment (a delivery-
            # layer bug): the claim is SPENT, so a worker-crash requeue
            # could never run this flush again — fail the unresolved
            # riders here, while we still own them.  Escapes reaching
            # the worker loop are therefore all PRE-claim, where the
            # crash handler's front-requeue is always safe.
            logger.exception(
                "flush %s delivery escaped containment on replica %d",
                flush.bid,
                replica.index,
            )
            self.fail_flush(flush, e)
        finally:
            flush.done()
            self._pool.complete(replica, ok=ok)

    def _run_batch(self, flush, replica) -> Optional[bool]:
        """Returns False exactly when the replica's APPLY failed — the
        outcome that should charge its breaker toward open.  True means
        the apply succeeded (charges a success, closes a half-open
        probe).  Shed/cancelled-only batches return None — nothing ran
        on the device, so the breaker is not charged either way: a sick
        replica whose inflated EWMA sheds every rider must not keep
        "passing" its half-open probes with zero device work."""
        batch = flush.riders
        bid = flush.bid
        rec = self.recorder
        now = time.monotonic()
        if rec is not None:
            riders = [r.request_id for r in batch if r.request_id is not None]
            if riders:
                # the batch span records its rider ids as span links —
                # the flush is SHARED by its riders, so it is recorded
                # once and joined on read (/requestz, trace_report).
                # One "serve.batch" event per rider marks its arrival on
                # THIS replica's worker (batch id + replica + queue
                # wait); deeper flush facts live on the batch record —
                # per-rider event count is part of the overhead budget.
                rec.batch(bid, riders, replica=replica.index, rows=len(batch))
            for req in batch:
                rec.annotate(
                    req.request_id,
                    "serve.batch",
                    batch=bid,
                    replica=replica.index,
                    queue_wait_seconds=round(now - req.t_submit, 6),
                )
        # shed what cannot make it: a request whose deadline expires
        # before the batch's predicted completion would occupy a padded
        # row and return an answer its caller already abandoned
        predicted = self._ewma_batch_s
        live = []
        for req in batch:
            fut = req.future
            if fut.done():
                # resolved on a previous attempt (a worker-crash re-run:
                # shed/cancelled/failed riders keep their outcome)
                continue
            if fut.running():
                # already claimed by a previous attempt on a crashed
                # worker — still owed a result; no state transition to
                # make (and set_running_or_notify_cancel on a RUNNING
                # future logs CRITICAL + raises)
                running = True
            else:
                running = fut.set_running_or_notify_cancel()
            if not running:
                # the caller cancelled while the request was queued:
                # don't spend a padded row on it (and, marked RUNNING,
                # a surviving request can no longer be cancelled out
                # from under the set_result below)
                metrics.inc("serve.cancelled")
                if rec is not None:
                    rec.finish(
                        req.request_id,
                        "cancelled",
                        only_live=True,
                        batch=bid,
                        replica=replica.index,
                    )
                continue
            if req.deadline is not None and req.deadline.remaining() <= predicted:
                metrics.inc("serve.shed")
                self._fail(
                    req,
                    guard.DeadlineExceeded(
                        "serve.shed", time.monotonic() - req.t_submit
                    ),
                    batch=bid,
                    replica=replica.index,
                    predicted_seconds=round(predicted, 6),
                    waited_seconds=round(time.monotonic() - req.t_submit, 6),
                )
            else:
                live.append(req)
        if not live:
            # nothing executed, so no new latency sample — DECAY the
            # predictor instead of leaving it frozen: one outlier batch
            # (a cold compile on an unprimed service) would otherwise
            # pin the EWMA above every deadline and shed 100% of
            # traffic forever.  Decay-and-retry converges: predicted
            # drops geometrically until a batch runs and real samples
            # resume.
            with self._ewma_lock:
                self._ewma_batch_s *= 1.0 - _EWMA_ALPHA
            return None
        k = len(live)
        bucket = self._bucket_for(k)
        trace_ids = [r.request_id for r in live if r.request_id is not None]
        deg0 = (
            metrics.REGISTRY.counter_total("executor.degraded")
            if rec is not None
            else 0.0
        )
        t0 = time.monotonic()
        try:
            with ledger.span(
                "serve.batch",
                rows=k,
                bucket=bucket,
                replica=replica.index,
                batch=bid,
                request_ids=trace_ids,
            ):
                fault_point("serve.batch")
                batch_deadline = None
                if self._degrade:
                    # the LOOSEST rider's deadline (and only when every
                    # rider carries one): the executor budget exists to
                    # stop stages NOBODY is still waiting on and to
                    # trigger declared degradation under pressure —
                    # keyed to min() instead, one near-expiry straggler
                    # that escaped the shed predictor would
                    # DeadlineExceeded the whole flush and fail
                    # co-batched requests holding comfortable budgets
                    dls = [r.deadline for r in live if r.deadline is not None]
                    if dls and len(dls) == len(live):
                        batch_deadline = max(dls, key=lambda d: d.at)
                # trace context for the wire: set ONLY when the recorder
                # is on AND the fleet is remote — recorder-off keeps
                # every apply frame byte-identical (pinned), and the
                # thread fleet has no wire to annotate.  Thread-local
                # because _apply_reqs is an override point
                # (serve/tenants.py) whose signature must not grow.
                if rec is not None and self._telemetry is not None:
                    self._trace_tls.ctx = {
                        "batch": bid,
                        "request_ids": trace_ids[: self._trace_ctx_cap],
                    }
                try:
                    out = self._apply_reqs(live, replica, batch_deadline)
                finally:
                    self._trace_tls.ctx = None
        except WorkerCrashed:
            # process death is NOT a batch error: the flush will be
            # re-run whole on the slot's replacement (see _run_flush)
            raise
        except BaseException as e:  # one bad batch must not kill the worker
            metrics.inc("serve.batch_errors")
            logger.warning(
                "serve batch of %d failed on replica %d: %s: %s",
                k,
                replica.index,
                type(e).__name__,
                e,
            )
            if rec is not None:
                rec.batch_update(bid, error=f"{type(e).__name__}: {e}")
            if self._bisect and _poison_suspect(e):
                # a request-attributable failure: bisect the batch to
                # isolate the poison rider(s) — innocent co-batched
                # riders complete, the poison fails typed + quarantined
                return self._bisect_flush(live, replica, bid, batch_deadline, e)
            for req in live:
                self._fail(req, e, batch=bid, replica=replica.index)
            return False
        dt = time.monotonic() - t0
        with self._ewma_lock:
            self._ewma_batch_s = (
                dt
                if not self._ewma_batch_s
                else (1.0 - _EWMA_ALPHA) * self._ewma_batch_s + _EWMA_ALPHA * dt
            )
        metrics.inc("serve.batches")
        self._batch_win.observe(dt)
        metrics.observe("serve.batch_rows", k)
        degraded = False
        if rec is not None:
            # best-effort per-flush degradation detection: the executor
            # counts declared-stage degradations process-wide, so a
            # delta across THIS apply marks the flush (concurrent
            # flushes can cross-attribute — observability, not control)
            degraded = (
                metrics.REGISTRY.counter_total("executor.degraded") > deg0
            )
            rec.batch_update(
                bid,
                rows=k,
                bucket=bucket,
                seconds=round(dt, 6),
                degraded=degraded,
            )
        self._deliver_completed(
            live, out, replica, bid, dt, t0, degraded=degraded
        )
        return True

    def _deliver_completed(
        self, reqs, out, replica, bid, dt, t0, degraded=False
    ) -> None:
        """Resolve completed riders: latency/outcome accounting, trace
        terminals, then the result delivery — shared by the flush happy
        path and bisection's innocent-rider completions.  A rider whose
        future is already resolved (a supervisor-abandoned flush whose
        hung runner finished after all) is skipped: no double terminal,
        no double metrics, and the late ``set_result`` is swallowed."""
        rec = self.recorder
        outcome = "degraded" if degraded else "completed"
        done_t = time.monotonic()
        # one ledger-activation check per FLUSH, not per rider: the
        # inert-path cost of N module-frontend calls is real at serving
        # rates (part of the recorder overhead budget)
        led_on = ledger.active() is not None
        rollout = self._rollout
        for i, req in enumerate(reqs):
            if req.future.done():
                continue
            self._lat_win.observe(done_t - req.t_submit)
            late = req.deadline is not None and req.deadline.expired()
            if late:
                # completed, but late: the shed predictor under-estimated
                # (e.g. the first batch after a stall) — count it so the
                # bench's "completed beat their deadlines" claim is honest
                metrics.inc("serve.deadline_miss")
            metrics.inc("serve.completed")
            self._account_tenant(req, outcome, done_t - req.t_submit)
            if rollout is not None:
                rollout.observe(req, outcome, done_t - req.t_submit)
            if req.request_id is not None:
                if rec is not None:
                    rec.finish(
                        req.request_id,
                        outcome,
                        batch=bid,
                        replica=replica.index,
                        apply_seconds=round(dt, 6),
                        late=late,
                    )
                if led_on:
                    ledger.event(
                        "serve.request",
                        request_id=req.request_id,
                        outcome=outcome,
                        batch=bid,
                        replica=replica.index,
                        seconds=round(done_t - req.t_submit, 6),
                        queue_wait_seconds=round(t0 - req.t_submit, 6),
                    )
            try:
                req.future.set_result(out[i])
            except InvalidStateError:
                pass  # a racing cancel/abandonment got there first

    # ---------------------------------------------------------- bisection
    def _bisect_flush(
        self, live, replica, bid, batch_deadline, first_error
    ) -> Optional[bool]:
        """Isolate poison rider(s) in a failed flush by recursive
        halving, re-using the padding buckets: each failing group is
        split and both halves re-applied; a failing SINGLETON is the
        poison — it alone fails (typed :class:`PoisonRequest`, content
        quarantined), every innocent rider completes.  Depth is
        structurally bounded by ⌈log2(rows)⌉ halvings; at most two
        applies run per level.  Returns the flush's breaker charge:
        True when only poison failures occurred (the replica is
        healthy), False when infrastructure failed a re-run too."""
        metrics.inc("serve.bisections")
        deepest = 0
        applies = 0
        poisons = 0
        infra_failed = False
        t_bisect0 = time.monotonic()

        def fail_poison(req, cause):
            nonlocal poisons
            poisons += 1
            metrics.inc("serve.poison")
            key = _content_key(req.x)
            with self._poison_lock:
                self._poison_cache[key] = time.monotonic()
                self._poison_cache.move_to_end(key)
                while len(self._poison_cache) > _POISON_CACHE_CAP:
                    self._poison_cache.popitem(last=False)
            self._fail(
                req,
                PoisonRequest(
                    "request content fails the model "
                    f"({type(cause).__name__}: {cause}); isolated by "
                    "batch bisection and quarantined"
                ),
                batch=bid,
                replica=replica.index,
            )

        def run_group(reqs, depth):
            nonlocal deepest, applies, infra_failed
            deepest = max(deepest, depth)
            try:
                applies += 1
                t0 = time.monotonic()
                out = self._apply_reqs(reqs, replica, batch_deadline)
            except BaseException as ge:
                if isinstance(ge, WorkerCrashed):
                    # the worker process died mid-bisect: propagate so
                    # the whole flush re-runs on the replacement
                    # (already-resolved riders are skipped there)
                    raise
                if not _poison_suspect(ge):
                    # infrastructure failed the RE-RUN: this group's
                    # riders get the real error, and the replica is
                    # charged (it could not complete clean work)
                    infra_failed = True
                    for req in reqs:
                        self._fail(req, ge, batch=bid, replica=replica.index)
                    return
                if len(reqs) == 1:
                    fail_poison(reqs[0], ge)
                    return
                mid = (len(reqs) + 1) // 2
                run_group(reqs[:mid], depth + 1)
                run_group(reqs[mid:], depth + 1)
                return
            self._deliver_completed(
                reqs, out, replica, bid, time.monotonic() - t0, t0
            )

        if len(live) == 1:
            fail_poison(live[0], first_error)
        else:
            mid = (len(live) + 1) // 2
            run_group(live[:mid], 1)
            run_group(live[mid:], 1)
        took = time.monotonic() - t_bisect0
        if ledger.active() is not None:
            ledger.event(
                "serve.bisect",
                batch=bid,
                replica=replica.index,
                rows=len(live),
                depth=deepest,
                n=applies,
                seconds=round(took, 6),
            )
        rec = self.recorder
        if rec is not None:
            rec.batch_update(bid, depth=deepest, poisons=poisons)
            rec.ops(
                "serve.bisect",
                batch=bid,
                replica=replica.index,
                rows=len(live),
                depth=deepest,
                poisons=poisons,
                seconds=round(took, 6),
            )
        logger.warning(
            "bisected a poisoned flush of %d on replica %d: %d poison "
            "request(s) isolated in %d applies (depth %d, %.3fs)",
            len(live),
            replica.index,
            poisons,
            applies,
            deepest,
            took,
        )
        return False if infra_failed else True

    # -------------------------------------------------------------- apply
    def _apply_reqs(self, reqs, replica, deadline):
        """One flush's apply body: stack the riders' rows and run the
        frozen graph.  Returns something indexable per rider (ndarray
        rows here).  The multi-tenant service overrides this with the
        segment-aware shared-pool apply — both the flush happy path and
        bisection's re-runs route through it, so poison isolation works
        identically per tenant.

        Preformed-flush fast path: when the flush is a complete
        in-order image of ONE admission block (slab-direct ingress),
        the block's slab IS the padded batch — already bucket-shaped,
        pad rows zeroed at allocation — so the ``np.stack`` copy and
        the ``iter_row_chunks`` re-pad are both skipped, and a process
        worker can attach the slab by reference."""
        blk = _block_of(reqs)
        if blk is not None and blk.padded_rows == self._bucket_for(len(reqs)):
            metrics.inc("serve.preformed_flushes")
            return self._apply_rows(
                blk.array,
                deadline=deadline,
                replica=replica,
                pre_padded_n=len(reqs),
                slab_ref=blk.ref,
            )
        stacked = np.stack([req.x for req in reqs])
        metrics.inc("serve.bytes_copied", stacked.nbytes)
        return self._apply_rows(stacked, deadline=deadline, replica=replica)

    def _bucket_for(self, k: int) -> int:
        for b in self.buckets:
            if b >= k:
                return b
        return self.buckets[-1]

    def _apply_rows(
        self,
        stacked: np.ndarray,
        deadline=None,
        replica=None,
        prime: bool = False,
        source_box: Optional[list] = None,
        pre_padded_n: Optional[int] = None,
        slab_ref: Optional[dict] = None,
        **apply_kw,
    ) -> np.ndarray:
        """Pad ``(k, ...)`` rows up to the smallest bucket >= k (the
        ``iter_row_chunks`` pad discipline — zero pad rows, outputs
        sliced back to k), apply the frozen graph on ``replica``
        (default: the pool's first), return host rows.

        ``source_box``: when given, ``"artifact"`` is appended iff the
        batch the applier actually sees matches an installed AOT bucket
        program AND the program survived the call — the authoritative
        prime-source label.  Checked on the POST-construction dataset
        (a sharded deviceless path may pad the batch past the bucket
        shape, in which case the program does not serve), and
        RE-checked after the apply (a program failing at call time is
        dropped and the walk serves — labeling that bucket "artifact"
        would hide exactly the fallback the metric exists to show)."""
        from keystone_tpu.workflow.dataset import Dataset
        from keystone_tpu.workflow.transformer import iter_row_chunks

        if pre_padded_n is not None:
            # slab-direct flush (serve/ingress.py): ``stacked`` is the
            # admission block's array, ALREADY padded to the bucket with
            # zeroed pad rows — re-padding would be the exact copy the
            # zero-copy path exists to skip
            k = int(pre_padded_n)
            padded = stacked
        else:
            k = stacked.shape[0]
            bucket = self._bucket_for(k)
            padded, _mask, _start = next(
                iter(iter_row_chunks(stacked, None, bucket))
            )
        rep = replica if replica is not None else self._pool.replicas[0]
        if getattr(rep.applier, "remote_worker", False):
            # process fleet: the padded HOST batch goes straight to the
            # worker over the shared-memory wire — the router performs
            # no device transfer and holds the GIL only for the memcpy.
            # The n kwarg rides through Replica.apply to the remote
            # applier; prime is consumed BY Replica.apply (it skips the
            # serve.replica fault site for warm-ups — the worker's
            # apply is identical either way).
            if slab_ref is not None and getattr(
                rep.applier, "accepts_slab_ref", False
            ):
                # the ingress already landed the batch in a shared-
                # memory slab: ship the REFERENCE, the worker attaches
                # the same segment by name — the dispatch memcpy is
                # skipped too
                apply_kw = dict(apply_kw, slab_ref=slab_ref)
            trace_ctx = getattr(self._trace_tls, "ctx", None)
            if trace_ctx is not None:
                # recorder-on dispatch: the batch id + rider ids ride
                # the apply frame so the worker's shipped spans stitch
                # back to this flush's record.  None (recorder off,
                # prime calls) adds no key — the frame is byte-identical
                apply_kw = dict(apply_kw, trace=trace_ctx)
            out = rep.apply(
                padded, deadline=deadline, prime=prime, n=k, **apply_kw
            )
            if source_box is not None and rep.applier.has_bucket_program(
                tuple(padded.shape), padded.dtype
            ):
                source_box.append("artifact")
            return np.asarray(out.array)[:k]
        if rep.device is not None:
            # fleet path: commit the batch to THIS replica's device —
            # the default Dataset sharding spans every local device,
            # which XLA rejects against parameters pinned to one
            import jax

            ds = Dataset(jax.device_put(padded, rep.device), n=k, shard=False)
        else:
            ds = Dataset(padded, n=k)
        has = getattr(rep.applier, "has_bucket_program", None)
        prog_key = None
        if (
            source_box is not None
            and has is not None
            and not ds.is_host
            and ds.mask is None
            and has(tuple(ds.array.shape), ds.array.dtype)
        ):
            prog_key = (tuple(ds.array.shape), ds.array.dtype)
        out = rep.apply(ds, deadline=deadline, prime=prime, **apply_kw)
        if prog_key is not None and has(*prog_key):
            source_box.append("artifact")
        if isinstance(out, dict):
            # multi-tenant applier: one full-batch output per tenant
            # (heads differ in output width, so there is no single
            # stacked array to return)
            return {t: np.asarray(d.array)[:k] for t, d in out.items()}
        return np.asarray(out.array)[:k]


def serve(
    pipeline,
    *,
    max_batch: int = 32,
    max_wait_ms: Optional[float] = None,
    queue_bound: int = 128,
    buckets: Optional[Sequence[int]] = None,
    deadline_ms: Optional[float] = None,
    example=None,
    degrade: bool = True,
    name: str = "serve",
    replicas: int = 1,
    devices: Optional[Sequence] = None,
    version: str = "v0",
    recorder=True,
    slo_ms: Optional[float] = None,
    slo_target: float = 0.99,
    slo_window_s: Optional[float] = None,
    supervise: bool = True,
    heartbeat_s: float = 30.0,
    supervise_interval_s: float = 0.5,
    restart_limit: int = 3,
    restart_window_s: float = 60.0,
    hedge_ms=_UNSET,
    bisect: bool = True,
    artifacts: Optional[dict] = None,
    workers: int = 0,
    worker_opts: Optional[dict] = None,
    autoscale: Optional[dict] = None,
    hosts=None,
) -> PipelineService:
    """Freeze a fitted pipeline and stand up a :class:`PipelineService`.

    - ``max_batch`` / ``max_wait_ms`` — flush the micro-batch when either
      bound is hit (count, or oldest-request age).  ``max_wait_ms``,
      ``buckets``, ``hedge_ms``, and the dispatch window resolve through
      the physical-plan precedence (explicit arg > env > installed
      ``PhysicalPlan`` > static default — ``keystone_tpu.planner``);
      passing a value always wins, and with no plan the defaults are
      the historical ones (5 ms wait, power-of-two buckets, hedging
      off).
    - ``queue_bound`` — admission control: ``submit`` past this depth
      raises :class:`Overloaded`.
    - ``buckets`` — padding-bucket batch sizes (default: powers of two
      from 8 up to ``max_batch``); every flush pads to the smallest
      bucket that fits, so compiled program shapes are finite.
    - ``deadline_ms`` — default per-request deadline; requests predicted
      to miss it are shed instead of executed.
    - ``example`` — one datum, used to prime every bucket's compiled
      program at construction (strongly recommended: without it the
      first request per bucket pays the trace+compile).
    - ``degrade`` — plumb the batch's loosest request deadline into the
      executor so ``optional``/``with_fallback`` stages degrade on the
      serve path (loosest so a single tight straggler cannot fail its
      co-batched requests; applied only when every rider has one).
    - ``replicas`` / ``devices`` — size of the serving fleet: each
      replica is an independent clone of the fitted state placed on its
      own device (``devices=None`` cycles ``jax.local_devices()``).
      ``replicas=1`` with no devices is the single-device fast path —
      the given pipeline's applier serves directly, no clone.
    - ``version`` — the model version label the initial replica
      generation reports (``/healthz``, ``/replicas``); hot-swaps via
      :meth:`PipelineService.swap` move it.
    - ``recorder`` — the flight recorder (ON by default): every request
      gets a traced causal chain (ingress → enqueue → batch → replica →
      outcome) in a bounded in-memory ring, served live by
      ``GET /tracez`` / ``GET /requestz/<id>``.  ``False`` disables
      tracing entirely — the service mints no ids and runs no trace
      hook (the PR-5 path, byte-identical — pinned); the HTTP front
      end still echoes an id per response for client-side log
      correlation, it just resolves nowhere server-side.  Or pass a
      configured :class:`~keystone_tpu.obs.recorder.FlightRecorder`.
    - ``slo_ms`` / ``slo_target`` — the latency objective behind
      ``GET /statusz``'s error-budget burn rate (default objective:
      ``deadline_ms``; no deadline, no SLO section).  ``slo_window_s``
      resizes the burn observation window (default 60 s) — the knob a
      guarded rollout's judge/bake guard (``serve/rollout.py``) reads
      through, so short windows make rollback verdicts reflect the
      canary's now rather than the last minute.
    - ``supervise`` (default ON) — the self-healing
      :class:`~keystone_tpu.serve.fleet.ReplicaSupervisor`: dead/wedged
      replica workers are restarted in place (re-clone + re-place from
      the pool's source, buckets re-primed, router rejoined);
      ``restart_limit`` restarts within ``restart_window_s`` seconds
      quarantine the slot.  ``heartbeat_s`` is the wedge budget — a
      worker holding one flush longer than this is declared wedged, so
      size it above the slowest honest apply.
    - ``hedge_ms`` — hedged dispatch (default OFF): a batch still
      unflushed after max(``hedge_ms``, 3× the EWMA batch time) is
      re-enqueued on a second replica; whichever replica claims it
      first runs it, the loser is cancelled without device work and
      charged breaker-neutral.
    - ``bisect`` (default ON) — batch-failure bisection: a flush that
      fails with a request-attributable error is recursively halved to
      isolate the poison request, which alone fails (typed
      :class:`PoisonRequest`, HTTP 422) while innocent co-batched
      riders complete; the content-keyed quarantine cache then refuses
      repeat offenders at admission.
    - ``workers`` — the PROCESS fleet (default 0 = the threaded fleet,
      byte-for-byte the pre-process path): ``workers=N`` runs N
      one-replica worker processes behind the same router — each loads
      the deploy payload + AOT artifacts, primes, and serves applies
      over a shared-memory wire (``serve/wire.py``), so a multi-core
      host's throughput is bounded by cores, not the GIL.  Exclusive
      with ``replicas``/``devices``.  ``worker_opts`` tunes spawn
      (``ready_timeout``, ``max_slab_bytes``).
    - ``hosts`` — the CROSS-HOST fleet (needs ``workers>=1``): workers
      connect over TCP (``serve/net.py``) instead of sharing memory.
      A host map (``"hostA:4,hostB:4"``, or a list / ``HostMap``)
      tells the router where ``keystone worker --connect`` processes
      may be spawned; ``"local"`` spawns on this box.  Each remote
      worker beats a heartbeat lease — an expired lease is treated as
      death (flushes re-served on survivors), and the worker
      self-fences when its OWN lease lapses so a healed partition
      cannot double-serve.  ``worker_opts`` grows ``lease_s``,
      ``listen_host``/``listen_port``, ``spawn_grace_s``,
      ``max_frame_bytes``.  Without ``hosts``, ``workers=N`` stays on
      the shared-memory transport, byte-for-byte.
    - ``autoscale`` — SLO-driven autoscaling (default OFF): a config
      dict for :class:`~keystone_tpu.serve.autoscale.Autoscaler`
      (``min_workers``/``max_workers``/``interval_s``/thresholds).  A
      control thread watches windowed occupancy, queue depth, SLO
      error-budget burn, and the shared-pool hit rate; it grows the
      fleet (spawn → prime-from-artifacts → admit), retires idle
      replicas (drain → join), and retunes the dispatch window live.
    - ``artifacts`` — an AOT artifact bundle
      (``FrozenApplier.export_artifacts`` / registry
      ``load_artifacts``): every replica installs the pre-lowered
      bucket programs so construction-time priming loads instead of
      re-tracing — the cold-start path stops paying compile time.  Any
      mismatch (jax version skew, different backend, corrupt blob,
      signature drift) silently falls one rung down the ladder —
      artifact → persistent compile cache → fresh compile — counted as
      ``serve.artifact_fallbacks``, never failing the deploy.
    """
    return PipelineService(
        pipeline,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        buckets=buckets,
        deadline_ms=deadline_ms,
        example=example,
        degrade=degrade,
        name=name,
        replicas=replicas,
        devices=devices,
        version=version,
        recorder=recorder,
        slo_ms=slo_ms,
        slo_target=slo_target,
        slo_window_s=slo_window_s,
        supervise=supervise,
        heartbeat_s=heartbeat_s,
        supervise_interval_s=supervise_interval_s,
        restart_limit=restart_limit,
        restart_window_s=restart_window_s,
        hedge_ms=hedge_ms,
        bisect=bisect,
        artifacts=artifacts,
        workers=workers,
        worker_opts=worker_opts,
        autoscale=autoscale,
        hosts=hosts,
    )
