"""Online inference service: dynamic micro-batching over a frozen
pipeline, with admission control and deadline-aware shedding.

KeystoneML pipelines are trained once and then applied to a stream of
requests; the reference served that stream through Velox/Spark batch
jobs, and Clipper-style systems (Crankshaw et al., NSDI 2017) showed the
serving win is a thin layer over the frozen model: micro-batch requests
to saturate the accelerator, bound the queue so tail latency stays
bounded, and shed work that cannot meet its deadline.  This module is
that layer for ``keystone_tpu``:

- **Frozen apply** — :class:`~keystone_tpu.workflow.FrozenApplier` runs
  the whole-pipeline optimizer once at service construction; each flush
  binds one padded batch to the pre-optimized graph.
- **Padding buckets** — every flush is padded UP to a fixed bucket size
  (``iter_row_chunks``, the same pad discipline as chunked offline
  applies), so the set of compiled program shapes is finite and
  cache-hot: a single-datum request rides the smallest bucket's batch
  program instead of tracing a per-datum one.
- **Dynamic micro-batching** — a background worker drains the bounded
  FIFO queue, flushing when ``max_batch`` requests are waiting or the
  oldest has waited ``max_wait_ms``, whichever first.
- **Admission control** — ``submit`` past ``queue_bound`` raises
  :class:`Overloaded` (backpressure to the caller); requests whose
  :class:`~keystone_tpu.utils.guard.Deadline` would expire before the
  batch completes (EWMA-predicted) are shed with
  :class:`~keystone_tpu.utils.guard.DeadlineExceeded` instead of
  wasting device time on an answer nobody is waiting for.
- **Degradation** — when every rider carries a deadline, the batch's
  LOOSEST one plumbs into the
  :class:`~keystone_tpu.workflow.GraphExecutor`, so ``optional`` /
  ``with_fallback`` stages degrade on the serve path exactly as they do
  in fits (loosest, not tightest: one near-expiry straggler must never
  deadline-fail a flush its co-riders could comfortably complete).

Observability (``keystone_tpu.obs``): ``serve.queue_depth`` gauge,
``serve.batch_rows``/``serve.batch_seconds``/``serve.latency_seconds``
histograms, ``serve.submitted``/``completed``/``shed``/``rejected``/
``batch_errors``/``deadline_miss`` counters, and one ``serve.batch``
ledger span per flush.  Fault injection (``keystone_tpu.faults``):
sites ``serve.enqueue`` (admission path) and ``serve.batch`` (worker
flush) — chaos plans exercise overload and hang scenarios.

Usage::

    svc = serve(fitted, max_batch=32, max_wait_ms=5, queue_bound=256,
                deadline_ms=100, example=x0)
    fut = svc.submit(x)            # concurrent.futures.Future
    y = fut.result()
    svc.close()                    # drains in-flight requests

The HTTP front end is ``keystone_tpu/serve/http.py``; the CLI entry is
``python -m keystone_tpu.cli serve``; the load generator is
``tools/serve_bench.py``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

#: EWMA smoothing for the per-batch latency predictor the shed decision
#: uses: new = (1-ALPHA)*old + ALPHA*sample.  0.3 tracks load shifts
#: within a few batches without letting one outlier batch (a compile, a
#: GC pause) shed everything behind it.
_EWMA_ALPHA = 0.3


class Overloaded(RuntimeError):
    """Admission control refused the request: the queue is at its bound.
    Backpressure is the caller's signal to retry later or route away —
    deliberately NOT an ``OSError``, so generic transient-I/O retry
    loops don't hammer an already-overloaded service."""


class ServiceClosed(RuntimeError):
    """The service is shut down (or shutting down) and accepts no new
    requests."""


def default_buckets(max_batch: int, min_bucket: int = 8) -> Tuple[int, ...]:
    """Power-of-two padding buckets up to (and including) ``max_batch``.
    The smallest bucket bounds single-datum padding waste; the largest
    equals ``max_batch`` so a full flush pads nothing."""
    max_batch = max(1, int(max_batch))
    b = min(int(min_bucket), max_batch)
    out = []
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


class _Request:
    __slots__ = ("x", "deadline", "future", "t_submit")

    def __init__(self, x, deadline: Optional[guard.Deadline]):
        self.x = x
        self.deadline = deadline
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class PipelineService:
    """A frozen fitted pipeline behind a micro-batching request queue.

    Construct via :func:`serve`.  ``submit``/``submit_many`` return
    ``concurrent.futures.Future`` objects resolved by the background
    batcher thread; ``close`` drains in-flight work.  Thread-safe: any
    number of client threads may submit concurrently (the HTTP front
    end's handler threads do)."""

    def __init__(
        self,
        pipeline,
        max_batch: int = 32,
        max_wait_ms: float = 5.0,
        queue_bound: int = 128,
        buckets: Optional[Sequence[int]] = None,
        deadline_ms: Optional[float] = None,
        example=None,
        degrade: bool = True,
        name: str = "serve",
    ):
        from keystone_tpu.workflow.pipeline import FrozenApplier

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        self._applier = (
            pipeline if isinstance(pipeline, FrozenApplier) else FrozenApplier(pipeline)
        )
        self.max_batch = int(max_batch)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.queue_bound = int(queue_bound)
        self.buckets = (
            tuple(sorted({int(b) for b in buckets}))
            if buckets
            else default_buckets(self.max_batch)
        )
        if self.buckets[-1] < self.max_batch:
            # a flush larger than every bucket would have nowhere to pad
            self.buckets = self.buckets + (self.max_batch,)
        self.default_deadline_s = (
            None if not deadline_ms else float(deadline_ms) / 1000.0
        )
        self._degrade = bool(degrade)
        self.name = name
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closing = False
        self._closed = False
        self._ewma_batch_s = 0.0
        #: admission-time shape/dtype contract, learned from ``example``
        #: (or the first request): a mismatched request fails ITS submit,
        #: never the whole batch it would have ridden in
        self._item_shape: Optional[tuple] = None
        self._dtype = None
        if example is not None:
            ex = np.asarray(example)
            self._item_shape = tuple(ex.shape)
            self._dtype = ex.dtype
            self.prime()
        self._worker = threading.Thread(
            target=self._loop, daemon=True, name=f"{name}-batcher"
        )
        self._worker.start()

    # ------------------------------------------------------------ priming
    def prime(self) -> None:
        """Compile (or cache-load) the apply programs at every bucket
        shape NOW, so no request ever pays a trace+compile against its
        deadline.  Requires the item shape (an ``example`` at
        construction, or a first request already served)."""
        if self._item_shape is None:
            raise ValueError(
                "prime() needs the request item shape; construct the "
                "service with example=<one datum> (or serve a request first)"
            )
        for bucket in self.buckets:
            zeros = np.zeros((bucket,) + self._item_shape, self._dtype)
            self._apply_rows(zeros, deadline=None)

    # ------------------------------------------------------------- submit
    def submit(self, x, deadline=None) -> Future:
        """Enqueue one datum; returns a Future resolving to its result
        row (numpy).  ``deadline``: seconds or a ``guard.Deadline``
        (default: the service's ``deadline_ms``).  Raises
        :class:`Overloaded` when the queue is at bound and
        :class:`ServiceClosed` after shutdown began."""
        return self._submit_all([x], deadline)[0]

    def submit_many(self, xs, deadline=None) -> list:
        """Enqueue a sequence of datums; returns their Futures in order.
        One shared deadline resolution (all requests of the call carry
        the same absolute expiry) and ATOMIC admission: either every
        datum is enqueued or none is — a partial enqueue would leave
        orphaned requests executing for a caller that saw the error."""
        return self._submit_all(list(xs), deadline)

    def _submit_all(self, xs, deadline) -> list:
        if not xs:
            return []
        if self._closing:
            raise ServiceClosed(f"service {self.name!r} is closed")
        dl = guard.as_deadline(
            deadline if deadline is not None else self.default_deadline_s
        )
        for _ in xs:
            fault_point("serve.enqueue")
        arrs = [np.asarray(x) for x in xs]
        with self._cond:
            if self._closing:
                raise ServiceClosed(f"service {self.name!r} is closed")
            # the shape/dtype contract is learned and checked UNDER the
            # lock: concurrent first requests must agree on one item
            # shape, and a mismatched request must fail ITS OWN submit
            # (before anything is enqueued), never the batch it would
            # have ridden in.  Staged, committed only after admission:
            # a rejected (or internally-inconsistent) call must not fix
            # the contract for requests that were never served
            item_shape, dtype = self._item_shape, self._dtype
            for arr in arrs:
                if item_shape is None:
                    item_shape, dtype = tuple(arr.shape), arr.dtype
                elif tuple(arr.shape) != item_shape:
                    raise TypeError(
                        f"request shape {tuple(arr.shape)} != service item "
                        f"shape {item_shape}"
                    )
            if len(self._q) + len(arrs) > self.queue_bound:
                metrics.inc("serve.rejected", len(arrs))
                raise Overloaded(
                    f"service {self.name!r} queue at bound "
                    f"({self.queue_bound}); retry later"
                )
            self._item_shape, self._dtype = item_shape, dtype
            reqs = [
                _Request(
                    a if a.dtype == dtype else a.astype(dtype), dl
                )
                for a in arrs
            ]
            self._q.extend(reqs)
            # gauge set under the lock: written outside it, a stale
            # pre-flush depth could overwrite the batcher's newer value
            # and report a full queue on an idle service
            metrics.set_gauge("serve.queue_depth", len(self._q))
            self._cond.notify_all()
        metrics.inc("serve.submitted", len(reqs))
        return [r.future for r in reqs]

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    @property
    def closed(self) -> bool:
        return self._closed

    # ----------------------------------------------------------- shutdown
    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting requests and shut the batcher down.  With
        ``drain=True`` (default) every already-queued request is flushed
        and resolved before the worker exits; with ``drain=False``
        queued requests fail with :class:`ServiceClosed`."""
        with self._cond:
            self._closing = True
            if not drain:
                while self._q:
                    req = self._q.popleft()
                    self._fail(
                        req, ServiceClosed("service closed before execution")
                    )
                metrics.set_gauge("serve.queue_depth", 0)
            self._cond.notify_all()
        self._worker.join(timeout)
        if self._worker.is_alive():
            logger.warning(
                "service %r batcher did not exit within %.1fs", self.name, timeout
            )
            # the batcher is wedged (e.g. a hung apply with no deadline
            # configured): it will never drain the queue, so fail the
            # still-queued futures rather than leave their callers
            # blocked forever
            with self._cond:
                while self._q:
                    self._fail(
                        self._q.popleft(),
                        ServiceClosed(
                            "service closed with the batcher wedged; "
                            "request never executed"
                        ),
                    )
                metrics.set_gauge("serve.queue_depth", 0)
        self._closed = True

    def __enter__(self) -> "PipelineService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- worker
    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            self._run_batch(batch)

    def _next_batch(self):
        """Block until a flush is due; pop and return it (None = shut
        down with an empty queue).  Flush condition: ``max_batch``
        requests waiting, the OLDEST has waited ``max_wait_s``, or the
        service is closing (drain)."""
        with self._cond:
            while not self._q:
                if self._closing:
                    return None
                # untimed: every producer path (submit, close) notifies
                # under this condition, so an idle service costs zero
                # wakeups
                self._cond.wait()
            flush_at = self._q[0].t_submit + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closing:
                timeout = flush_at - time.monotonic()
                if timeout <= 0:
                    break
                self._cond.wait(timeout)
            k = min(len(self._q), self.max_batch)
            batch = [self._q.popleft() for _ in range(k)]
            metrics.set_gauge("serve.queue_depth", len(self._q))
            return batch

    @staticmethod
    def _fail(req, exc) -> None:
        """Deliver an exception to a request, tolerating a caller that
        already cancelled its future — an InvalidStateError here would
        kill the batcher thread and brick the whole service."""
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass

    def _run_batch(self, batch) -> None:
        # shed what cannot make it: a request whose deadline expires
        # before the batch's predicted completion would occupy a padded
        # row and return an answer its caller already abandoned
        predicted = self._ewma_batch_s
        live = []
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                # the caller cancelled while the request was queued:
                # don't spend a padded row on it (and, marked RUNNING,
                # a surviving request can no longer be cancelled out
                # from under the set_result below)
                metrics.inc("serve.cancelled")
                continue
            if req.deadline is not None and req.deadline.remaining() <= predicted:
                metrics.inc("serve.shed")
                self._fail(
                    req,
                    guard.DeadlineExceeded(
                        "serve.shed", time.monotonic() - req.t_submit
                    ),
                )
            else:
                live.append(req)
        if not live:
            # nothing executed, so no new latency sample — DECAY the
            # predictor instead of leaving it frozen: one outlier batch
            # (a cold compile on an unprimed service) would otherwise
            # pin the EWMA above every deadline and shed 100% of
            # traffic forever.  Decay-and-retry converges: predicted
            # drops geometrically until a batch runs and real samples
            # resume.
            self._ewma_batch_s *= 1.0 - _EWMA_ALPHA
            return
        k = len(live)
        t0 = time.monotonic()
        try:
            with ledger.span(
                "serve.batch", rows=k, bucket=self._bucket_for(k)
            ):
                fault_point("serve.batch")
                stacked = np.stack([req.x for req in live])
                batch_deadline = None
                if self._degrade:
                    # the LOOSEST rider's deadline (and only when every
                    # rider carries one): the executor budget exists to
                    # stop stages NOBODY is still waiting on and to
                    # trigger declared degradation under pressure —
                    # keyed to min() instead, one near-expiry straggler
                    # that escaped the shed predictor would
                    # DeadlineExceeded the whole flush and fail
                    # co-batched requests holding comfortable budgets
                    dls = [r.deadline for r in live if r.deadline is not None]
                    if dls and len(dls) == len(live):
                        batch_deadline = max(dls, key=lambda d: d.at)
                out = self._apply_rows(stacked, deadline=batch_deadline)
        except BaseException as e:  # one bad batch must not kill the worker
            metrics.inc("serve.batch_errors")
            logger.warning(
                "serve batch of %d failed: %s: %s", k, type(e).__name__, e
            )
            for req in live:
                self._fail(req, e)
            return
        dt = time.monotonic() - t0
        self._ewma_batch_s = (
            dt
            if not self._ewma_batch_s
            else (1.0 - _EWMA_ALPHA) * self._ewma_batch_s + _EWMA_ALPHA * dt
        )
        metrics.inc("serve.batches")
        metrics.observe("serve.batch_seconds", dt)
        metrics.observe("serve.batch_rows", k)
        done_t = time.monotonic()
        for i, req in enumerate(live):
            metrics.observe("serve.latency_seconds", done_t - req.t_submit)
            if req.deadline is not None and req.deadline.expired():
                # completed, but late: the shed predictor under-estimated
                # (e.g. the first batch after a stall) — count it so the
                # bench's "completed beat their deadlines" claim is honest
                metrics.inc("serve.deadline_miss")
            metrics.inc("serve.completed")
            req.future.set_result(out[i])

    # -------------------------------------------------------------- apply
    def _bucket_for(self, k: int) -> int:
        for b in self.buckets:
            if b >= k:
                return b
        return self.buckets[-1]

    def _apply_rows(self, stacked: np.ndarray, deadline=None) -> np.ndarray:
        """Pad ``(k, ...)`` rows up to the smallest bucket >= k (the
        ``iter_row_chunks`` pad discipline — zero pad rows, outputs
        sliced back to k), apply the frozen graph, return host rows."""
        from keystone_tpu.workflow.dataset import Dataset
        from keystone_tpu.workflow.transformer import iter_row_chunks

        k = stacked.shape[0]
        bucket = self._bucket_for(k)
        padded, _mask, _start = next(iter(iter_row_chunks(stacked, None, bucket)))
        ds = Dataset(padded, n=k)
        out = self._applier(ds, deadline=deadline)
        return np.asarray(out.array)[:k]


def serve(
    pipeline,
    *,
    max_batch: int = 32,
    max_wait_ms: float = 5.0,
    queue_bound: int = 128,
    buckets: Optional[Sequence[int]] = None,
    deadline_ms: Optional[float] = None,
    example=None,
    degrade: bool = True,
    name: str = "serve",
) -> PipelineService:
    """Freeze a fitted pipeline and stand up a :class:`PipelineService`.

    - ``max_batch`` / ``max_wait_ms`` — flush the micro-batch when either
      bound is hit (count, or oldest-request age).
    - ``queue_bound`` — admission control: ``submit`` past this depth
      raises :class:`Overloaded`.
    - ``buckets`` — padding-bucket batch sizes (default: powers of two
      from 8 up to ``max_batch``); every flush pads to the smallest
      bucket that fits, so compiled program shapes are finite.
    - ``deadline_ms`` — default per-request deadline; requests predicted
      to miss it are shed instead of executed.
    - ``example`` — one datum, used to prime every bucket's compiled
      program at construction (strongly recommended: without it the
      first request per bucket pays the trace+compile).
    - ``degrade`` — plumb the batch's loosest request deadline into the
      executor so ``optional``/``with_fallback`` stages degrade on the
      serve path (loosest so a single tight straggler cannot fail its
      co-batched requests; applied only when every rider has one).
    """
    return PipelineService(
        pipeline,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_bound=queue_bound,
        buckets=buckets,
        deadline_ms=deadline_ms,
        example=example,
        degrade=degrade,
        name=name,
    )
