"""Process-backed replicas: spawn, speak the wire protocol, supervise.

PR 8's :class:`~keystone_tpu.serve.fleet.ReplicaPool` replicas are
worker THREADS — on a multi-core host the measured serving ceiling is
the GIL, not the hardware.  This module promotes a replica's *compute*
into a worker process while every control-plane invariant stays in the
router process exactly as built over PR 8–14: the batcher, the
least-outstanding router, dispatch-window flow control, flush claims
(hedging, crash requeues), poison bisection, breakers, blue/green
stage/commit, and the supervisor all operate on the same
:class:`~keystone_tpu.serve.fleet.Replica` objects — a
:class:`ProcessReplica` merely routes ``replica.apply`` through a
:class:`RemoteApplier` that copies the padded batch into a
shared-memory slab (``serve/wire.py``) and waits on the worker's
control pipe.  The parent thread blocks in ``recv`` with the GIL
RELEASED, so N workers compute on N cores in true parallel.

Lifecycle mapping (thread → process):

- **spawn** — always the ``spawn`` start method (a forked JAX runtime
  inherits locked internals and wedges; ``tools/lint.py proc-spawn``
  fences ``multiprocessing`` into these modules).  The worker loads
  the staged deploy payload (pipeline + AOT artifact bundle), primes
  its padding buckets, and answers a ``ready`` frame — cheap because
  PR-11 artifacts make cold-start-to-first-prediction load-not-compile.
- **dead** — the child exited (crash, OOM-kill, chaos ``SIGKILL``).
  A request in flight fails with :class:`WorkerCrashed`; the service
  layer un-claims the flush and requeues it at the front of the slot's
  queue, the parent worker thread marks the slot dead, and the
  supervisor's standard heal (build replacement → prime → adopt,
  queued work transferred) serves it on the replacement — zero lost
  futures, the same contract the threaded crash path pins.
- **wedged** — the child hangs mid-apply: the parent thread is blocked
  in ``recv`` with the flush in hand, its heartbeat goes stale, and
  the supervisor's wedge classification fires unchanged.  Unlike a
  wedged thread, a wedged PROCESS is killable:
  :meth:`ProcessReplica.drain_queue` SIGKILLs the child so the blocked
  thread unblocks (EOF) and OS resources are reclaimed immediately.
- **retire** — graceful: the parent thread drains its queue, then
  ``bye`` → join → terminate → kill escalation reaps the child.

The worker also beats a shared-memory heartbeat
(``multiprocessing.Value``) the router reads for ``/statusz`` — the
supervisor's wedge detection stays parent-side (stale parent heartbeat
with a flush in hand), but the child-side beat distinguishes "child
computing slowly" from "child gone" in the ops view.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket  # lint: allow-socket (gethostname only; no network use)
import threading
import time
from typing import Optional

import numpy as np

from keystone_tpu.obs import metrics
from keystone_tpu.serve import wire
from keystone_tpu.serve.worker import worker_main

logger = logging.getLogger(__name__)

#: default ceiling on spawn→ready (payload load + artifact install +
#: bucket priming).  Generous: a cold compile of every bucket on a
#: loaded CI box is minutes, and a spawn that outlives it is killed
#: and reported rather than silently wedging construction.
DEFAULT_READY_TIMEOUT_S = 300.0


class WorkerSpawnError(RuntimeError):
    """The worker process failed to reach ready (payload unreadable,
    import failure, ready timeout).  The spawner kills the child before
    raising — no half-born workers."""


class WorkerCrashed(OSError):
    """The worker process died with a request in flight (or refused the
    control channel).  An ``OSError`` on purpose — infrastructure, not
    content: it must never be bisected as poison.  The service layer
    treats it as the process twin of a worker-thread crash: un-claim,
    front-requeue, mark the slot dead, let the supervisor heal."""


class RemoteApplyError(RuntimeError):
    """A content-shaped failure relayed from the worker (the child's
    apply raised something outside the OSError/MemoryError families).
    A ``RuntimeError`` so ``_poison_suspect`` sees it exactly as it
    would the in-process original — bisection and poison quarantine
    work identically across the process boundary."""


class RemoteInfraError(OSError):
    """An infrastructure failure relayed from the worker (the child's
    apply raised an ``OSError``: injected faults, real I/O).  Rides
    ``OSError`` so breaker charging and bisection's infra short-circuit
    behave as in-process."""


class _HostOut:
    """Duck-typed apply result (`.array`) for the remote path — the
    service's ``_apply_rows`` tail reads ``np.asarray(out.array)``."""

    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array


def stage_payload(dir_path: str, seq: int, source, artifacts) -> str:
    """Pickle one generation's deploy payload (fitted pipeline +
    optional AOT bundle) for workers to load — written once per
    generation, read by every worker of it (initial build, scale-ups,
    supervisor heals).  Atomic rename so a half-written payload is
    never loadable."""
    path = os.path.join(dir_path, f"payload-{int(seq)}.pkl")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump({"pipeline": source, "artifacts": artifacts}, f)
    os.replace(tmp, path)
    return path


class WorkerHandle:
    """Owns one worker process: the control pipe, the request slab
    pool (parent-owned), the response-slab attacher, the shared
    heartbeat, and the strict one-in-flight request lock."""

    #: same-host shared memory: a caller holding a payload that ALREADY
    #: lives in a slab (serve/ingress.py admission blocks) may ship the
    #: reference instead of the bytes — the worker attaches the segment
    #: by name.  Cross-host handles (net.NetWorkerHandle) lack this.
    accepts_slab_ref = True

    def __init__(
        self,
        name: str,
        index: int,
        payload_path: str,
        buckets=None,
        item_shape=None,
        dtype: Optional[str] = None,
        ready_timeout: float = DEFAULT_READY_TIMEOUT_S,
        max_slab_bytes: int = wire.DEFAULT_MAX_SLAB_BYTES,
    ):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self.name = f"{name}-worker{index}"
        self.index = int(index)
        #: fleet-telemetry sink (``serve/telemetry.py``), attached by
        #: the pool via :meth:`attach_telemetry`; None = telemetry off
        #: (shipped blobs are simply dropped — old-router behavior)
        self.telemetry = None
        #: host label for fleet metrics — the process fleet is same-box
        #: by construction
        self.peer_host = socket.gethostname()
        self._hb = ctx.Value("d", 0.0)
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        self._conn = parent_conn
        self._lock = threading.Lock()
        self._pool = wire.SlabPool(
            prefix=f"{name}{index}", max_slab_bytes=max_slab_bytes
        )
        self._attacher = wire.SlabAttacher()
        self._closed = False
        spec = {
            "name": str(name),
            "index": self.index,
            "max_slab_bytes": int(max_slab_bytes),
            "payload_path": str(payload_path),
            "buckets": None if buckets is None else [int(b) for b in buckets],
            "item_shape": (
                None if item_shape is None else tuple(int(d) for d in item_shape)
            ),
            "dtype": dtype,
            "heartbeat": self._hb,
        }
        self.proc = ctx.Process(
            target=worker_main,
            args=(child_conn, spec),
            daemon=True,
            name=self.name,
        )
        t0 = time.monotonic()
        self.proc.start()
        child_conn.close()
        try:
            ready = wire.recv_frame(self._conn, timeout=ready_timeout)
        except (TimeoutError, EOFError, OSError, wire.WireError) as e:
            self.kill()
            self._release_resources()
            raise WorkerSpawnError(
                f"{self.name}: no ready frame within {ready_timeout:.0f}s "
                f"({type(e).__name__}: {e})"
            ) from e
        if ready.get("op") == "fatal":
            self.kill()
            self._release_resources()
            raise WorkerSpawnError(
                f"{self.name}: worker failed to start "
                f"({ready.get('etype')}: {ready.get('emsg')})"
            )
        if ready.get("op") != "ready":
            self.kill()
            self._release_resources()
            raise WorkerSpawnError(
                f"{self.name}: unexpected first frame {ready.get('op')!r}"
            )
        self.ready_info = ready
        self.spawn_seconds = time.monotonic() - t0
        #: the ready exchange's telemetry (load/build/prime spans), held
        #: until a sink is attached — the pool attaches one right after
        #: construction, so cold-start spans are not lost to ordering
        self._pending_ready = (t0, time.monotonic(), ready.get("telemetry"))
        #: installed AOT program keys, for honest prime-source labels
        self.artifact_keys = {
            (tuple(shape), str(dt))
            for shape, dt in ready.get("artifact_keys", ())
        }

    # --------------------------------------------------------- telemetry
    def attach_telemetry(self, sink) -> None:
        """Wire this handle to the pool's fleet-telemetry sink and
        flush the ready exchange's shipment (spawn-time spans).  Safe
        with ``sink=None`` (telemetry stays off)."""
        self.telemetry = sink
        pending, self._pending_ready = getattr(
            self, "_pending_ready", None
        ), None
        if sink is None or pending is None:
            return
        t_send, t_recv, shipped = pending
        sink.on_exchange(self.name, self.peer_host, t_send, t_recv, shipped)

    def _ship_reply_telemetry(self, reply, t_send, t_recv, trace) -> None:
        """Hand one reply's shipped telemetry to the sink (never raises
        into the request path — the sink swallows malformed blobs)."""
        sink = self.telemetry
        if sink is None or not isinstance(reply, dict):
            return
        shipped = reply.get("telemetry")
        if shipped is not None:
            sink.on_exchange(
                self.name, self.peer_host, t_send, t_recv, shipped, trace=trace
            )

    # ---------------------------------------------------------- liveness
    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.is_alive()

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the child's last beat (None before the first).
        CLOCK_MONOTONIC is system-wide on Linux, so the comparison is
        sound across the process boundary."""
        v = self._hb.value
        if v <= 0.0:
            return None
        return max(0.0, time.monotonic() - v)

    # ----------------------------------------------------------- request
    def apply(
        self,
        arr: np.ndarray,
        n: int,
        deadline_s: Optional[float] = None,
        slab_ref: Optional[dict] = None,
        trace: Optional[dict] = None,
    ) -> np.ndarray:
        """One remote apply: copy into a slab, frame, wait, read the
        result slab.  Raises the relayed typed error, or
        :class:`WorkerCrashed` when the child died mid-request.
        (Prime/live distinction stays router-side: ``Replica.apply``
        consumes ``prime`` to skip the fault site; the worker's apply
        is identical either way.)

        ``slab_ref``: the batch already lives in a shared-memory slab
        the CALLER owns (an ingress admission block) — ship the
        reference and skip the dispatch memcpy entirely.  The caller
        must keep the slab alive until this returns (it does: the
        request is strictly one-in-flight and blocks for the reply).

        ``trace``: optional trace context (``{"batch": ..,
        "request_ids": [..]}``) carried as a frame body key — absent
        when the recorder is off (the frame is byte-identical to the
        pre-trace wire), ignored by an old worker when present."""
        msg = {"op": "apply", "n": int(n), "deadline_s": deadline_s}
        if trace is not None:
            msg["trace"] = trace
        if slab_ref is not None:
            reply, out = self._request(msg, ref=slab_ref, trace=trace)
        else:
            reply, out = self._request(msg, arr=arr, trace=trace)
        return out

    def ping(self) -> dict:
        reply, _ = self._request({"op": "ping"})
        return reply

    def _request(
        self,
        msg: dict,
        arr: Optional[np.ndarray] = None,
        ref: Optional[dict] = None,
        trace: Optional[dict] = None,
    ):
        with self._lock:
            if self._closed:
                raise WorkerCrashed(f"{self.name}: handle is closed")
            slab = None
            try:
                if ref is not None:
                    # pre-slabbed payload: the reference rides the
                    # control frame, zero dispatch bytes copied
                    msg = dict(msg, ref=ref)
                elif arr is not None:
                    slab, ref_ = wire.write_array(self._pool, arr)
                    metrics.inc("dispatch.bytes_copied", int(arr.nbytes))
                    msg = dict(msg, ref=ref_)
                t_send = time.monotonic()
                try:
                    wire.send_frame(self._conn, msg)
                    reply = wire.recv_frame(self._conn)
                except (EOFError, OSError, wire.WireError) as e:
                    raise WorkerCrashed(
                        f"{self.name} (pid {self.pid}) died mid-request "
                        f"({type(e).__name__}: {e})"
                    ) from e
                # error replies ship telemetry too: a failing apply is
                # exactly the span an operator wants on /requestz
                self._ship_reply_telemetry(
                    reply, t_send, time.monotonic(), trace
                )
            finally:
                if slab is not None:
                    # the child copies at use and has answered: the
                    # request slab is reusable now
                    self._pool.release(slab)
            if reply.get("op") == "error":
                raise self._map_error(reply)
            if reply.get("op") == "result":
                out = self._attacher.read(reply["ref"])
                return reply, out
            return reply, None

    @staticmethod
    def _map_error(reply: dict) -> BaseException:
        """Rehydrate the worker's typed failure on the router side,
        preserving the error taxonomy bisection and breakers key on."""
        from keystone_tpu.utils import guard

        kind = reply.get("kind", "content")
        detail = f"{reply.get('etype')}: {reply.get('emsg')}"
        if kind == "too_large":
            # the worker's RESULT overflowed the slab cap: the same
            # typed refusal a request-side overflow raises (ValueError
            # family — the client's payload shape is the cause; a
            # bisected sub-batch whose output fits will simply succeed)
            return wire.PayloadTooLarge(f"remote apply result: {detail}")
        if kind == "deadline":
            return guard.DeadlineExceeded(
                f"remote apply: {detail}", float(reply.get("seconds") or 0.0)
            )
        if kind == "circuit":
            return guard.CircuitOpenError(f"remote apply: {detail}")
        if kind == "memory":
            return MemoryError(f"remote apply: {detail}")
        if kind == "oserror":
            return RemoteInfraError(f"remote apply: {detail}")
        return RemoteApplyError(f"remote apply: {detail}")

    # ---------------------------------------------------------- shutdown
    def kill(self) -> None:
        """SIGKILL the child (the wedge/quarantine path, and chaos's
        process-kill action).  A parent thread blocked in ``recv``
        unblocks with EOF → :class:`WorkerCrashed`."""
        p = self.proc
        try:
            if p.is_alive():
                p.kill()
            p.join(5.0)
        except (OSError, ValueError, AssertionError):
            pass

    def shutdown(self, timeout: float = 3.0) -> None:
        """Graceful-then-forceful reap: ``bye`` (if the channel is
        idle), join, terminate, kill — then release pipe + slabs.
        Idempotent; called from the parent worker thread's exit hook
        and from pool close."""
        if self._closed:
            return
        got = self._lock.acquire(timeout=max(0.0, timeout) / 3.0)
        try:
            if got and self.proc.is_alive():
                try:
                    wire.send_frame(self._conn, {"op": "bye"})
                    wire.recv_frame(self._conn, timeout=max(0.2, timeout / 3.0))
                except (
                    TimeoutError,
                    EOFError,
                    OSError,
                    wire.WireError,
                ):
                    pass
        finally:
            if got:
                self._lock.release()
        try:
            self.proc.join(max(0.2, timeout / 3.0))
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(max(0.2, timeout / 3.0))
            if self.proc.is_alive():
                self.proc.kill()
                self.proc.join(2.0)
        except (OSError, ValueError, AssertionError):
            pass
        self._release_resources()

    def _release_resources(self) -> None:
        self._closed = True
        try:
            self._conn.close()
        except OSError:
            pass
        exitcode = self.proc.exitcode
        if exitcode not in (0, None):
            # the child died without its own cleanup (SIGKILL, crash):
            # reap its orphaned response slabs from this side
            self._attacher.unlink_all()
        else:
            self._attacher.close()
        self._pool.close()

    def stats(self) -> dict:
        return {
            "pid": self.pid,
            "alive": self.alive(),
            "heartbeat_age_s": self.heartbeat_age(),
            "spawn_seconds": round(self.spawn_seconds, 3),
            "slabs": self._pool.stats(),
        }


class RemoteApplier:
    """The applier-contract shim a :class:`ProcessReplica` carries: the
    padded host batch goes to the worker over shared memory; the result
    comes back the same way.  Accepts a raw padded ndarray (the fast
    path — the service skips the parent-side device transfer entirely
    for remote replicas) or anything with ``.array``/``.n``."""

    #: duck-typed markers: never re-wrap (fleet._as_applier), and the
    #: service's _apply_rows takes the host fast path
    serve_applier = True
    remote_worker = True

    def __init__(self, handle: WorkerHandle):
        self.handle = handle

    @property
    def accepts_slab_ref(self) -> bool:
        """Capability marker the service's dispatch gate reads: True
        exactly when the HANDLE can attach a caller-owned slab by name
        (same-host process workers; cross-host net handles cannot)."""
        return bool(getattr(self.handle, "accepts_slab_ref", False))

    def __call__(self, x, deadline=None, n=None, slab_ref=None, trace=None, **kw):
        if kw:
            # multi-tenant segment kwargs need in-process walks; the
            # service refuses workers>0 for multi-tenant deploys
            raise TypeError(
                f"remote apply does not support kwargs {sorted(kw)}"
            )
        if hasattr(x, "array"):
            arr = np.asarray(x.array)
            if n is None:
                n = getattr(x, "n", arr.shape[0])
        else:
            arr = np.ascontiguousarray(x)
            if n is None:
                n = arr.shape[0]
        deadline_s = None
        if deadline is not None:
            deadline_s = max(0.0, deadline.remaining())
        if slab_ref is not None and self.accepts_slab_ref:
            out = self.handle.apply(
                arr, int(n), deadline_s, slab_ref=slab_ref, trace=trace
            )
        else:
            out = self.handle.apply(arr, int(n), deadline_s, trace=trace)
        return _HostOut(out)

    # ------------------------------------------------- status/prime hooks
    def installed_buckets(self) -> int:
        return int(self.handle.ready_info.get("artifact_buckets", 0))

    def has_bucket_program(self, shape, dtype) -> bool:
        return (tuple(shape), np.dtype(dtype).str) in self.handle.artifact_keys


from keystone_tpu.serve.fleet import Replica  # noqa: E402


class ProcessReplica(Replica):
    """A routing slot whose compute lives in a worker process.  All
    queue/claim/breaker/heartbeat semantics are inherited — only the
    lifecycle edges differ (see module docstring)."""

    def __init__(
        self,
        index: int,
        handle: WorkerHandle,
        version: str = "v0",
        pool_name: str = "serve",
        heartbeat_timeout: float = 30.0,
    ):
        super().__init__(
            index,
            RemoteApplier(handle),
            device=None,
            version=version,
            pool_name=pool_name,
            heartbeat_timeout=heartbeat_timeout,
        )
        self.handle = handle
        self._shutdown_once = threading.Lock()
        self._shut = False

    # ------------------------------------------------------------ health
    def is_dead(self) -> bool:
        """Dead = the parent worker thread crashed (base), OR the child
        process exited while the slot is still live — an idle child
        SIGKILLed between flushes must be healed without waiting for
        the next dispatch to discover the corpse."""
        if super().is_dead():
            return True
        return not (self._retired or self.quarantined) and not self.handle.alive()

    # --------------------------------------------------------- lifecycle
    def _on_worker_exit(self) -> None:
        """Parent worker thread exit hook (sentinel drain or crash):
        reap the child.  Graceful first — a swap-retired worker has
        just finished draining its queue and the child is idle."""
        self._shutdown_handle()

    def _shutdown_handle(self) -> None:
        with self._shutdown_once:
            if self._shut:
                return
            self._shut = True
        self.handle.shutdown()

    def drain_queue(self):
        """The supervisor's decommission drain (heal/quarantine): after
        taking the queue, a child still holding a flush is KILLED so
        the blocked parent thread unblocks (EOF → WorkerCrashed) and
        the hung compute stops occupying a core.  Never called on the
        graceful swap/scale-down path (that's ``retire``)."""
        left = super().drain_queue()
        if self.inflight is not None and self.handle.alive():
            logger.warning(
                "killing wedged worker process %s (pid %s)",
                self.handle.name,
                self.handle.pid,
            )
            self.handle.kill()
        return left

    def join(self, timeout: float):
        left = super().join(timeout)
        w = self._worker
        if w is not None and w.is_alive():
            # the parent thread is stuck in a remote call: kill the
            # child to EOF it loose, then give it a moment
            self.handle.kill()
            w.join(2.0)
        self._shutdown_handle()
        return left

    def status(self) -> dict:
        out = super().status()
        out["backend"] = "process"
        out.update(
            {
                "pid": self.handle.pid,
                "worker_alive": self.handle.alive(),
                "worker_heartbeat_age_s": (
                    None
                    if (age := self.handle.heartbeat_age()) is None
                    else round(age, 3)
                ),
            }
        )
        out["artifact_buckets"] = self.applier.installed_buckets()
        return out
