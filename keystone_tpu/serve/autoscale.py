"""SLO-driven autoscaling: close the loop from signals to fleet size.

PR 9 gave the serving layer windowed occupancy, queue depth, and an SLO
error-budget burn rate; PR 11 made spawning a fresh worker cheap
(artifact-primed cold start); PR 15 made replicas processes that can be
added and retired at runtime.  This module is the controller that
connects them: a single control thread samples the service's signals
every ``interval_s`` and

- **scales up** (``scale_to(n+1)``: spawn → prime-from-artifacts →
  admit) when the queue is persistently deep, the SLO budget is
  burning, or windowed occupancy says every replica is computing
  wall-to-wall;
- **scales down** (graceful drain → join; queued work transfers) after
  ``down_ticks`` consecutive idle samples — hysteresis, so one quiet
  window never thrashes the fleet;
- **retunes the dispatch window** between size changes: deepening
  per-replica queueing when the backlog is transient, tightening
  backpressure when the fleet is idle.

The **pool hit rate** (``serve.pool_hit_rate``, the PR-14 shared stage
pool) acts as a capacity lever: a high hit rate means co-tenant flushes
amortize their shared prefix, so measured occupancy overstates the
marginal cost of more traffic — the controller raises its occupancy
threshold proportionally and scales up later.

Decisions are PURE (:meth:`AutoscalePolicy.decide` maps a
:class:`Signals` snapshot + controller state to an action), the clock
and the signal source are injectable, and every action lands in
metrics (``serve.autoscale_events{action=}``), the ops ring
(``/tracez``), the ledger, and ``/statusz`` — an autoscaler nobody can
see is an outage generator.

Cooldowns: ``up_cooldown_s`` after a scale-up (give the new worker a
window to absorb load before judging again) and ``down_cooldown_s``
after any action before a scale-down.  Scale-downs never go below
``min_workers``; scale-ups never above ``max_workers`` — nor above the
cross-host fleet's mapped slot capacity (``service.host_capacity``,
net backend only): the controller will not ask for a worker no host
has room to run.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from keystone_tpu.obs import ledger, metrics

logger = logging.getLogger(__name__)


@dataclass
class Signals:
    """One sample of everything the policy reads — constructed by
    :meth:`Autoscaler.sample` from the live service, or handed in by
    tests (the injectable signal source)."""

    workers: int
    queue_depth: int
    queue_bound: int
    occupancy: float  # windowed busy fraction, 0..1
    burn_rate: Optional[float]  # SLO error-budget burn; None = no SLO
    pool_hit_rate: Optional[float]  # shared stage pool; None = no pool

    @property
    def queue_frac(self) -> float:
        return self.queue_depth / max(1, self.queue_bound)


@dataclass
class AutoscalePolicy:
    """Thresholds + hysteresis.  All time quantities in seconds."""

    min_workers: int = 1
    max_workers: int = 4
    #: scale up when the queue holds more than this fraction of bound
    up_queue_frac: float = 0.5
    #: ... or the SLO budget burns faster than this
    up_burn: float = 1.0
    #: ... or windowed occupancy exceeds this (lifted by pool hit rate)
    up_occupancy: float = 0.85
    #: how much a fully-hitting shared pool lifts the occupancy bar
    #: (hit_rate × this is added to up_occupancy): shared-prefix
    #: amortization means high occupancy overstates marginal cost
    pool_occupancy_credit: float = 0.10
    #: scale down when occupancy is below this AND the queue is empty
    #: AND the burn rate is calm ...
    down_occupancy: float = 0.30
    down_burn: float = 0.5
    #: ... for this many consecutive samples (hysteresis)
    down_ticks: int = 5
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 30.0
    #: dispatch-window retune band (None disables retuning)
    window_min: Optional[int] = 2
    window_max: Optional[int] = 4

    def is_idle(self, s: Signals) -> bool:
        """The scale-down idle predicate — ONE definition, used both by
        :meth:`decide` and by the controller's hysteresis counter (two
        copies would let the counter gate on a different notion of
        'idle' than the decision itself)."""
        return (
            s.queue_depth == 0
            and s.occupancy <= self.down_occupancy
            and (s.burn_rate is None or s.burn_rate <= self.down_burn)
        )

    def decide(
        self, s: Signals, idle_ticks: int, since_up: float, since_any: float
    ) -> Optional[str]:
        """``"up"``, ``"down"``, or None — pure, clock-free (elapsed
        times come in as arguments)."""
        occ_bar = self.up_occupancy + self.pool_occupancy_credit * (
            s.pool_hit_rate or 0.0
        )
        pressed = (
            s.queue_frac >= self.up_queue_frac
            or (s.burn_rate is not None and s.burn_rate >= self.up_burn)
            or s.occupancy >= occ_bar
        )
        if pressed and s.workers < self.max_workers and since_up >= self.up_cooldown_s:
            return "up"
        if (
            self.is_idle(s)
            and idle_ticks + 1 >= self.down_ticks
            and s.workers > self.min_workers
            and since_any >= self.down_cooldown_s
        ):
            return "down"
        return None

    def window_for(self, s: Signals, current: int) -> Optional[int]:
        """The dispatch-window retune: deepen while a backlog exists
        with the fleet already hot (absorb a transient without a spawn),
        tighten back when calm.  None = leave it alone."""
        if self.window_min is None or self.window_max is None:
            return None
        if s.queue_frac >= self.up_queue_frac and s.workers >= self.max_workers:
            return min(self.window_max, current + 1) if current < self.window_max else None
        if s.queue_depth == 0 and s.occupancy <= self.down_occupancy:
            return max(self.window_min, current - 1) if current > self.window_min else None
        return None


class Autoscaler:
    """The control thread.  ``clock`` and ``signal_source`` are
    injectable (tests drive :meth:`tick` directly with a fake clock and
    synthetic :class:`Signals`); ``apply=False`` makes it a dry-run
    advisor (decisions recorded, fleet untouched)."""

    def __init__(
        self,
        service,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        interval_s: float = 1.0,
        policy: Optional[AutoscalePolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        signal_source: Optional[Callable[[], Signals]] = None,
        apply: bool = True,
        **policy_overrides,
    ):
        if policy is None:
            policy = AutoscalePolicy(
                min_workers=int(1 if min_workers is None else min_workers),
                max_workers=int(4 if max_workers is None else max_workers),
                **policy_overrides,
            )
        elif (
            min_workers is not None
            or max_workers is not None
            or policy_overrides
        ):
            # silently dropping bounds an operator passed alongside an
            # explicit policy is how a fleet "mysteriously" caps at the
            # policy default — misconfiguration must be loud
            raise ValueError(
                "pass EITHER policy= OR min_workers/max_workers/"
                "threshold overrides, not both"
            )
        if policy.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if policy.max_workers < policy.min_workers:
            raise ValueError("max_workers must be >= min_workers")
        self.service = service
        self.policy = policy
        self.interval_s = max(0.05, float(interval_s))
        self._clock = clock
        self._signals = signal_source or self.sample
        self._apply = bool(apply)
        self._idle_ticks = 0
        self._last_up = -1e9
        self._last_any = -1e9
        self.ups = 0
        self.downs = 0
        self.window_retunes = 0
        self.last_action: Optional[dict] = None
        self.last_signals: Optional[Signals] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop,
            daemon=True,
            name=f"{service.name}-autoscaler",
        )

    # ------------------------------------------------------------ signals
    def sample(self) -> Signals:
        """Read the live service's signal set (the default source).
        The pool hit rate comes from THIS service's own shared stage
        pool (multi-tenant services carry one); a service with no pool
        reads None — the process-global gauge would leak a co-resident
        service's hit rate into this fleet's decisions."""
        svc = self.service
        applier = getattr(svc, "_mt_applier", None)
        pool_rate = None
        if applier is not None:
            try:
                pool_rate = applier.pool().hit_rate()
            except Exception:
                pool_rate = None
        return Signals(
            workers=svc._pool.size,
            queue_depth=svc.queue_depth,
            queue_bound=svc.queue_bound,
            occupancy=svc.occupancy(),
            burn_rate=svc.slo_burn_rate(),
            pool_hit_rate=pool_rate,
        )

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "Autoscaler":
        self._thread.start()
        return self

    def stop(self, timeout: float = 60.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _loop(self) -> None:
        ledger.restore_context(self.service._obs_ctx)
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # the controller must never die of a resize
                logger.exception("autoscaler tick failed")

    # ---------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """One control decision (the loop body; tests call it
        directly).  Returns the action taken ("up"/"down"/"window"/
        None)."""
        svc = self.service
        if getattr(svc, "_closing", False):
            return None
        s = self._signals()
        self.last_signals = s
        now = self._clock()
        action = self.policy.decide(
            s,
            self._idle_ticks,
            now - self._last_up,
            now - self._last_any,
        )
        self._idle_ticks = (
            self._idle_ticks + 1 if self.policy.is_idle(s) else 0
        )
        if action == "up":
            target = min(self.policy.max_workers, s.workers + 1)
            # the cross-host fleet's host map bounds growth: a scale-up
            # past the mapped slot capacity would spawn a worker no
            # host has room to run (HostCapacityError mid-resize)
            cap = getattr(svc, "host_capacity", None)
            if cap is not None:
                target = min(target, int(cap))
            if target <= s.workers:
                return None
            self._act("up", s, target)
            self._last_up = now
            self._last_any = now
            self.ups += 1
            self._idle_ticks = 0
            return "up"
        if action == "down":
            target = max(self.policy.min_workers, s.workers - 1)
            self._act("down", s, target)
            self._last_any = now
            self.downs += 1
            self._idle_ticks = 0
            return "down"
        # between size changes: the cheap lever
        new_window = self.policy.window_for(s, svc._pool.window)
        if new_window is not None:
            if self._apply:
                svc.set_dispatch_window(new_window)
            self.window_retunes += 1
            metrics.inc("serve.autoscale_events", action="window")
            self._record("window", s, new_window)
            return "window"
        return None

    def _act(self, action: str, s: Signals, target: int) -> None:
        metrics.inc("serve.autoscale_events", action=action)
        if self._apply:
            self.service.scale_to(target)
        self._record(action, s, target)

    def _record(self, action: str, s: Signals, target) -> None:
        self.last_action = {
            "action": action,
            "target": target,
            "workers": s.workers,
            "queue_depth": s.queue_depth,
            "occupancy": round(s.occupancy, 4),
            "burn_rate": None if s.burn_rate is None else round(s.burn_rate, 3),
            "pool_hit_rate": (
                None if s.pool_hit_rate is None else round(s.pool_hit_rate, 4)
            ),
        }
        ledger.event(
            "serve.autoscale",
            action=action,
            workers=s.workers,
            queue_depth=s.queue_depth,
            occupancy=round(s.occupancy, 4),
        )
        rec = getattr(self.service, "recorder", None)
        if rec is not None:
            rec.ops(
                "serve.autoscale",
                action=action,
                workers=s.workers,
                queue_depth=s.queue_depth,
                occupancy=round(s.occupancy, 4),
            )
        logger.info(
            "autoscale %s -> %s (occupancy %.2f, queue %d, burn %s)",
            action,
            target,
            s.occupancy,
            s.queue_depth,
            "n/a" if s.burn_rate is None else f"{s.burn_rate:.2f}",
        )

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        p = self.policy
        s = self.last_signals
        return {
            "min_workers": p.min_workers,
            "max_workers": p.max_workers,
            "interval_seconds": self.interval_s,
            "apply": self._apply,
            "ups": self.ups,
            "downs": self.downs,
            "window_retunes": self.window_retunes,
            "idle_ticks": self._idle_ticks,
            "last_action": self.last_action,
            "last_signals": (
                None
                if s is None
                else {
                    "workers": s.workers,
                    "queue_depth": s.queue_depth,
                    "occupancy": round(s.occupancy, 4),
                    "burn_rate": (
                        None if s.burn_rate is None else round(s.burn_rate, 3)
                    ),
                    "pool_hit_rate": (
                        None
                        if s.pool_hit_rate is None
                        else round(s.pool_hit_rate, 4)
                    ),
                }
            ),
        }
