"""Multi-tenant serving: N pipelines, one fleet, shared prefixes once.

A production deployment serves many heads over the same featurization
(one SIFT/FV/Nyström front end feeding per-customer classifiers).
Served as N independent :class:`~keystone_tpu.serve.service.PipelineService`
instances, every tenant's flush recomputes the shared prefix; this
module co-serves them behind ONE batcher + replica fleet and computes
each shared prefix once per combined flush:

- :class:`MultiTenantApplier` — the frozen-apply unit the
  :class:`~keystone_tpu.serve.fleet.ReplicaPool` replicates: one
  :class:`~keystone_tpu.workflow.pipeline.FrozenApplier` per tenant
  plus the cross-pipeline :class:`~keystone_tpu.workflow.cross.SharingPlan`
  (shared-prefix signatures, collision-gated).  Applying a flush walks
  each tenant's graph over the SAME bound batch under one flush token;
  the walks read marked stages through the process-wide
  :class:`~keystone_tpu.workflow.stage_pool.SharedStagePool`, so the
  first tenant computes the shared prefix and every co-tenant's walk
  prunes at the pool hit.
- :class:`MultiTenantService` — per-tenant admission queues with
  per-tenant quotas and default deadlines, deficit-round-robin flush
  scheduling (fair share of every combined flush under unequal offered
  load), per-tenant circuit breakers (a tenant whose requests keep
  failing is refused at ITS admission, nobody else's), per-tenant
  metrics/latency windows/SLO burn rate in ``/statusz``, and
  tenant-contained flush failures: a tenant-targeted ``serve.batch``
  fault (``ctx.tenant=``) fails that tenant's riders only — co-flushed
  tenants deliver.

Fairness/batching: the batcher drains the per-tenant queues with
classic deficit round robin (quantum = ``max_batch / active tenants``
rows per round), then orders the flush tenant-contiguously so each
tenant's rows form one segment of the combined padded batch.  Each
tenant's HEAD runs over the full padded batch (heads are cheap; the
shared prefix is the cost) and its rows are sliced out at delivery.

Single-tenant degeneration is pinned: with one tenant the sharing plan
is empty, the executor takes the identical pre-pool walk, and
predictions are byte-identical to a plain ``PipelineService`` over the
same pipeline (tests/test_multitenant.py).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from concurrent.futures import InvalidStateError
from typing import Dict, Optional

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.serve.service import Overloaded, PipelineService
from keystone_tpu.utils import guard
from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.cross import plan_sharing
from keystone_tpu.workflow.stage_pool import (
    SharedStagePool,
    default_pool,
    pool_by_token,
)

logger = logging.getLogger(__name__)

metrics.register_buckets(
    "serve.tenant_latency_seconds", metrics.LATENCY_MS_BUCKETS
)
metrics.register_buckets(
    "serve.tenant_failed_wait_seconds", metrics.LATENCY_MS_BUCKETS
)

#: process-wide flush-token mint — tokens must never repeat while any
#: pool entry lives, and never collide across co-resident services
_TOKENS = itertools.count(1)

#: per-service registration namespace mint: two co-resident services
#: (blue/green, bench A/B arms) may share tenant NAMES — registrations
#: on the shared default pool must not clobber each other
_OWNERS = itertools.count(1)


class UnknownTenant(TypeError):
    """The request names a tenant this service does not serve — the
    CLIENT's fault (a ``TypeError`` like the shape-contract violation:
    HTTP 400, no SLO burn)."""


def _freeze(pipeline):
    from keystone_tpu.workflow.pipeline import FrozenApplier

    return (
        pipeline
        if isinstance(pipeline, FrozenApplier)
        else FrozenApplier(pipeline)
    )


class MultiTenantApplier:
    """N frozen appliers + the cross-pipeline sharing plan, applied as
    one unit per combined flush.  This is what the
    :class:`~keystone_tpu.serve.fleet.ReplicaPool` clones per replica —
    the plan is plain data and pickles along; a clone's walks share the
    same pool entries because the keys are content-addressed, not
    instance-addressed."""

    #: duck-typed frozen-applier marker (serve/fleet._as_applier)
    serve_applier = True

    def __init__(self, models: Dict[str, object], pool=None, share: bool = True):
        if not models:
            raise ValueError("serve_multi needs at least one tenant model")
        self.appliers = {str(k): _freeze(p) for k, p in models.items()}
        self.share = bool(share)
        if share:
            self.plan = plan_sharing(
                {t: a.graph for t, a in self.appliers.items()}
            )
        else:
            from keystone_tpu.workflow.cross import SharingPlan

            self.plan = SharingPlan(
                {t: {} for t in self.appliers}, frozenset(), {}, 0
            )
        #: a private pool (tests / budget isolation).  The pool object
        #: holds a lock (unpicklable), so pickling keeps only its
        #: TOKEN — replica clones in this process re-resolve the SAME
        #: pool (stage_pool.pool_by_token), preserving the configured
        #: budget/registrations; a cross-process unpickle falls back to
        #: the process default (keys stay content+token addressed)
        self._pool = pool
        self._pool_ref = None if pool is None else pool.token
        if self.plan.shared:
            ledger.event(
                "serve.pool_plan",
                tenants=len(self.appliers),
                shared_stages=len(self.plan.shared),
                refused=self.plan.refused,
            )

    def pool(self) -> SharedStagePool:
        if self._pool is not None:
            return self._pool
        if self._pool_ref is not None:
            resolved = pool_by_token(self._pool_ref)
            if resolved is not None:
                self._pool = resolved
                return resolved
        return default_pool()

    def graphs(self):
        """Per-tenant graphs (serve/fleet device placement walks them)."""
        return [a.graph for a in self.appliers.values()]

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_pool"] = None  # holds a lock; clones re-resolve via token
        return state

    # -------------------------------------------------------------- apply
    def __call__(self, ds, deadline=None, tenants=None, errors_out=None):
        """Walk every requested tenant's graph over ``ds`` under ONE
        flush token; returns ``{tenant: result Dataset}`` (each over the
        FULL batch — the service slices per-tenant rows out).

        ``errors_out``: a dict marks this a LIVE flush — each tenant's
        walk fires the ``serve.batch`` fault site with ``ctx.tenant``
        and a per-tenant failure is stored there instead of propagating
        (blast-radius containment: one tenant's poison/overload must
        not shed another's traffic).  ``None`` (priming, offline use)
        propagates the first failure after the pool flush is released."""
        pool = self.pool()
        names = list(self.appliers) if tenants is None else list(tenants)
        unknown = [t for t in names if t not in self.appliers]
        if unknown:
            raise UnknownTenant(f"unknown tenant(s) {unknown!r}")
        token = next(_TOKENS)
        pool.begin_flush(token, self.plan.sigs_for(names))
        outs: Dict[str, object] = {}
        first_error = None
        try:
            for t in names:
                try:
                    if tenants is not None:
                        # live flushes only (priming passes tenants=None):
                        # the tenant-scoped serve.batch fire is what lets
                        # a chaos plan target ONE tenant's flush work
                        fault_point("serve.batch", tenant=t)
                    outs[t] = self._walk(t, ds, deadline, pool, token)
                except BaseException as e:
                    if errors_out is None:
                        raise
                    errors_out[t] = e
                    metrics.inc("serve.tenant_batch_errors", tenant=t)
        finally:
            pool.end_flush(token)
        return outs

    def _walk(self, tenant: str, ds, deadline, pool, token):
        from keystone_tpu.workflow.executor import DatasetExpr, GraphExecutor

        a = self.appliers[tenant]
        g, _ = a.graph.replace_source_with_node(
            a.source, G.DatasetOperator(ds)
        )
        ex = GraphExecutor(
            g,
            deadline=deadline,
            stage_pool=pool,
            pool_token=token,
            pool_sigs=self.plan.node_sigs.get(tenant),
        )
        expr = ex.execute(g.sink_dependencies[a.sink])
        if not isinstance(expr, DatasetExpr):
            raise TypeError(
                f"tenant {tenant!r} apply produced "
                f"{type(expr).__name__}, expected dataset"
            )
        return expr.dataset


class MultiTenantService(PipelineService):
    """A :class:`PipelineService` serving N tenants through one batcher
    and one replica fleet, with the shared stage pool computing common
    featurization prefixes once per combined flush.  Construct via
    :func:`serve_multi`."""

    def __init__(
        self,
        models: Dict[str, object],
        *,
        share: bool = True,
        pool: Optional[SharedStagePool] = None,
        tenant_queue_bound: Optional[Dict[str, int]] = None,
        tenant_deadline_ms: Optional[Dict[str, float]] = None,
        tenant_breaker_threshold: Optional[int] = None,
        dedup: bool = False,
        **kw,
    ):
        if kw.get("workers") or kw.get("hosts") is not None:
            raise NotImplementedError(
                "multi-tenant serving runs in-process (the shared stage "
                "pool and per-tenant containment need the executor walk); "
                "workers= (process fleet) and hosts= (cross-host fleet) "
                "apply to single-tenant services"
            )
        applier = MultiTenantApplier(models, pool=pool, share=share)
        self.tenants = tuple(applier.appliers)
        self._mt_applier = applier
        # per-tenant state must exist BEFORE super().__init__: the base
        # constructor primes (broadcast apply) and starts the batcher
        # thread, which immediately calls the overridden _next_batch
        self._tq: Dict[str, deque] = {t: deque() for t in self.tenants}
        self._deficit: Dict[str, float] = {t: 0.0 for t in self.tenants}
        self._rr = 0
        self._tlat = {
            t: metrics.WindowedHistogram(
                "serve.tenant_latency_seconds", tenant=t
            )
            for t in self.tenants
        }
        self._tfail = {
            t: metrics.WindowedHistogram(
                "serve.tenant_failed_wait_seconds", tenant=t
            )
            for t in self.tenants
        }
        #: cross-request in-flight dedup (opt-in): identical concurrent
        #: payloads for the SAME tenant are computed once — the
        #: follower's future resolves from the leader's result,
        #: bit-identical.  Keyed per tenant: two tenants' identical
        #: payloads run different models and must never share.
        self._dedup = bool(dedup)
        self._dedup_lock = threading.Lock()
        self._dedup_inflight: Dict[tuple, object] = {}
        self._tenant_bounds = dict(tenant_queue_bound or {})
        self._tenant_deadline_s = {
            t: float(ms) / 1000.0
            for t, ms in (tenant_deadline_ms or {}).items()
        }
        #: per-tenant quota/deadline breakers (the guard layer): None
        #: threshold = off (the default, zero per-request cost)
        self._tenant_breakers = (
            {
                t: guard.CircuitBreaker(
                    f"serve.tenant.{t}",
                    threshold=int(tenant_breaker_threshold),
                )
                for t in self.tenants
            }
            if tenant_breaker_threshold
            else {}
        )
        super().__init__(applier, **kw)
        stage_pool = applier.pool()
        #: registrations are namespaced per SERVICE instance: a
        #: co-resident service closing its own tenant "a" must not
        #: unregister another service's live "a" on the shared pool
        self._pool_owner = f"{self.name}#{next(_OWNERS)}"
        for t in self.tenants:
            stage_pool.register_tenant(
                f"{self._pool_owner}:{t}",
                set(applier.plan.node_sigs.get(t, {}).values()),
            )
        # ProfilingAutoCacheRule-style placement at pool granularity:
        # priming observed every shared stage's output bytes, so the
        # pin set can be chosen under the budget now
        if applier.plan.shared and self._item_shape is not None:
            stage_pool.auto_pin()

    # --------------------------------------------------------- tenant hooks
    def _resolve_tenant(self, tenant):
        if tenant is None:
            if len(self.tenants) == 1:
                return self.tenants[0]
            raise UnknownTenant(
                f"service {self.name!r} serves tenants "
                f"{list(self.tenants)}; submit(tenant=...) is required"
            )
        tenant = str(tenant)
        if tenant not in self._tq:
            raise UnknownTenant(
                f"unknown tenant {tenant!r}; serving {list(self.tenants)}"
            )
        brk = self._tenant_breakers.get(tenant)
        if brk is not None and not brk.allow():
            raise guard.CircuitOpenError(
                f"tenant {tenant!r} breaker is open (repeated failures); "
                "admission refused for this tenant only"
            )
        return tenant

    def _default_deadline_for(self, tenant):
        return self._tenant_deadline_s.get(tenant, self.default_deadline_s)

    def _tenant_bound(self, tenant: str) -> int:
        """Per-tenant quota: explicit, else an equal share of the global
        bound — one tenant's burst can never occupy another's slots."""
        explicit = self._tenant_bounds.get(tenant)
        if explicit is not None:
            return int(explicit)
        return max(1, self.queue_bound // max(1, len(self.tenants)))

    def _check_bound_locked(self, n_new, tenant):
        q = self._tq[tenant]
        bound = self._tenant_bound(tenant)
        if len(q) + n_new > bound:
            metrics.inc("serve.rejected", n_new)
            raise Overloaded(
                f"tenant {tenant!r} queue at its quota ({bound}); "
                "retry later"
            )
        if self._queue_depth_locked() + n_new > self.queue_bound:
            metrics.inc("serve.rejected", n_new)
            raise Overloaded(
                f"service {self.name!r} queue at bound "
                f"({self.queue_bound}); retry later"
            )

    def _push_locked(self, reqs, tenant):
        q = self._tq[tenant]
        q.extend(reqs)
        depth = self._queue_depth_locked()
        metrics.set_gauge("serve.queue_depth", depth)
        metrics.set_gauge("serve.tenant_queue_depth", len(q), tenant=tenant)
        return depth

    def _queue_depth_locked(self) -> int:
        return sum(len(q) for q in self._tq.values())

    @property
    def queue_depth(self) -> int:
        return self._queue_depth_locked()

    def _fail_queued_locked(self, make_exc) -> None:
        for t, q in self._tq.items():
            while q:
                self._fail(q.popleft(), make_exc())
            metrics.set_gauge("serve.tenant_queue_depth", 0, tenant=t)
        metrics.set_gauge("serve.queue_depth", 0)

    def _account_admission(self, tenant, outcome, n):
        if tenant is None or tenant not in self._tq:
            return
        if outcome == "submitted":
            metrics.inc("serve.tenant_submitted", n, tenant=tenant)
        elif outcome == "rejected":
            metrics.inc("serve.tenant_rejected", n, tenant=tenant)
            for _ in range(n):
                self._tfail[tenant].observe(0.0)
        elif outcome in ("poison", "error"):
            metrics.inc("serve.tenant_errors", n, tenant=tenant)

    def _account_tenant(self, req, outcome, seconds):
        t = req.tenant
        if t is None or t not in self._tq:
            return
        brk = self._tenant_breakers.get(t)
        if outcome in ("completed", "degraded"):
            metrics.inc("serve.tenant_completed", tenant=t)
            self._tlat[t].observe(seconds)
            if brk is not None:
                brk.record_success()
            return
        if outcome == "shed":
            metrics.inc("serve.tenant_shed", tenant=t)
            self._tfail[t].observe(seconds)
            # a shed is the SERVICE's capacity decision, breaker-neutral
            return
        metrics.inc("serve.tenant_errors", tenant=t)
        self._tfail[t].observe(seconds)
        if brk is not None:
            brk.record_failure()

    # --------------------------------------------------------------- dedup
    def _dedup_keys(self, arrs):
        """Per-datum content digests (outside the admission lock —
        hashing payloads is the expensive part)."""
        if not self._dedup:
            return None
        from keystone_tpu.serve.service import _content_key

        return [_content_key(a) for a in arrs]

    def _dedup_match(self, tenant, keys) -> dict:
        """Map datum index → in-flight leader (an earlier unresolved
        request with identical content) or — for a duplicate WITHIN
        this call — the leading datum's index (resolved to its request
        by :meth:`_dedup_register` once the requests exist).  Holds the
        admission lock; the map lock nests inside."""
        followers: dict = {}
        local: dict = {}
        with self._dedup_lock:
            for i, k in enumerate(keys):
                mk = (tenant, k)
                if mk in local:
                    followers[i] = local[mk]  # datum index of the leader
                    continue
                cand = self._dedup_inflight.get(mk)
                if cand is not None and not cand.future.done():
                    followers[i] = cand
                else:
                    local[mk] = i  # this datum leads for mk
        return followers

    def _dedup_register(self, tenant, keys, reqs, followers) -> None:
        # resolve within-call followers (datum-index placeholders) to
        # their leader request objects now that requests exist
        for i, leader in list(followers.items()):
            if isinstance(leader, int):
                followers[i] = reqs[leader]
        with self._dedup_lock:
            for i, req in enumerate(reqs):
                if i in followers:
                    continue
                mk = (tenant, keys[i])
                self._dedup_inflight[mk] = req
                req.future.add_done_callback(self._dedup_cleanup(mk, req))

    def _dedup_cleanup(self, mk, req):
        def cb(_fut):
            with self._dedup_lock:
                if self._dedup_inflight.get(mk) is req:
                    del self._dedup_inflight[mk]

        return cb

    def _dedup_attach(self, followers: dict, reqs: list) -> None:
        """Fan the leader's outcome out to each follower (outside the
        admission lock).  Success delivers a COPY of the leader's
        result row — bit-identical, and a caller mutating its response
        can never corrupt a co-rider's.  Failure propagates the
        leader's typed error through the standard failure terminal."""
        metrics.inc("serve.dedup_hits", len(followers))
        rec = self.recorder
        for i, leader in followers.items():
            req = reqs[i]
            if rec is not None and req.request_id is not None:
                rec.annotate(
                    req.request_id,
                    "serve.dedup",
                    leader=leader.request_id,
                )

            def deliver(lf, req=req, leader=leader):
                try:
                    exc = lf.exception()
                except BaseException as e:  # a cancelled leader
                    exc = e
                if exc is not None:
                    self._fail(req, exc, leader=leader.request_id)
                    return
                waited = time.monotonic() - req.t_submit
                metrics.inc("serve.completed")
                self._lat_win.observe(waited)
                self._account_tenant(req, "completed", waited)
                if req.request_id is not None:
                    if rec is not None:
                        rec.finish(
                            req.request_id,
                            "completed",
                            only_live=True,
                            leader=leader.request_id,
                        )
                    if ledger.active() is not None:
                        ledger.event(
                            "serve.request",
                            request_id=req.request_id,
                            outcome="completed",
                            leader=leader.request_id,
                            seconds=round(waited, 6),
                        )
                try:
                    req.future.set_result(np.copy(lf.result()))
                except InvalidStateError:
                    pass  # the follower was cancelled meanwhile

            leader.future.add_done_callback(deliver)

    # ------------------------------------------------------------ batching
    def _next_batch(self):
        """Deficit-round-robin flush former: every active tenant earns
        ``max_batch / active`` row credits per round and spends them
        FIFO from its own queue, so a combined flush carries a fair
        share of each tenant's backlog no matter how unequal the
        offered loads are.  Riders are then ordered tenant-contiguously
        (stable within a tenant) so the flush's rows form one segment
        per tenant."""
        from keystone_tpu.serve.service import _Flush

        with self._cond:
            while self._queue_depth_locked() == 0:
                if self._closing:
                    return None
                self._cond.wait()
            oldest = min(q[0].t_submit for q in self._tq.values() if q)
            flush_at = oldest + self.max_wait_s
            while (
                self._queue_depth_locked() < self.max_batch
                and not self._closing
            ):
                timeout = flush_at - time.monotonic()
                if timeout <= 0:
                    break
                self._cond.wait(timeout)
            batch = self._drr_pop_locked()
            metrics.set_gauge("serve.queue_depth", self._queue_depth_locked())
            for t in self.tenants:
                metrics.set_gauge(
                    "serve.tenant_queue_depth", len(self._tq[t]), tenant=t
                )
            return _Flush(batch, f"b{next(self._batch_seq)}")

    def _drr_pop_locked(self) -> list:
        active = [t for t in self.tenants if self._tq[t]]
        for t in self.tenants:
            if t not in self._deficit or not self._tq[t]:
                self._deficit[t] = 0.0
        if not active:
            return []
        quantum = max(1.0, self.max_batch / len(active))
        # rotate the starting tenant per flush so sub-quantum rounding
        # never systematically favors tenant order
        self._rr += 1
        start = self._rr % len(active)
        order = active[start:] + active[:start]
        batch: list = []
        while len(batch) < self.max_batch and any(
            self._tq[t] for t in order
        ):
            for t in order:
                if len(batch) >= self.max_batch:
                    # a full flush earns nobody further credit this
                    # round — banked quantum would let one tenant
                    # monopolize the NEXT flush wholesale
                    break
                q = self._tq[t]
                if not q:
                    self._deficit[t] = 0.0
                    continue
                self._deficit[t] += quantum
                while (
                    q
                    and self._deficit[t] >= 1.0
                    and len(batch) < self.max_batch
                ):
                    batch.append(q.popleft())
                    self._deficit[t] -= 1.0
        for t in order:
            # carry at most one quantum of unspent credit across
            # flushes (the DRR discipline): enough to smooth
            # sub-quantum rounding, never enough to capture a whole
            # future flush
            self._deficit[t] = min(self._deficit[t], quantum)
        idx = {t: i for i, t in enumerate(order)}
        batch.sort(key=lambda r: idx.get(r.tenant, len(idx)))
        return batch

    # --------------------------------------------------------------- apply
    def _apply_reqs(self, reqs, replica, deadline):
        """Segment-aware combined apply: one padded batch, one flush
        token, each tenant's walk reading the shared prefix through the
        pool.  Per-tenant failures are CONTAINED: the failing tenant's
        riders fail (bisected when the error is content-shaped — poison
        isolation works per tenant), co-flushed tenants deliver.  Only
        when EVERY tenant failed does the flush take the base error
        path (replica breaker charge, whole-flush accounting)."""
        segs = []
        for i, r in enumerate(reqs):
            if not segs or segs[-1][0] != r.tenant:
                segs.append([r.tenant, i, i + 1])
            else:
                segs[-1][2] = i + 1
        names = list(dict.fromkeys(s[0] for s in segs))
        if len(names) == 1:
            # single-tenant group (bisection sub-runs land here): let
            # failures PROPAGATE so the caller's bisection/containment
            # machinery sees them
            outs = self._apply_rows(
                np.stack([r.x for r in reqs]),
                deadline=deadline,
                replica=replica,
                tenants=names,
            )
            return outs[names[0]]
        errors: dict = {}
        outs = self._apply_rows(
            np.stack([r.x for r in reqs]),
            deadline=deadline,
            replica=replica,
            tenants=names,
            errors_out=errors,
        )
        if errors and len(errors) == len(names):
            raise next(iter(errors.values()))
        out_rows: list = [None] * len(reqs)
        for t, s, e in segs:
            if t in errors:
                exc = errors[t]
                group = reqs[s:e]
                from keystone_tpu.serve.service import _poison_suspect

                if self._bisect and _poison_suspect(exc):
                    # content-shaped failure: isolate the poison rider
                    # WITHIN this tenant's segment — innocents complete
                    self._bisect_flush(
                        group, replica, "tenant-bisect", deadline, exc
                    )
                else:
                    for r in group:
                        self._fail(r, exc, replica=replica.index)
                continue
            rows = outs[t]
            for i in range(s, e):
                out_rows[i] = rows[i]
        return out_rows

    # -------------------------------------------------------------- status
    def status(self) -> dict:
        out = super().status()
        reg = metrics.REGISTRY
        tenants = {}
        for t in self.tenants:
            lat = self._tlat[t].summary()
            n_ok = lat["count"]
            n_fail = self._tfail[t].summary()["count"]
            entry = {
                "queue_depth": len(self._tq[t]),
                "quota": self._tenant_bound(t),
                "latency_ms": self._ms(lat),
                "counters": {
                    "submitted": reg.counter_value(
                        "serve.tenant_submitted", tenant=t
                    ),
                    "completed": reg.counter_value(
                        "serve.tenant_completed", tenant=t
                    ),
                    "shed": reg.counter_value("serve.tenant_shed", tenant=t),
                    "rejected": reg.counter_value(
                        "serve.tenant_rejected", tenant=t
                    ),
                    "errors": reg.counter_value(
                        "serve.tenant_errors", tenant=t
                    ),
                },
            }
            brk = self._tenant_breakers.get(t)
            if brk is not None:
                entry["breaker"] = brk.state()
            if self._slo_s is not None:
                n = n_ok + n_fail
                bad = (
                    0.0
                    if n == 0
                    else (
                        self._tlat[t].fraction_above(self._slo_s) * n_ok
                        + n_fail
                    )
                    / n
                )
                budget = 1.0 - self._slo_target
                entry["slo"] = {
                    "bad_fraction": round(bad, 6),
                    "burn_rate": (
                        None if budget <= 0.0 else round(bad / budget, 3)
                    ),
                }
            tenants[t] = entry
        out["tenants"] = tenants
        plan = self._mt_applier.plan
        out["stage_pool"] = {
            **self._mt_applier.pool().stats(),
            "shared_stages": len(plan.shared),
            "collision_refusals": plan.refused,
            "sharing": self._mt_applier.share,
        }
        return out

    # ------------------------------------------------------------ shutdown
    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        super().close(drain=drain, timeout=timeout)
        pool = self._mt_applier.pool()
        for t in self.tenants:
            pool.unregister_tenant(f"{self._pool_owner}:{t}")


def serve_multi(
    models: Dict[str, object],
    *,
    share: bool = True,
    pool: Optional[SharedStagePool] = None,
    tenant_queue_bound: Optional[Dict[str, int]] = None,
    tenant_deadline_ms: Optional[Dict[str, float]] = None,
    tenant_breaker_threshold: Optional[int] = None,
    dedup: bool = False,
    **kw,
) -> MultiTenantService:
    """Stand up a multi-tenant :class:`MultiTenantService`.

    ``models``: ``{tenant name: fitted pipeline (or FrozenApplier)}``.
    ``share=False`` disables the cross-pipeline stage pool (the A/B
    arm ``tools/serve_bench.py --tenants`` measures against).  ``pool``:
    a private :class:`SharedStagePool` (default: the process-wide one).
    ``tenant_queue_bound``/``tenant_deadline_ms``: per-tenant quota and
    default deadline overrides (quota default: an equal share of
    ``queue_bound``).  ``tenant_breaker_threshold``: consecutive
    failures before a tenant's OWN admission breaker opens (None =
    off).  Remaining keywords are :func:`keystone_tpu.serve.serve`'s
    (``max_batch``, ``deadline_ms``, ``replicas``, ``example``, ...).

    ``dedup=True`` enables cross-request in-flight dedup: identical
    concurrent payloads for the same tenant are computed ONCE — later
    arrivals ride the in-flight leader's computation, consume no queue
    slot, and resolve bit-identically from its result (counted as
    ``serve.dedup_hits``).  Off by default: coupled outcomes (a shed
    leader sheds its followers) are a semantic opt-in.

    Requests are routed with ``svc.submit(x, tenant="name")`` / HTTP
    ``POST /predict`` with ``"tenant"`` in the body."""
    return MultiTenantService(
        models,
        share=share,
        pool=pool,
        tenant_queue_bound=tenant_queue_bound,
        tenant_deadline_ms=tenant_deadline_ms,
        tenant_breaker_threshold=tenant_breaker_threshold,
        dedup=dedup,
        **kw,
    )
