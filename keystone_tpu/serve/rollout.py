"""Guarded rollouts: canary-fraction swaps with automatic rollback.

The blue/green swap (``service.swap`` → ``fleet.stage``/``commit``)
moves a version from 0% to 100% of traffic in one commit — a bad
publish is caught only by a human watching ``/statusz``.  This module
closes the loop:

- :class:`CanaryController` stages a generation exactly like ``swap``,
  but before committing it serves a configurable **traffic fraction**
  to the staged replicas: the batcher's routing hook hands each formed
  flush to :meth:`CanaryController.take`, which splits by a
  deterministic seeded BLAKE2b hash of the flush's first request id —
  the same seed and ids reproduce the same split, so a canary episode
  is replayable (``tools/workloads.py`` provides the seeded traffic).
- While the canary serves, per-generation outcome/latency stats
  accumulate (:meth:`CanaryController.observe`, called from the
  service's request terminals).  Once a **minimum sample window** is
  reached the judge evaluates guardrails — canary error/poison/shed
  rate, the service's windowed SLO burn rate
  (:meth:`~keystone_tpu.serve.service.PipelineService.slo_burn`),
  canary p99 vs the live generation, and an optional
  prediction-divergence probe on dual-applied sampled rows — and either
  **commits** (the ordinary ``pool.commit``) or **rolls back**
  (staged generation retired and drained, zero lost futures; the bad
  version durably quarantined in the registry so the watcher cannot
  re-deploy it).
- Post-commit, a :class:`RollbackGuard` keeps watching the burn rate
  for a **bake period** and reverts to the prior generation on
  sustained violation.

Every decision is recorded as a ``serve.rollout`` recorder ops span,
counted under ``serve.rollout.*`` metrics, and visible in the
``GET /rolloutz`` status block (``service.rollout_status()``).

With ``canary=None`` nothing here runs at all — ``service.swap`` is the
byte-for-byte PR-8/11 blue/green path (pinned by tests).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import Counter
from typing import List, Optional

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics

logger = logging.getLogger(__name__)

#: hash-split granularity: 53 bits of BLAKE2b mapped into [0, 1) — every
#: float in the unit interval is exactly representable, so the split
#: threshold compare is deterministic across platforms
_HASH_BITS = 53
_HASH_DENOM = float(1 << _HASH_BITS)

#: request-terminal outcomes that count AGAINST the canary (the 4xx/5xx
#: family plus deadline sheds); "completed"/"degraded" count for it
_BAD_OUTCOMES = ("error", "poison", "shed")


def canary_hash(seed: int, request_id: str) -> float:
    """Deterministic [0, 1) split coordinate for one request id: the
    router serves a flush on the canary generation iff this is below
    the configured fraction.  Seeded — replaying the same ids under the
    same seed reproduces the exact routing split (the determinism pin
    tests/test_rollout.py holds)."""
    h = hashlib.blake2b(
        f"{int(seed)}:{request_id}".encode(), digest_size=8
    ).digest()
    return (int.from_bytes(h, "big") >> (64 - _HASH_BITS)) / _HASH_DENOM


class RolloutConfig:
    """Knobs for one guarded rollout episode.

    - ``canary`` — traffic fraction (0, 1] served by the staged
      generation during the judge window.  None disables the guard
      entirely (the caller should use plain ``service.swap``).
    - ``seed`` — the routing-hash seed (replayable split).
    - ``min_samples`` — request terminals the canary must accumulate
      before the judge may decide; below it the judge refuses to read
      noise as a verdict.
    - ``decide_s`` — judge window bound: if ``min_samples`` has not
      arrived by then, ``insufficient`` ("rollback" default, or
      "commit") decides.
    - ``max_error_rate`` — canary error+poison+shed fraction above
      which the judge rolls back.
    - ``max_burn`` — service-wide windowed SLO burn rate above which
      the judge rolls back (needs an ``slo_ms`` objective and at least
      ``min_samples`` requests in the burn window).
    - ``p99_ratio`` — roll back when canary p99 latency exceeds this
      multiple of the live generation's p99 (both need >= 8 completed
      samples; None disables).
    - ``divergence_rtol`` — optional prediction-divergence probe: up to
      ``divergence_samples`` canary rows are re-applied on BOTH
      generations and the max relative difference above this rolls
      back (None disables — models with intentional output drift).
    - ``bake_s`` — post-commit bake: a :class:`RollbackGuard` watches
      the burn rate this long and reverts on sustained violation
      (``bake_max_burn`` for at least ``bake_sustain_s``).  0 disables.
    """

    __slots__ = (
        "canary",
        "seed",
        "min_samples",
        "decide_s",
        "max_error_rate",
        "max_burn",
        "p99_ratio",
        "divergence_rtol",
        "divergence_samples",
        "bake_s",
        "bake_max_burn",
        "bake_sustain_s",
        "insufficient",
        "poll_s",
    )

    def __init__(
        self,
        canary: Optional[float] = 0.1,
        seed: int = 0,
        min_samples: int = 32,
        decide_s: float = 30.0,
        max_error_rate: float = 0.1,
        max_burn: float = 2.0,
        p99_ratio: Optional[float] = 3.0,
        divergence_rtol: Optional[float] = None,
        divergence_samples: int = 4,
        bake_s: float = 0.0,
        bake_max_burn: float = 2.0,
        bake_sustain_s: float = 1.0,
        insufficient: str = "rollback",
        poll_s: float = 0.02,
    ):
        if canary is not None:
            canary = float(canary)
            if not (0.0 < canary <= 1.0):
                raise ValueError(
                    f"canary fraction must be in (0, 1], got {canary}"
                )
        if insufficient not in ("rollback", "commit"):
            raise ValueError(
                f"insufficient must be 'rollback' or 'commit', "
                f"got {insufficient!r}"
            )
        self.canary = canary
        self.seed = int(seed)
        self.min_samples = max(1, int(min_samples))
        self.decide_s = max(0.0, float(decide_s))
        self.max_error_rate = float(max_error_rate)
        self.max_burn = float(max_burn)
        self.p99_ratio = None if p99_ratio is None else float(p99_ratio)
        self.divergence_rtol = (
            None if divergence_rtol is None else float(divergence_rtol)
        )
        self.divergence_samples = max(1, int(divergence_samples))
        self.bake_s = max(0.0, float(bake_s))
        self.bake_max_burn = float(bake_max_burn)
        self.bake_sustain_s = max(0.0, float(bake_sustain_s))
        self.insufficient = insufficient
        self.poll_s = max(0.001, float(poll_s))

    #: body keys POST /swap (and the watcher config) may carry; anything
    #: else in the body is NOT a rollout knob and is left alone
    REQUEST_KEYS = (
        "canary",
        "seed",
        "min_samples",
        "decide_s",
        "max_error_rate",
        "max_burn",
        "p99_ratio",
        "divergence_rtol",
        "bake_s",
        "bake_max_burn",
        "bake_sustain_s",
        "insufficient",
    )

    @classmethod
    def from_request(cls, body: dict) -> "RolloutConfig":
        """Build from an admin request body (``POST /swap``); unknown
        keys are ignored, bad values raise ValueError (a 400)."""
        kw = {k: body[k] for k in cls.REQUEST_KEYS if body.get(k) is not None}
        try:
            return cls(**kw)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad rollout config: {e}")

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class _GenStats:
    """Outcome/latency tally for one generation during the canary
    window.  Mutated under the controller's lock."""

    __slots__ = ("outcomes", "latencies")

    def __init__(self):
        self.outcomes: Counter = Counter()
        self.latencies: List[float] = []

    def total(self) -> int:
        return sum(self.outcomes.values())

    def bad(self) -> int:
        return sum(self.outcomes.get(o, 0) for o in _BAD_OUTCOMES)

    def p99(self) -> Optional[float]:
        if len(self.latencies) < 8:
            return None
        lats = sorted(self.latencies)
        return lats[min(len(lats) - 1, int(0.99 * len(lats)))]

    def summary(self) -> dict:
        total = self.total()
        p99 = self.p99()
        return {
            "requests": total,
            "bad": self.bad(),
            "bad_rate": (self.bad() / total) if total else None,
            "outcomes": dict(self.outcomes),
            "p99_ms": None if p99 is None else round(1000.0 * p99, 3),
        }


class CanaryController:
    """One guarded rollout episode: stage → canary-serve a fraction →
    judge → commit or roll back.  Build one per episode (single-use);
    :func:`guarded_swap` is the convenience wrapper.

    ``registry``: when given, a rollback durably quarantines the bad
    version (``ModelRegistry.quarantine``) and restores the ``CURRENT``
    pointer to the prior version, so the watcher cannot re-deploy the
    publish the guard just condemned; a commit moves ``CURRENT`` to the
    new version (the admin-swap discipline).
    """

    def __init__(self, service, config: RolloutConfig, registry=None):
        if config.canary is None:
            raise ValueError(
                "CanaryController needs a canary fraction; use "
                "service.swap() directly for unguarded swaps"
            )
        self.service = service
        self.config = config
        self.registry = registry
        self._lock = threading.Lock()
        self._stats = {"live": _GenStats(), "canary": _GenStats()}
        self._staged: List = []
        #: accepting-flushes flag: True only while the judge window is
        #: open (routing also requires service._rollout is self)
        self._open = False
        self._canary_flushes = 0
        self._live_flushes = 0
        self._fallbacks = 0
        #: sampled canary rows for the optional divergence probe
        self._probe_rows: List[np.ndarray] = []
        self._used = False

    # ------------------------------------------------------ routing hook
    def take(self, flush) -> bool:
        """Batcher hook: claim ``flush`` for the canary generation.
        Returns True iff the flush was dispatched onto a staged replica
        (the batcher then skips its normal dispatch).  Deterministic:
        the seeded hash of the flush's first request id (falling back
        to the flush id for untraced services) against the configured
        fraction.  Never blocks and never raises — when no staged
        replica can take the flush (window full, breaker open) it falls
        back to the live generation and is counted
        (``serve.rollout.canary_fallbacks``)."""
        if not self._open:
            return False
        riders = flush.riders
        rid = None
        for r in riders:
            if getattr(r, "request_id", None) is not None:
                rid = r.request_id
                break
        if rid is None:
            rid = flush.bid
        if canary_hash(self.config.seed, rid) >= self.config.canary:
            self._mark(riders, "live")
            with self._lock:
                self._live_flushes += 1
            return False
        # tag riders BEFORE enqueueing: the staged worker may pop and
        # terminate them before take() returns
        self._mark(riders, "canary")
        try:
            chosen = self.service._pool.dispatch_staged(flush, self._staged)
        except Exception:
            logger.exception("canary dispatch failed; serving on live")
            chosen = None
        if chosen is None:
            self._mark(riders, "live")
            with self._lock:
                self._fallbacks += 1
            metrics.inc("serve.rollout.canary_fallbacks")
            return False
        with self._lock:
            self._canary_flushes += 1
            if (
                self.config.divergence_rtol is not None
                and len(self._probe_rows) < self.config.divergence_samples
            ):
                x = getattr(riders[0], "x", None)
                if x is not None:
                    self._probe_rows.append(np.array(x, copy=True))
        metrics.inc("serve.rollout.canary_flushes")
        return True

    @staticmethod
    def _mark(riders, gen: str) -> None:
        for r in riders:
            try:
                r.gen = gen
            except AttributeError:
                pass  # raw riders (tests) need no generation tag

    # -------------------------------------------------- terminal hook
    def observe(self, req, outcome: str, seconds: float) -> None:
        """Request-terminal hook (called from the service's ``_fail``
        and ``_deliver_completed`` next to the tenant accounting):
        attribute the outcome and latency to the rider's generation."""
        gen = getattr(req, "gen", None) or "live"
        with self._lock:
            st = self._stats.get(gen)
            if st is None:
                return
            st.outcomes[outcome] += 1
            if outcome in ("completed", "degraded"):
                st.latencies.append(seconds)

    def snapshot(self) -> dict:
        """Live per-generation stats (the /rolloutz canary block)."""
        with self._lock:
            return {
                "live": self._stats["live"].summary(),
                "canary": self._stats["canary"].summary(),
                "canary_flushes": self._canary_flushes,
                "live_flushes": self._live_flushes,
                "canary_fallbacks": self._fallbacks,
            }

    # ------------------------------------------------------------ episode
    def run(
        self,
        pipeline,
        version: Optional[str] = None,
        artifacts: Optional[dict] = None,
    ) -> dict:
        """The guarded swap: stage + prime ``pipeline`` (exactly the
        ``service.swap`` discipline), canary-serve the configured
        fraction until the judge decides, then commit or roll back.
        Returns an info dict — ``verdict`` is ``"committed"`` or
        ``"rolled_back"``, ``reason`` names the deciding guardrail; a
        commit's dict is a superset of ``swap``'s (version /
        pause_seconds / prime_seconds / replicas).  A rollback does NOT
        raise — the prior generation never stopped serving and the
        caller reads the verdict.

        Serialized under the service's swap lock for the WHOLE episode:
        a concurrent swap/scale waits out the canary window (bounded by
        ``decide_s``), and ``close()``'s bounded lock wait maps an
        in-flight canary to a rollback (the judge sees ``_closing``)."""
        if self._used:
            raise RuntimeError("CanaryController is single-use; build a new one")
        self._used = True
        svc = self.service
        cfg = self.config
        from keystone_tpu.serve.service import ServiceClosed

        if svc._closing:
            raise ServiceClosed(f"service {svc.name!r} is closed")
        # a previous episode's bake guard is superseded by this rollout
        # — stop it BEFORE taking the swap lock (its revert path takes
        # the same lock; joining it while holding the lock would wedge)
        prev = svc._rollout_guard
        if prev is not None:
            prev.stop()
            svc._rollout_guard = None
        with svc._swap_lock:
            if svc._closing:
                raise ServiceClosed(f"service {svc.name!r} is closed")
            svc._swap_seq += 1
            version = version or f"swap{svc._swap_seq}"
            from_version = svc.version
            pool = svc._pool
            t0 = time.monotonic()
            state = {
                "phase": "staging",
                "version": version,
                "from_version": from_version,
                "canary_fraction": cfg.canary,
                "seed": cfg.seed,
            }
            svc._rollout_state = state
            verdict, reason = "rolled_back", "stage_failed"
            committed = False
            pause_s = prime_s = 0.0
            try:
                with ledger.span(
                    "serve.rollout",
                    version=version,
                    canary_fraction=cfg.canary,
                ):
                    fault_point("serve.rollout", version=version)
                    if artifacts:
                        from keystone_tpu.utils.compile_cache import (
                            seed_compile_cache,
                        )

                        seed_compile_cache(artifacts)
                    staged = pool.stage(pipeline, version, artifacts=artifacts)
                    self._staged = staged
                    try:
                        if svc._item_shape is not None:
                            svc.prime(
                                replicas=staged,
                                have_artifacts=artifacts is not None,
                            )
                        prime_s = time.monotonic() - t0
                        # the canary window: install the routing hook,
                        # judge, uninstall — the hook MUST come off
                        # before commit/abandon either way
                        state["phase"] = "canary"
                        self._open = True
                        svc._rollout = self
                        try:
                            verdict, reason = self._judge(state)
                        finally:
                            svc._rollout = None
                            self._open = False
                        if verdict == "committed":
                            # capture what a bake-period revert needs
                            # BEFORE commit moves the staged source in
                            prior_source = pool._source
                            prior_artifacts = pool._artifacts
                            pause_s = pool.commit(staged, version)
                            committed = True
                    finally:
                        if not committed:
                            self._abandon(staged)
            except BaseException:
                svc._rollout_state = None
                self._finish(state, verdict, "episode_error", from_version)
                raise
            seconds = time.monotonic() - t0
            info = {
                "version": version,
                "from_version": from_version,
                "verdict": verdict,
                "reason": reason,
                "canary_fraction": cfg.canary,
                "seconds": seconds,
                "canary": self.snapshot(),
            }
            if committed:
                info.update(
                    pause_seconds=pause_s,
                    prime_seconds=prime_s,
                    replicas=len(self._staged),
                )
                svc._version_history.append(from_version)
                metrics.inc("serve.swaps")
                metrics.inc("serve.rollout.commits")
                metrics.observe("serve.swap_pause_seconds", pause_s)
                metrics.observe("serve.swap_prime_seconds", prime_s)
                self._registry_commit(version)
                if cfg.bake_s > 0.0:
                    svc._rollout_guard = RollbackGuard(
                        svc,
                        cfg,
                        from_version=from_version,
                        to_version=version,
                        prior_source=prior_source,
                        prior_artifacts=prior_artifacts,
                        registry=self.registry,
                    ).start()
            else:
                metrics.inc("serve.rollout.rollbacks")
                self._registry_rollback(version, from_version, reason)
            svc._rollout_state = (
                None if svc._rollout_guard is None else svc._rollout_guard.status()
            )
            self._finish(state, verdict, reason, from_version)
            logger.info(
                "guarded rollout of %r to %s: %s (%s) — canary %.0f%% "
                "served %d flushes in %.2fs",
                svc.name,
                version,
                verdict,
                reason,
                100.0 * cfg.canary,
                self._canary_flushes,
                seconds,
            )
            return info

    # ------------------------------------------------------------- judge
    def _judge(self, state: dict):
        """Poll until a verdict: a guardrail violation rolls back
        immediately; a clean read at >= min_samples commits; the
        decide_s bound expiring maps to the configured insufficient-
        sample action.  ``service._closing`` aborts to rollback so
        ``close()`` never waits out a full canary window."""
        cfg = self.config
        svc = self.service
        deadline = time.monotonic() + cfg.decide_s
        while True:
            if svc._closing:
                return "rolled_back", "service_closing"
            with self._lock:
                canary_total = self._stats["canary"].total()
            state["canary_samples"] = canary_total
            if canary_total >= cfg.min_samples:
                violation = self._guardrails()
                if violation is not None:
                    return "rolled_back", violation
                divergence = self._divergence()
                if divergence is not None:
                    return "rolled_back", divergence
                return "committed", "guardrails_clean"
            if time.monotonic() >= deadline:
                if cfg.insufficient == "commit":
                    return "committed", "insufficient_samples"
                return "rolled_back", "insufficient_samples"
            time.sleep(cfg.poll_s)

    def _guardrails(self) -> Optional[str]:
        """First violated guardrail's name, or None when all clean."""
        cfg = self.config
        with self._lock:
            canary = self._stats["canary"]
            bad_rate = canary.bad() / max(1, canary.total())
            canary_p99 = canary.p99()
            live_p99 = self._stats["live"].p99()
        if bad_rate > cfg.max_error_rate:
            return "error_rate"
        burn = self.service.slo_burn()
        if (
            burn is not None
            and burn["burn_rate"] is not None
            and burn["window_requests"] >= cfg.min_samples
            and burn["burn_rate"] > cfg.max_burn
        ):
            return "slo_burn"
        if (
            cfg.p99_ratio is not None
            and canary_p99 is not None
            and live_p99 is not None
            and live_p99 > 0.0
            and canary_p99 > cfg.p99_ratio * live_p99
        ):
            return "p99_ratio"
        return None

    def _divergence(self) -> Optional[str]:
        """The optional dual-apply probe: sampled canary rows applied
        on one live AND one staged replica must agree within rtol.  A
        probe failure ON THE STAGED side is a rollback reason; a LIVE-
        side failure (or no live replica to probe) skips the probe —
        the canary must not be condemned for the old generation's
        faults."""
        cfg = self.config
        if cfg.divergence_rtol is None:
            return None
        with self._lock:
            rows = list(self._probe_rows)
        if not rows:
            return None
        svc = self.service
        live_rep = next(
            (r for r in svc._pool.replicas if r.routable()), None
        )
        staged_rep = next((r for r in self._staged if r.routable()), None)
        if live_rep is None or staged_rep is None:
            return None
        x = np.stack(rows)
        try:
            ref = np.asarray(svc._apply_rows(x, replica=live_rep, prime=True))
        except Exception as e:
            logger.warning("divergence probe skipped (live apply failed): %s", e)
            return None
        try:
            got = np.asarray(
                svc._apply_rows(x, replica=staged_rep, prime=True)
            )
        except Exception as e:
            logger.warning("divergence probe failed on canary: %s", e)
            return "divergence"
        if ref.shape != got.shape or not np.all(np.isfinite(got)):
            return "divergence"
        denom = np.maximum(np.abs(ref), 1e-6)
        if float(np.max(np.abs(got - ref) / denom)) > cfg.divergence_rtol:
            return "divergence"
        return None

    # ------------------------------------------------------------ outcome
    def _abandon(self, staged) -> None:
        """Retire + drain the staged generation without committing.
        Queued canary flushes the staged workers already drained served
        normally; leftovers (post-sentinel stragglers, a wedged staged
        worker's in-hand flush) re-dispatch onto the live generation —
        the scale-down discipline, zero lost futures."""
        svc = self.service
        from keystone_tpu.serve.fleet import FleetUnavailable

        for flush in svc._pool.abandon_staged(staged):
            if getattr(flush, "unflushed", lambda: False)():
                svc._handle_stranded_flush(
                    flush, why="canary generation rolled back"
                )
            else:
                getattr(flush, "abort", lambda: False)()
                svc.fail_flush(
                    flush,
                    FleetUnavailable(
                        "canary generation rolled back with a flush "
                        "still in hand"
                    ),
                )

    def _registry_commit(self, version: str) -> None:
        """Move CURRENT to the committed version (admin-swap parity);
        best-effort — a pointer failure never un-commits the fleet."""
        reg = self.registry
        if reg is None:
            return
        try:
            if version in reg.versions() and reg.current() != version:
                reg.set_current(version)
        except Exception as e:
            logger.warning(
                "rollout committed %s but CURRENT update failed: %s",
                version,
                e,
            )

    def _registry_rollback(
        self, version: str, from_version: str, reason: str
    ) -> None:
        """Durably quarantine the condemned version and point CURRENT
        back at what the fleet still serves; best-effort."""
        reg = self.registry
        if reg is None:
            return
        try:
            if version in reg.versions():
                reg.quarantine(version, reason=f"rollout rollback: {reason}")
        except Exception as e:
            logger.warning("failed to quarantine %s: %s", version, e)
        try:
            if (
                reg.current() == version
                and from_version in reg.versions()
            ):
                reg.set_current(from_version)
        except Exception as e:
            logger.warning(
                "failed to restore CURRENT to %s: %s", from_version, e
            )

    def _finish(
        self, state: dict, verdict: str, reason: str, from_version: str
    ) -> None:
        """Record the episode terminal: history entry + recorder ops
        span + ledger event.  Never raises."""
        svc = self.service
        cfg = self.config
        entry = {
            "version": state.get("version"),
            "from_version": from_version,
            "verdict": verdict,
            "reason": reason,
            "canary_fraction": cfg.canary,
            "canary": self.snapshot(),
            "at": time.time(),  # lint: allow-wall-clock
        }
        try:
            svc._rollout_history.append(entry)
            ledger.event(
                "serve.rollout",
                version=state.get("version"),
                from_version=from_version,
                to_version=state.get("version"),
                verdict=verdict,
                reason=reason,
                canary_fraction=cfg.canary,
            )
            rec = svc.recorder
            if rec is not None:
                rec.ops(
                    "serve.rollout",
                    version=state.get("version"),
                    from_version=from_version,
                    to_version=state.get("version"),
                    verdict=verdict,
                    reason=reason,
                    canary_fraction=cfg.canary,
                )
        except Exception:
            logger.exception("failed to record rollout terminal")


class RollbackGuard:
    """Post-commit bake watch: after a guarded rollout commits, keep
    reading the service's windowed SLO burn rate for ``bake_s`` seconds
    and revert to the prior generation (an ordinary ``service.swap``
    back to the captured source/artifacts) on sustained violation —
    burn above ``bake_max_burn`` for at least ``bake_sustain_s``, with
    at least ``min_samples`` requests in the burn window.  The revert
    quarantines the bad version in the registry and restores CURRENT,
    exactly like a pre-commit rollback.  Stopped by ``close()``, or
    superseded by the next guarded rollout."""

    def __init__(
        self,
        service,
        config: RolloutConfig,
        *,
        from_version: str,
        to_version: str,
        prior_source,
        prior_artifacts: Optional[dict] = None,
        registry=None,
    ):
        self.service = service
        self.config = config
        self.from_version = from_version
        self.to_version = to_version
        self.prior_source = prior_source
        self.prior_artifacts = prior_artifacts
        self.registry = registry
        self._stop = threading.Event()
        self._started = time.monotonic()
        self._outcome: Optional[str] = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-rollout-bake"
        )

    def start(self) -> "RollbackGuard":
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def status(self) -> dict:
        elapsed = time.monotonic() - self._started
        return {
            "phase": "bake",
            "version": self.to_version,
            "from_version": self.from_version,
            "bake_s": self.config.bake_s,
            "elapsed_s": round(elapsed, 3),
            "remaining_s": round(max(0.0, self.config.bake_s - elapsed), 3),
            "outcome": self._outcome,
        }

    def _loop(self) -> None:
        cfg = self.config
        svc = self.service
        end = self._started + cfg.bake_s
        bad_since: Optional[float] = None
        poll = max(cfg.poll_s, min(0.05, cfg.bake_sustain_s / 4.0 or 0.05))
        while not self._stop.wait(poll):
            now = time.monotonic()
            if svc._closing:
                self._outcome = "service_closing"
                return
            if now >= end:
                self._outcome = "bake_passed"
                metrics.inc("serve.rollout.bakes_passed")
                self._clear_guard()
                return
            burn = svc.slo_burn()
            violating = (
                burn is not None
                and burn["burn_rate"] is not None
                and burn["window_requests"] >= cfg.min_samples
                and burn["burn_rate"] > cfg.bake_max_burn
            )
            if violating:
                if bad_since is None:
                    bad_since = now
                elif now - bad_since >= cfg.bake_sustain_s:
                    self._revert(burn)
                    return
            else:
                bad_since = None
        self._outcome = "stopped"

    def _revert(self, burn: dict) -> None:
        """Sustained burn during the bake: swap back to the prior
        generation and quarantine the baked version."""
        svc = self.service
        self._outcome = "rolled_back"
        metrics.inc("serve.rollout.rollbacks")
        metrics.inc("serve.rollout.bake_rollbacks")
        logger.warning(
            "bake guard reverting %r from %s to %s: burn %.2f over %d "
            "requests",
            svc.name,
            self.to_version,
            self.from_version,
            burn["burn_rate"],
            burn["window_requests"],
        )
        try:
            svc.swap(
                self.prior_source,
                version=self.from_version,
                artifacts=self.prior_artifacts,
            )
        except Exception as e:
            self._outcome = "revert_failed"
            logger.exception("bake-guard revert failed: %s", e)
            return
        finally:
            self._clear_guard()
        reg = self.registry
        if reg is not None:
            try:
                if self.to_version in reg.versions():
                    reg.quarantine(
                        self.to_version,
                        reason=(
                            f"bake rollback: burn {burn['burn_rate']:.2f}"
                        ),
                    )
                if (
                    reg.current() == self.to_version
                    and self.from_version in reg.versions()
                ):
                    reg.set_current(self.from_version)
            except Exception as e:
                logger.warning(
                    "bake revert registry bookkeeping failed: %s", e
                )
        entry = {
            "version": self.from_version,
            "from_version": self.to_version,
            "verdict": "rolled_back",
            "reason": "bake_burn",
            "canary_fraction": self.config.canary,
            "at": time.time(),  # lint: allow-wall-clock
        }
        svc._rollout_history.append(entry)
        ledger.event(
            "serve.rollout",
            from_version=self.to_version,
            to_version=self.from_version,
            verdict="rolled_back",
            reason="bake_burn",
        )
        rec = svc.recorder
        if rec is not None:
            rec.ops(
                "serve.rollout",
                from_version=self.to_version,
                to_version=self.from_version,
                verdict="rolled_back",
                reason="bake_burn",
            )

    def _clear_guard(self) -> None:
        svc = self.service
        if svc._rollout_guard is self:
            svc._rollout_guard = None
            svc._rollout_state = None


def guarded_swap(
    service,
    pipeline,
    version: Optional[str] = None,
    artifacts: Optional[dict] = None,
    config: Optional[RolloutConfig] = None,
    registry=None,
) -> dict:
    """Swap with the rollout guard when ``config`` carries a canary
    fraction, or the plain (pinned, byte-for-byte PR-8/11) blue/green
    ``service.swap`` when it does not — the single entry point the
    HTTP admin endpoint and the registry watcher share."""
    if config is None or config.canary is None:
        return service.swap(pipeline, version=version, artifacts=artifacts)
    return CanaryController(service, config, registry=registry).run(
        pipeline, version=version, artifacts=artifacts
    )
