"""Zero-copy async ingress: a selector-driven front end for a
:class:`~keystone_tpu.serve.service.PipelineService`.

PR 15/16 moved replica compute into worker processes and across hosts,
which left the stdlib ``ThreadingHTTPServer`` front end — one thread
plus one JSON body per request — as the serving stack's QPS ceiling
(``tools/serve_bench.py`` measured its per-datum submit loop capping
near 3k QPS on a small host).  This module replaces thread-per-request
with an event loop and per-datum JSON with a batch wire format:

- **Selector loop, not threads.**  Each :class:`AsyncIngress` shard is
  ONE thread running a ``selectors`` poll over its listener and every
  connection it accepted: non-blocking reads into reusable buffers,
  write backlogs drained on writability, a self-pipe to wake the loop
  when a batch's futures resolve on service threads.  With
  ``shards=N`` (and ``SO_REUSEPORT``), N listener loops share one
  port — the kernel load-balances accepts across cores.

- **Binary batch protocol.**  A high-volume client submits a WHOLE
  batch in one CRC-framed message (framing discipline shared with
  ``serve/wire.py``'s v2 stream frames)::

      MAGIC(4)=KSBB | version(1)=1 | body_len(4) | payload_len(4)
      | crc32(4) | JSON body | payload bytes

  The JSON body carries ``op`` (``predict`` | ``ping``), ``count``,
  ``dtype``, ``shape`` (item shape), and optional ``tenant`` /
  ``deadline_ms`` / ``seq``; the payload is the batch's raw row bytes.
  Lengths and CRC ride big-endian; CRC covers body+payload, so a torn
  or damaged frame fails loudly (error frame + connection close, the
  wire-v2 contract) instead of misparsing.  A mid-frame stall past
  ``stall_timeout_s`` condemns the connection — typed error at the
  peer, never a hang.

- **Slab-direct admission.**  A predict frame's payload bytes are
  ``recv_into``'d straight off the socket into a
  :class:`~keystone_tpu.serve.wire.SlabBlock` — a shared-memory slab
  pre-padded to the service's padding bucket.  The whole client batch
  is admitted under ONE ``PipelineService`` lock round
  (:meth:`~keystone_tpu.serve.service.PipelineService.submit_batch`),
  each request row a zero-copy view of the block; when the batch forms
  a flush by itself, the router skips the stack+pad copies and a
  process worker attaches the SAME slab by name (the control frame
  carries ``block.ref``), so payload bytes cross
  admission→router→worker with zero intermediate copies.

- **HTTP stays, on the same port.**  The first bytes of every
  connection are sniffed with ``MSG_PEEK``: the binary magic keeps the
  connection on the event loop; anything else (an HTTP verb) hands the
  socket to the stdlib handler on its own thread
  (:func:`~keystone_tpu.serve.http.handle_http_connection`) — every
  JSON endpoint, status page, and admin verb keeps its one
  implementation, now as the explicit slow path.

Usage::

    front = serve_ingress(svc, port=8000, shards=2)   # started
    ...
    front.stop(); svc.close()

Client side (tests, benches, high-volume feeders)::

    with BinaryClient("127.0.0.1", front.port) as c:
        preds = c.predict(batch)          # (n, ...) float32 in, out

Observability: ``ingress.accepts`` / ``ingress.http_conns`` /
``ingress.bin_conns`` / ``ingress.frames`` / ``ingress.batch_rows`` /
``ingress.frame_errors{kind=...}`` counters, ``ingress.parse_seconds``
and ``ingress.admit_seconds`` histograms (fine sub-ms bounds —
``obs.metrics.INGRESS_TIME_BUCKETS``), and ``ingress.bytes_copied`` —
the JSON path charges every parsed payload byte to it, the binary path
charges zero, so the zero-copy claim is a counter, not a comment.
"""

from __future__ import annotations

import json
import logging
import selectors
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu.obs import metrics
from keystone_tpu.obs.recorder import new_request_id
from keystone_tpu.serve import wire
from keystone_tpu.serve.fleet import FleetUnavailable
from keystone_tpu.serve.http import handle_http_connection
from keystone_tpu.serve.service import (
    Overloaded,
    PipelineService,
    PoisonRequest,
    ServiceClosed,
)
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

#: batch-protocol magic: distinct from the worker wire magic (``KSWP``)
#: so a batch client dialing a worker port (or vice versa) fails the
#: magic check instead of the length parse, and distinct from every
#: HTTP method so protocol sniffing is a 4-byte compare.
BATCH_MAGIC = b"KSBB"
BATCH_VERSION = 1

#: fixed header past magic+version: body_len, payload_len,
#: crc32(body + payload) — all big-endian u32 (the wire-v2 layout)
_HEADER = struct.Struct(">III")
_PREFIX_LEN = len(BATCH_MAGIC) + 1 + _HEADER.size

#: refuse frames past this before allocating anything
DEFAULT_MAX_FRAME_BYTES = wire.DEFAULT_MAX_FRAME_BYTES

#: result-wait bound per batch (mirrors http.py's _RESULT_TIMEOUT_S):
#: the service's own deadline machinery is the real latency bound; this
#: only unsticks a connection if the service is killed under it
_RESULT_TIMEOUT_S = 120.0


def pack_batch_frame(msg: dict, payload: bytes = b"") -> bytes:
    """Serialize one batch-protocol frame (client side, and the
    server's responses): prefix + JSON body + payload."""
    if not isinstance(msg, dict):
        raise wire.WireError(
            f"frame body must be a dict, got {type(msg).__name__}"
        )
    try:
        body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    except (TypeError, ValueError) as e:
        raise wire.WireError(f"unserializable frame body: {e}") from e
    payload = bytes(payload) if not isinstance(payload, memoryview) else payload
    crc = zlib.crc32(payload, zlib.crc32(body)) & 0xFFFFFFFF
    return (
        BATCH_MAGIC
        + bytes([BATCH_VERSION])
        + _HEADER.pack(len(body), len(payload), crc)
        + body
        + bytes(payload)
    )


def recv_batch_frame(
    sock_,
    timeout: Optional[float] = None,
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
) -> Tuple[dict, bytes]:
    """Blocking receive of one batch frame (the CLIENT side — the
    server parses incrementally on its event loop).  Same error
    taxonomy as ``wire.recv_stream_frame``: ``TimeoutError`` when idle,
    ``EOFError`` on a clean close between frames, ``WireError`` on
    anything torn."""
    prefix = wire._recv_exact(sock_, _PREFIX_LEN, timeout)
    if prefix[: len(BATCH_MAGIC)] != BATCH_MAGIC:
        raise wire.WireError("bad batch-frame magic (foreign or torn stream)")
    ver = prefix[len(BATCH_MAGIC)]
    if ver != BATCH_VERSION:
        raise wire.WireError(
            f"batch-frame version {ver} != {BATCH_VERSION} (peer skew)"
        )
    body_len, payload_len, crc = _HEADER.unpack(prefix[len(BATCH_MAGIC) + 1 :])
    if body_len + payload_len > max_frame_bytes:
        raise wire.WireError(
            f"batch frame claims {body_len + payload_len} bytes "
            f"(cap {max_frame_bytes}); refusing before allocation"
        )
    try:
        body = (
            wire._recv_exact(sock_, body_len, wire.MID_FRAME_TIMEOUT_S)
            if body_len
            else b""
        )
        payload = (
            wire._recv_exact(sock_, payload_len, wire.MID_FRAME_TIMEOUT_S)
            if payload_len
            else b""
        )
    except (TimeoutError, EOFError) as e:
        raise wire.WireError(f"truncated batch frame: {e}") from None
    got = zlib.crc32(payload, zlib.crc32(body)) & 0xFFFFFFFF
    if got != crc:
        raise wire.WireError(
            f"batch-frame CRC mismatch (got {got:#010x}, header "
            f"{crc:#010x}) — bytes damaged in flight"
        )
    try:
        msg = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise wire.WireError(f"unparseable batch-frame body: {e}") from e
    if not isinstance(msg, dict):
        raise wire.WireError(
            f"batch-frame body must be a dict, got {type(msg).__name__}"
        )
    return msg, payload


class IngressError(RuntimeError):
    """A server-side refusal relayed through an error frame.  ``kind``
    carries the admission taxonomy (``overloaded`` / ``deadline`` /
    ``poison`` / ``unavailable`` / ``closed`` / ``bad_request`` /
    ``error``) so a client can map it without string-matching."""

    def __init__(
        self,
        message: str,
        kind: str = "error",
        retry_after=None,
        request_ids=None,
    ):
        super().__init__(message)
        self.kind = kind
        self.retry_after = retry_after
        #: the per-row trace ids the refused frame would have served
        #: under (echoed by the server on every typed refusal) — quote
        #: one at ``GET /requestz/<id>`` to see how far it got
        self.request_ids = list(request_ids or [])


# ---------------------------------------------------------------- server


class _Conn:
    """Per-connection state on a shard loop: sniff → binary frame state
    machine (prefix → body → payload-into-slab) → in-flight batches →
    write backlog."""

    SNIFF, PREFIX, BODY, PAYLOAD = "sniff", "prefix", "body", "payload"

    __slots__ = (
        "sock",
        "addr",
        "shard",
        "state",
        "buf",
        "want",
        "msg",
        "body_len",
        "payload_len",
        "crc_expect",
        "crc_run",
        "block",
        "payload_view",
        "payload_got",
        "t_frame_start",
        "t_progress",
        "outq",
        "closing",
    )

    def __init__(self, sock_, addr, shard: int = 0):
        self.sock = sock_
        self.addr = addr
        self.shard = shard
        self.state = _Conn.SNIFF
        self.buf = bytearray()
        self.want = _PREFIX_LEN
        self.msg: Optional[dict] = None
        self.body_len = 0
        self.payload_len = 0
        self.crc_expect = 0
        self.crc_run = 0
        self.block: Optional[wire.SlabBlock] = None
        self.payload_view: Optional[memoryview] = None
        self.payload_got = 0
        self.t_frame_start: Optional[float] = None
        self.t_progress = time.monotonic()
        self.outq: List[memoryview] = []
        self.closing = False  # close once the write backlog drains

    def mid_frame(self) -> bool:
        return self.state in (_Conn.BODY, _Conn.PAYLOAD) or (
            self.state == _Conn.PREFIX and len(self.buf) > 0
        )


class AsyncIngress:
    """The selector-driven front end.  ``shards`` > 1 runs that many
    accept+event loops on one port via ``SO_REUSEPORT`` (one loop per
    core is the intended shape); falls back to a single shard where the
    platform lacks it.  ``stall_timeout_s`` bounds mid-frame silence
    (tests shrink it); ``max_frame_bytes`` bounds any single frame.

    The ingress owns one :class:`~keystone_tpu.serve.wire.SlabPool` for
    admission blocks; its cap follows the service fleet's dispatch slab
    cap so a payload the ingress admits is never refused downstream."""

    def __init__(
        self,
        service: PipelineService,
        host: str = "127.0.0.1",
        port: int = 0,
        shards: int = 1,
        registry=None,
        stall_timeout_s: float = wire.MID_FRAME_TIMEOUT_S,
        max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
        trace_dump_dir: Optional[str] = None,
    ):
        self.service = service
        self.registry = registry
        #: default directory for POST /tracez/dump on the sniffed HTTP
        #: path (None: the endpoint needs an explicit "dir" in its body)
        self.trace_dump_dir = trace_dump_dir
        self.host = host
        self.stall_timeout_s = float(stall_timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        shards = max(1, int(shards))
        if shards > 1 and not hasattr(socket, "SO_REUSEPORT"):
            logger.warning(
                "ingress: SO_REUSEPORT unavailable; running 1 shard"
            )
            shards = 1
        cap = getattr(
            getattr(service, "_pool", None),
            "max_slab_bytes",
            wire.DEFAULT_MAX_SLAB_BYTES,
        )
        self._pool = wire.SlabPool(prefix="ing", max_slab_bytes=cap)
        self._listeners: List[socket.socket] = []
        bound_port = int(port)
        for i in range(shards):
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if shards > 1:
                ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            try:
                ls.bind((host, bound_port))
            except OSError:
                for other in self._listeners:
                    other.close()
                raise
            if bound_port == 0:
                bound_port = ls.getsockname()[1]
            ls.listen(512)
            ls.setblocking(False)
            self._listeners.append(ls)
        self.port = bound_port
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._wakes: List[socket.socket] = []
        #: completed batches pending response assembly, per shard:
        #: (conn, frame_bytes) pushed by future callbacks, drained by
        #: the shard loop after a self-pipe wake
        self._done_q: List[List] = [[] for _ in range(shards)]
        self._done_lock = threading.Lock()
        self._started = False
        metrics.register_buckets(
            "ingress.parse_seconds", metrics.INGRESS_TIME_BUCKETS
        )
        metrics.register_buckets(
            "ingress.admit_seconds", metrics.INGRESS_TIME_BUCKETS
        )

    @property
    def shards(self) -> int:
        return len(self._listeners)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "AsyncIngress":
        if self._started:
            return self
        self._started = True
        for i, ls in enumerate(self._listeners):
            r, w = socket.socketpair()
            r.setblocking(False)
            self._wakes.append(w)
            t = threading.Thread(
                target=self._loop,
                args=(i, ls, r),
                daemon=True,
                name=f"ingress-{i}",
            )
            self._threads.append(t)
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for w in self._wakes:
            try:
                w.send(b"x")
            except OSError:
                pass
        for t in self._threads:
            t.join(5.0)
        for ls in self._listeners:
            try:
                ls.close()
            except OSError:
                pass
        for w in self._wakes:
            try:
                w.close()
            except OSError:
                pass
        self._pool.close()

    def __enter__(self) -> "AsyncIngress":
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stats(self) -> dict:
        return {"shards": self.shards, "pool": self._pool.stats()}

    # --------------------------------------------------------- shard loop
    def _loop(self, shard: int, listener: socket.socket, wake_r) -> None:
        sel = selectors.DefaultSelector()
        sel.register(listener, selectors.EVENT_READ, ("accept", None))
        sel.register(wake_r, selectors.EVENT_READ, ("wake", None))
        conns: Dict[int, _Conn] = {}
        try:
            while not self._stop.is_set():
                timeout = min(0.25, self.stall_timeout_s / 4.0)
                for key, events in sel.select(timeout):
                    kind, conn = key.data
                    try:
                        if kind == "accept":
                            self._accept(sel, listener, conns, shard)
                            continue
                        if kind == "wake":
                            try:
                                wake_r.recv(4096)
                            except (BlockingIOError, OSError):
                                pass
                            continue
                        if events & selectors.EVENT_READ:
                            self._readable(sel, conn, conns)
                        alive = conns.get(conn.sock.fileno()) is conn
                        if alive and (
                            conn.outq or events & selectors.EVENT_WRITE
                        ):
                            self._writable(sel, conn, conns)
                    except (OSError, ValueError) as e:
                        if conn is not None:
                            logger.debug("ingress: conn died: %s", e)
                            self._drop(sel, conn, conns)
                    except Exception:
                        # one bad connection must never take the shard
                        # loop (and with it the listener plus every
                        # other conn) down: drop the offender, count
                        # it, keep serving
                        metrics.inc(
                            "ingress.frame_errors", kind="internal"
                        )
                        logger.exception(
                            "ingress: internal error on conn %s",
                            getattr(conn, "addr", None),
                        )
                        if conn is not None:
                            self._drop(sel, conn, conns)
                # response frames assembled by future callbacks
                self._flush_done(sel, shard, conns)
                # condemn mid-frame stalls: a peer that started a frame
                # and went silent holds a slab and a connection slot —
                # typed failure at the peer (RST/EOF), never a hang here
                now = time.monotonic()
                for conn in list(conns.values()):
                    if (
                        conn.mid_frame()
                        and now - conn.t_progress > self.stall_timeout_s
                    ):
                        metrics.inc(
                            "ingress.frame_errors", kind="mid_frame_stall"
                        )
                        logger.debug(
                            "ingress: condemning stalled conn %s", conn.addr
                        )
                        self._drop(sel, conn, conns)
        finally:
            for conn in list(conns.values()):
                self._drop(sel, conn, conns)
            sel.close()

    def _accept(self, sel, listener, conns, shard: int) -> None:
        for _ in range(64):  # bounded accept burst per readiness
            try:
                sock_, addr = listener.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            metrics.inc("ingress.accepts")
            sock_.setblocking(False)
            try:
                sock_.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock_, addr, shard)
            conns[sock_.fileno()] = conn
            sel.register(sock_, selectors.EVENT_READ, (None, conn))

    def _drop(self, sel, conn: _Conn, conns) -> None:
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        for fd, c in list(conns.items()):
            if c is conn:
                conns.pop(fd, None)
        self._abandon_frame(conn)
        conn.closing = True
        try:
            conn.sock.close()
        except OSError:
            pass

    def _abandon_frame(self, conn: _Conn) -> None:
        """Free a partially-read frame's slab (the conn is dying)."""
        conn.payload_view = None
        if conn.block is not None:
            conn.block.close()
            conn.block = None

    # ----------------------------------------------------------- reading
    def _readable(self, sel, conn: _Conn, conns) -> None:
        if conn.closing:
            return  # condemned: drain the write backlog, read no more
        if conn.state == _Conn.SNIFF:
            self._sniff(sel, conn, conns)
            return
        # drain what's available, frame by frame
        for _ in range(32):
            if conn.state == _Conn.PAYLOAD:
                if not self._read_payload(sel, conn, conns):
                    return
            else:
                try:
                    chunk = conn.sock.recv(
                        min(conn.want - len(conn.buf), 1 << 20)
                    )
                except (BlockingIOError, InterruptedError):
                    return
                except (ConnectionResetError, OSError):
                    self._drop(sel, conn, conns)
                    return
                if not chunk:
                    if conn.mid_frame():
                        metrics.inc(
                            "ingress.frame_errors", kind="truncated"
                        )
                    self._drop(sel, conn, conns)
                    return
                conn.t_progress = time.monotonic()
                if conn.t_frame_start is None:
                    conn.t_frame_start = conn.t_progress
                conn.buf.extend(chunk)
                if len(conn.buf) < conn.want:
                    return
                if conn.state == _Conn.PREFIX:
                    if not self._parse_prefix(sel, conn, conns):
                        return
                elif conn.state == _Conn.BODY:
                    if not self._parse_body(sel, conn, conns):
                        return

    def _sniff(self, sel, conn: _Conn, conns) -> None:
        """Peek the first bytes without consuming: binary magic stays
        on the loop, anything else becomes a delegated HTTP thread.

        A strict PREFIX of the magic is consumed into the frame buffer
        and the conn committed to the binary parser right away: peeked-
        but-unread bytes would make the level-triggered selector report
        the socket readable every iteration (a peer sending ``b"KS"``
        and stalling would spin this loop at full CPU), and no HTTP
        method shares a first byte with the magic, so committing early
        loses nothing — a stream that diverges after the prefix fails
        the magic check with a typed error, and a staller is now
        mid-frame (``PREFIX`` with buffered bytes) so the stall sweep
        condemns it."""
        try:
            peek = conn.sock.recv(len(BATCH_MAGIC), socket.MSG_PEEK)
        except (BlockingIOError, InterruptedError):
            return
        except (ConnectionResetError, OSError):
            self._drop(sel, conn, conns)
            return
        if not peek:
            self._drop(sel, conn, conns)
            return
        if BATCH_MAGIC.startswith(peek):
            metrics.inc("ingress.bin_conns")
            try:
                got = conn.sock.recv(len(peek))
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionResetError, OSError):
                self._drop(sel, conn, conns)
                return
            if not got:
                self._drop(sel, conn, conns)
                return
            conn.buf.extend(got)
            conn.t_progress = time.monotonic()
            conn.t_frame_start = conn.t_progress
            conn.state = _Conn.PREFIX
            conn.want = _PREFIX_LEN
            self._readable(sel, conn, conns)
            return
        # HTTP (or anything else): hand the UNCONSUMED socket to the
        # stdlib handler on its own thread — the threaded slow path
        metrics.inc("ingress.http_conns")
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conns.pop(conn.sock.fileno(), None)
        sock_, addr = conn.sock, conn.addr
        sock_.setblocking(True)
        threading.Thread(
            target=handle_http_connection,
            args=(
                sock_,
                addr,
                self.service,
                self.registry,
                self.trace_dump_dir,
            ),
            daemon=True,
            name="ingress-http",
        ).start()

    def _parse_prefix(self, sel, conn: _Conn, conns) -> bool:
        buf = bytes(conn.buf)
        conn.buf.clear()
        if buf[: len(BATCH_MAGIC)] != BATCH_MAGIC:
            self._frame_error(sel, conn, conns, "bad_magic", "bad frame magic")
            return False
        ver = buf[len(BATCH_MAGIC)]
        if ver != BATCH_VERSION:
            self._frame_error(
                sel,
                conn,
                conns,
                "version_skew",
                f"batch-frame version {ver} != {BATCH_VERSION}",
            )
            return False
        body_len, payload_len, crc = _HEADER.unpack(buf[len(BATCH_MAGIC) + 1 :])
        if body_len + payload_len > self.max_frame_bytes:
            self._frame_error(
                sel,
                conn,
                conns,
                "oversize",
                f"frame claims {body_len + payload_len} bytes "
                f"(cap {self.max_frame_bytes})",
            )
            return False
        conn.body_len, conn.payload_len, conn.crc_expect = (
            body_len,
            payload_len,
            crc,
        )
        conn.crc_run = 0
        conn.state = _Conn.BODY
        conn.want = body_len
        if body_len == 0:
            return self._parse_body(sel, conn, conns)
        return True

    def _parse_body(self, sel, conn: _Conn, conns) -> bool:
        body = bytes(conn.buf)
        conn.buf.clear()
        conn.crc_run = zlib.crc32(body)
        try:
            msg = json.loads(body.decode("utf-8"))
            if not isinstance(msg, dict):
                raise ValueError("frame body must be a JSON object")
        except (UnicodeDecodeError, json.JSONDecodeError, ValueError) as e:
            self._frame_error(
                sel, conn, conns, "bad_body", f"unparseable frame body: {e}"
            )
            return False
        conn.msg = msg
        op = msg.get("op")
        if op == "ping":
            if conn.payload_len:
                self._frame_error(
                    sel, conn, conns, "bad_body", "ping carries no payload"
                )
                return False
            if conn.crc_run != conn.crc_expect:
                self._crc_mismatch(sel, conn, conns)
                return False
            self._frame_done(conn)
            self._respond(
                conn,
                {
                    "op": "pong",
                    "seq": msg.get("seq"),
                    "shards": self.shards,
                    "version": self.service.version,
                },
            )
            return True
        if op != "predict":
            self._frame_error(
                sel, conn, conns, "bad_op", f"unknown op {op!r}"
            )
            return False
        try:
            count = int(msg["count"])
            dtype = np.dtype(str(msg["dtype"]))
            item_shape = tuple(int(d) for d in msg.get("shape") or ())
            if count < 1:
                raise ValueError(f"count must be >= 1, got {count}")
            # wire dtypes are numeric scalars only: an object dtype
            # over the slab would turn raw socket bytes into PyObject
            # pointers the moment anything dereferences the array
            if dtype.hasobject or dtype.kind not in "biufc":
                raise ValueError(
                    f"dtype {dtype.str!r} not admissible on the wire "
                    "(numeric kinds biufc only)"
                )
            # overflow-safe Python-int math: a crafted dim must fail
            # typed here, not wrap through a fixed-width product into
            # passing the payload-length consistency check below
            row_elems = 1
            for d in item_shape:
                if d < 1:
                    raise ValueError(
                        f"item shape {item_shape} has a dim < 1"
                    )
                row_elems *= d
                if row_elems * dtype.itemsize > self.max_frame_bytes:
                    raise ValueError(
                        f"item shape {item_shape} exceeds the "
                        f"{self.max_frame_bytes}-byte frame cap"
                    )
        except (KeyError, TypeError, ValueError) as e:
            self._frame_error(
                sel, conn, conns, "bad_body", f"bad predict header: {e}"
            )
            return False
        expect = count * row_elems * dtype.itemsize
        if expect != conn.payload_len:
            self._frame_error(
                sel,
                conn,
                conns,
                "bad_body",
                f"payload carries {conn.payload_len} bytes but header "
                f"claims {count}x{item_shape}:{dtype.str} = {expect}",
            )
            return False
        svc = self.service
        # pre-pad to the service's padding bucket so a flush of this
        # block needs no re-pad copy; a batch wider than max_batch
        # spans flushes anyway, so it rides unpadded
        padded = (
            svc.bucket_for(count) if count <= svc.max_batch else count
        )
        try:
            conn.block = wire.alloc_block(
                self._pool, count, item_shape, dtype, padded_rows=padded
            )
        except wire.PayloadTooLarge as e:
            # typed refusal, connection stays healthy: the frame's
            # payload still has to be drained... but draining an
            # oversize payload is exactly the DoS the cap refuses, so
            # condemn the connection instead
            self._frame_error(sel, conn, conns, "too_large", str(e))
            return False
        conn.payload_view = memoryview(conn.block.array).cast("B")[
            : conn.payload_len
        ]
        conn.payload_got = 0
        conn.state = _Conn.PAYLOAD
        return self._read_payload(sel, conn, conns)

    def _read_payload(self, sel, conn: _Conn, conns) -> bool:
        """Non-blocking recv straight into the slab-backed block (the
        zero-copy read); returns False when the caller's read loop must
        stop (would-block, dropped, or frame finished via dispatch)."""
        while conn.payload_got < conn.payload_len:
            try:
                n = conn.sock.recv_into(
                    conn.payload_view[conn.payload_got :]
                )
            except (BlockingIOError, InterruptedError):
                return False
            except (ConnectionResetError, OSError):
                self._drop(sel, conn, conns)
                return False
            if n == 0:
                metrics.inc("ingress.frame_errors", kind="truncated")
                self._drop(sel, conn, conns)
                return False
            conn.crc_run = zlib.crc32(
                conn.payload_view[conn.payload_got : conn.payload_got + n],
                conn.crc_run,
            )
            conn.payload_got += n
            conn.t_progress = time.monotonic()
        conn.payload_view = None
        if (conn.crc_run & 0xFFFFFFFF) != conn.crc_expect:
            self._crc_mismatch(sel, conn, conns)
            return False
        t0 = conn.t_frame_start
        if t0 is not None:
            metrics.observe("ingress.parse_seconds", time.monotonic() - t0)
        metrics.inc("ingress.frames")
        self._dispatch(conn)
        self._frame_done(conn)
        return True

    def _frame_done(self, conn: _Conn) -> None:
        """Reset the state machine for the next frame on this conn."""
        conn.state = _Conn.PREFIX
        conn.want = _PREFIX_LEN
        conn.buf.clear()
        conn.msg = None
        conn.block = None  # ownership moved to the batch (or closed)
        conn.payload_view = None
        conn.t_frame_start = None

    def _crc_mismatch(self, sel, conn, conns) -> None:
        self._frame_error(
            sel,
            conn,
            conns,
            "crc_mismatch",
            "batch-frame CRC mismatch — bytes damaged in flight",
        )

    def _frame_error(self, sel, conn: _Conn, conns, kind: str, msg: str) -> None:
        """A FRAMING violation: the byte stream itself can no longer be
        trusted, so answer with a typed error frame and condemn the
        connection (the wire-v2 discipline).  Admission refusals — the
        stream is fine, the REQUEST was refused — go through
        :meth:`_error_frame` and keep the connection."""
        metrics.inc("ingress.frame_errors", kind=kind)
        self._abandon_frame(conn)
        self._respond(
            conn, {"op": "error", "ok": False, "kind": kind, "error": msg}
        )
        conn.closing = True  # close once the error frame drains

    # -------------------------------------------------------- dispatching
    @staticmethod
    def _request_ids_for(msg: dict, count: int) -> List[str]:
        """Request-id parity with the HTTP front end: honor the
        client's ``request_id`` body key, else mint one; a multi-row
        frame fans out ``<rid>/<i>`` sub-ids so each row's causal chain
        resolves individually at ``/requestz/<id>``."""
        rid = msg.get("request_id")
        rid = (str(rid).strip() if rid is not None else "") or new_request_id()
        if count == 1:
            return [rid]
        return [f"{rid}/{i}" for i in range(count)]

    def _dispatch(self, conn: _Conn) -> None:
        """Admit one complete predict frame: the whole block under one
        service lock round; futures resolve on service threads and the
        LAST one assembles the response and wakes this shard's loop."""
        msg, block = conn.msg, conn.block
        seq = msg.get("seq")
        deadline_ms = msg.get("deadline_ms")
        deadline = (
            None if deadline_ms is None else float(deadline_ms) / 1000.0
        )
        tenant = msg.get("tenant")
        tenant = None if tenant is None else str(tenant)
        svc = self.service
        rids = self._request_ids_for(msg, block.count)
        rec = svc.recorder
        if rec is not None:
            for r in rids:
                rec.annotate(r, "bin.ingress", rows=block.count)
        t0 = time.monotonic()
        try:
            futs = svc.submit_batch(
                block, deadline=deadline, request_ids=rids, tenant=tenant
            )
        except BaseException as e:
            block.close()
            self._enqueue_response(conn, self._error_frame(seq, e, rids))
            return
        metrics.observe("ingress.admit_seconds", time.monotonic() - t0)
        metrics.inc("ingress.batch_rows", len(futs))
        # hold the slab until every future resolves (dispatch may read
        # it up to that point: hedges, crash requeues, bisection)
        block.retain(len(futs))
        for f in futs:
            f.add_done_callback(block.release_one)
        state = {"left": len(futs), "lock": threading.Lock()}

        def on_done(_f):
            with state["lock"]:
                state["left"] -= 1
                if state["left"]:
                    return
            self._finish_batch(conn, seq, futs, rids)

        for f in futs:
            f.add_done_callback(on_done)

    def _finish_batch(self, conn: _Conn, seq, futs, rids=None) -> None:
        """All futures of one batch resolved (runs on a service
        thread): assemble the response frame, enqueue, wake the loop."""
        try:
            rows = [f.result(timeout=0) for f in futs]
        except BaseException as e:
            self._enqueue_response(conn, self._error_frame(seq, e, rids))
            return
        try:
            out = np.ascontiguousarray(np.stack(rows))
            frame = pack_batch_frame(
                {
                    "op": "result",
                    "ok": True,
                    "seq": seq,
                    "count": int(out.shape[0]),
                    "dtype": out.dtype.str,
                    "shape": list(out.shape[1:]),
                    "request_ids": list(rids or []),
                },
                out.tobytes(),
            )
        except BaseException as e:  # heterogeneous rows, pack failure
            self._enqueue_response(conn, self._error_frame(seq, e, rids))
            return
        self._enqueue_response(conn, frame)

    @staticmethod
    def _error_frame(seq, e: BaseException, rids=None) -> bytes:
        if isinstance(e, Overloaded):
            kind = "overloaded"
        elif isinstance(e, guard.DeadlineExceeded):
            kind = "deadline"
        elif isinstance(e, PoisonRequest):
            kind = "poison"
        elif isinstance(e, FleetUnavailable):
            kind = "unavailable"
        elif isinstance(e, (ServiceClosed,)):
            kind = "closed"
        elif isinstance(e, guard.CircuitOpenError):
            kind = "overloaded"
        elif isinstance(e, (TypeError, ValueError)):
            kind = "bad_request"
        else:
            kind = "error"
        body = {
            "op": "error",
            "ok": False,
            "seq": seq,
            "kind": kind,
            "error": f"{type(e).__name__}: {e}",
        }
        if rids:
            # every typed refusal echoes the ids the frame would have
            # served under — the id a client quotes at /requestz/<id>
            # must exist whether the request succeeded or was refused
            body["request_ids"] = list(rids)
        retry = getattr(e, "retry_after_seconds", None)
        if retry is not None:
            body["retry_after_seconds"] = float(retry)
        return pack_batch_frame(body)

    # ----------------------------------------------------------- writing
    def _respond(self, conn: _Conn, msg: dict, payload: bytes = b"") -> None:
        """Queue a response frame assembled ON the loop thread."""
        self._enqueue_write(conn, pack_batch_frame(msg, payload))

    def _enqueue_response(self, conn: _Conn, frame: bytes) -> None:
        """Queue a response assembled OFF the loop thread (future
        callbacks): park it on the conn's shard done queue and wake that
        shard's selector via the self-pipe.  Connections are pinned to
        the shard that accepted them, so the owning loop is the only
        thread that ever touches the conn's write state."""
        with self._done_lock:
            self._done_q[conn.shard].append((conn, frame))
        try:
            self._wakes[conn.shard].send(b"x")
        except (OSError, IndexError):
            pass

    def _flush_done(self, sel, shard: int, conns) -> None:
        with self._done_lock:
            batch, self._done_q[shard] = self._done_q[shard], []
        for conn, frame in batch:
            # identity check: the frame's conn may have died (and its fd
            # been reused) while the batch was in flight — drop silently
            if conns.get(conn.sock.fileno()) is not conn:
                continue
            self._enqueue_write(conn, frame)
            self._writable(sel, conn, conns)

    def _enqueue_write(self, conn: _Conn, frame: bytes) -> None:
        conn.outq.append(memoryview(frame))

    def _writable(self, sel, conn: _Conn, conns) -> None:
        while conn.outq:
            mv = conn.outq[0]
            try:
                n = conn.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                break
            except (BrokenPipeError, ConnectionResetError, OSError):
                self._drop(sel, conn, conns)
                return
            if n < len(mv):
                conn.outq[0] = mv[n:]
                break
            conn.outq.pop(0)
        events = selectors.EVENT_READ
        if conn.outq:
            events |= selectors.EVENT_WRITE
        try:
            sel.modify(conn.sock, events, (None, conn))
        except (KeyError, ValueError):
            return
        if conn.closing and not conn.outq:
            self._drop(sel, conn, conns)


# ---------------------------------------------------------------- client


class BinaryClient:
    """Blocking batch-protocol client (benches, tests, high-volume
    feeders).  One connection, strict request/response; thread-safe via
    an internal lock — run several clients for pipelined load.

    ``predict`` submits a whole ``(n, ...)`` batch in one frame and
    returns the ``(n, ...)`` predictions; server refusals raise
    :class:`IngressError` with the admission taxonomy in ``.kind``."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = _RESULT_TIMEOUT_S,
        connect_timeout: float = 10.0,
    ):
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self._seq = 0
        #: per-row trace ids of the most recent successful predict
        self.last_request_ids: List[str] = []
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # readers wait via select (wire._recv_exact); the socket's own
        # timeout budgets sendall, the wire.py discipline
        self.sock.settimeout(wire.SEND_TIMEOUT_S)

    def _roundtrip(self, msg: dict, payload: bytes = b"") -> Tuple[dict, bytes]:
        with self._lock:
            self._seq += 1
            msg = dict(msg, seq=self._seq)
            self.sock.sendall(pack_batch_frame(msg, payload))
            reply, rpayload = recv_batch_frame(self.sock, timeout=self.timeout)
        if reply.get("op") == "error" or reply.get("ok") is False:
            raise IngressError(
                str(reply.get("error") or "server error"),
                kind=str(reply.get("kind") or "error"),
                retry_after=reply.get("retry_after_seconds"),
                request_ids=reply.get("request_ids"),
            )
        return reply, rpayload

    def ping(self) -> dict:
        reply, _ = self._roundtrip({"op": "ping"})
        return reply

    def predict(
        self,
        batch: np.ndarray,
        tenant: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> np.ndarray:
        """``request_id``: the trace identity for this frame (else the
        server mints one) — per-row ids come back on the reply and are
        kept on :attr:`last_request_ids`; a refusal carries them on
        ``IngressError.request_ids``."""
        batch = np.ascontiguousarray(batch)
        if batch.ndim < 1:
            raise ValueError("batch must be (n, ...) — at least 1-D")
        msg = {
            "op": "predict",
            "count": int(batch.shape[0]),
            "dtype": batch.dtype.str,
            "shape": list(batch.shape[1:]),
        }
        if request_id is not None:
            msg["request_id"] = str(request_id)
        if tenant is not None:
            msg["tenant"] = str(tenant)
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        reply, payload = self._roundtrip(msg, batch.tobytes())
        self.last_request_ids = list(reply.get("request_ids") or [])
        dtype = np.dtype(reply["dtype"])
        shape = (int(reply["count"]),) + tuple(
            int(d) for d in reply.get("shape") or ()
        )
        return np.frombuffer(payload, dtype=dtype).reshape(shape).copy()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "BinaryClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_ingress(
    service: PipelineService,
    host: str = "127.0.0.1",
    port: int = 8000,
    shards: int = 1,
    registry=None,
    **kw,
) -> AsyncIngress:
    """Stand up (and start) the async ingress for ``service``; returns
    the started :class:`AsyncIngress` (``.port`` for ephemeral binds,
    ``.stop()`` to shut down).  HTTP/JSON clients keep working on the
    same port (sniffed, delegated to ``serve/http.py``); binary batch
    clients get the zero-copy path."""
    return AsyncIngress(
        service, host=host, port=port, shards=shards, registry=registry, **kw
    ).start()
