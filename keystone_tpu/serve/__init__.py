"""Online serving: dynamic micro-batching pipeline endpoint with
admission control (the Clipper-layer over frozen keystone_tpu
pipelines; see ``serve/service.py`` for the design).

Deliberately NOT imported by ``keystone_tpu/__init__`` — the offline
library import path (and every traced program) is byte-identical
whether or not a service exists in the process (pinned by
tests/test_serve.py).
"""

from keystone_tpu.serve.http import HttpFrontend, serve_http  # noqa: F401
from keystone_tpu.serve.service import (  # noqa: F401
    Overloaded,
    PipelineService,
    ServiceClosed,
    default_buckets,
    serve,
)

__all__ = [
    "HttpFrontend",
    "Overloaded",
    "PipelineService",
    "ServiceClosed",
    "default_buckets",
    "serve",
    "serve_http",
]
