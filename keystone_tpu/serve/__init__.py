"""Online serving: dynamic micro-batching pipeline endpoint with
admission control (the Clipper-layer over frozen keystone_tpu
pipelines; see ``serve/service.py`` for the design), scaled out as a
replica fleet with versioned live model hot-swap (``serve/fleet.py``,
``serve/registry.py``).

Deliberately NOT imported by ``keystone_tpu/__init__`` — the offline
library import path (and every traced program) is byte-identical
whether or not a service exists in the process (pinned by
tests/test_serve.py).
"""

from keystone_tpu.serve.autoscale import (  # noqa: F401
    AutoscalePolicy,
    Autoscaler,
    Signals,
)
from keystone_tpu.serve.fleet import (  # noqa: F401
    FleetUnavailable,
    Replica,
    ReplicaPool,
    ReplicaSupervisor,
)
from keystone_tpu.serve.procfleet import (  # noqa: F401
    ProcessReplica,
    RemoteApplier,
    WorkerCrashed,
    WorkerHandle,
    WorkerSpawnError,
)
from keystone_tpu.serve.net import (  # noqa: F401
    ConnectRetriesExhausted,
    NetReplica,
    NetWorkerHandle,
    WorkerListener,
    run_worker,
)
from keystone_tpu.serve.http import HttpFrontend, serve_http  # noqa: F401
from keystone_tpu.serve.ingress import (  # noqa: F401
    AsyncIngress,
    BinaryClient,
    IngressError,
    serve_ingress,
)
from keystone_tpu.serve.registry import (  # noqa: F401
    ModelRegistry,
    RegistryError,
    RegistryWatcher,
)
from keystone_tpu.serve.rollout import (  # noqa: F401
    CanaryController,
    RollbackGuard,
    RolloutConfig,
    guarded_swap,
)
from keystone_tpu.serve.service import (  # noqa: F401
    Overloaded,
    PipelineService,
    PoisonRequest,
    ServiceClosed,
    default_buckets,
    serve,
)
from keystone_tpu.serve.telemetry import (  # noqa: F401
    ClockSync,
    FleetTelemetry,
    WorkerTelemetry,
    clamp_span,
)
from keystone_tpu.serve.tenants import (  # noqa: F401
    MultiTenantApplier,
    MultiTenantService,
    UnknownTenant,
    serve_multi,
)

__all__ = [
    "AsyncIngress",
    "AutoscalePolicy",
    "Autoscaler",
    "BinaryClient",
    "CanaryController",
    "ClockSync",
    "ConnectRetriesExhausted",
    "FleetTelemetry",
    "FleetUnavailable",
    "HttpFrontend",
    "IngressError",
    "NetReplica",
    "NetWorkerHandle",
    "ProcessReplica",
    "RemoteApplier",
    "WorkerListener",
    "Signals",
    "WorkerCrashed",
    "WorkerHandle",
    "WorkerSpawnError",
    "ModelRegistry",
    "MultiTenantApplier",
    "MultiTenantService",
    "Overloaded",
    "PipelineService",
    "PoisonRequest",
    "Replica",
    "ReplicaPool",
    "ReplicaSupervisor",
    "RegistryError",
    "RegistryWatcher",
    "RollbackGuard",
    "RolloutConfig",
    "ServiceClosed",
    "UnknownTenant",
    "WorkerTelemetry",
    "clamp_span",
    "default_buckets",
    "guarded_swap",
    "run_worker",
    "serve",
    "serve_http",
    "serve_ingress",
    "serve_multi",
]
