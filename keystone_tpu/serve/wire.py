"""Worker wire protocol: control frames + shared-memory payload slabs.

The process fleet (``serve/procfleet.py``) moves batch payloads between
the router process and its worker processes.  Pickling every array over
a pipe would put the serialization cost back on the hot path the whole
promotion exists to remove, so the protocol splits control from data:

- **Control frames** — tiny JSON dicts (an op, a slab reference, an
  error classification), length-delimited by the underlying
  ``multiprocessing.connection`` transport and framed here with a magic
  + version prefix so a torn or foreign message fails loudly
  (:func:`pack_frame` / :func:`unpack_frame`).  Arrays NEVER ride a
  frame — a frame carries at most a :func:`slab reference <write_array>`.
- **Payload slabs** — ``multiprocessing.shared_memory`` segments sized
  to power-of-two classes that mirror the service's padding buckets
  (every flush is padded to a bucket, so slab sizes are as finite as
  the compiled program shapes).  Dispatch is one ``memcpy`` into a
  slab; the receiving side attaches by name (cached — attach is a
  syscall) and reads a NumPy view.  :class:`SlabPool` owns creation,
  reuse, and unlink; :class:`SlabAttacher` is the read side.

Reuse discipline: the protocol is strict request/response with ONE
in-flight request per worker (the parent serializes on a per-worker
lock), so a request slab may be reused as soon as the response frame
arrives, and a response slab as soon as the next request is sent — no
acknowledgement round-trip.  :meth:`SlabPool.acquire` refuses payloads
past ``max_slab_bytes`` with :class:`PayloadTooLarge` (a typed refusal
at dispatch beats an OOM in a worker that every tenant shares).

**Cross-host framing (wire v2).**  Off-box peers (``serve/net.py``)
cannot share memory, so the same control-frame discipline is carried
over a raw TCP socket with the payload bytes INLINE::

    MAGIC(4) | version(1)=2 | body_len(4) | payload_len(4) | crc32(4)
    | JSON body | payload bytes

Lengths and the CRC ride big-endian; the CRC covers body+payload so a
corrupted or torn stream frame fails loudly (:class:`WireError`) at
the receiver instead of misparsing — the connection is condemned and
the worker replaced, exactly the slab-path discipline.  TCP gives no
message boundaries, so the length prefix is load-bearing here where
``multiprocessing.connection`` provided it for free.  A stream frame
carries at most one array payload (:func:`array_payload` /
:func:`payload_array`); the strict one-in-flight rule is unchanged.

This module is transport only — no JAX, no pipeline imports — so both
the router and a freshly spawned worker can import it before paying
the accelerator-runtime import.
"""

from __future__ import annotations

import json
import select
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

#: frame prefix: magic + protocol version.  A frame from a different
#: keystone version (rolling restart skew) or a stray writer fails the
#: unpack instead of silently misparsing.
MAGIC = b"KSWP"
VERSION = 1

#: the socket (cross-host) framing version.  Distinct from the slab
#: protocol's VERSION: the two transports can rev independently, and a
#: v1 slab frame accidentally written to a socket fails the version
#: check instead of the length parse.
SOCKET_VERSION = 2

#: stream-frame fixed header past magic+version: body length, payload
#: length, crc32(body + payload) — all big-endian u32
_STREAM_HEADER = struct.Struct(">III")
_STREAM_PREFIX_LEN = len(MAGIC) + 1 + _STREAM_HEADER.size

#: refuse stream frames past this before allocating (a garbage length
#: field must not turn into a multi-GiB recv buffer)
DEFAULT_MAX_FRAME_BYTES = 1 << 28  # 256 MiB

#: once the first byte of a frame arrives, the rest must follow within
#: this window — a peer that stalls mid-frame holds the channel torn,
#: and a torn channel means replace-the-worker, not wait-forever
MID_FRAME_TIMEOUT_S = 30.0

#: the SEND direction's budget on a shared stream socket.  The socket
#: object's timeout caps the TOTAL duration of ``sendall`` (Python
#: 3.5+ semantics), so it must be generous enough for a full-size frame
#: over a congested cross-host link — and it is set ONCE at connection
#: setup, never by the read side: reader threads wait with ``select``
#: (:func:`_wait_readable`) precisely so their short idle poll cannot
#: shrink a concurrent ``sendall``'s budget out from under the sender.
SEND_TIMEOUT_S = 60.0

#: slab size classes are powers of two from this floor — small enough
#: that a probe request wastes little, large enough that the common
#: bucket sizes land in few classes
MIN_SLAB_BYTES = 1 << 16  # 64 KiB

#: refuse single payloads past this (acquire raises PayloadTooLarge)
DEFAULT_MAX_SLAB_BYTES = 1 << 28  # 256 MiB


class WireError(RuntimeError):
    """A malformed control frame: wrong magic, wrong version, truncated
    or non-JSON body.  Deliberately loud — a torn frame means the
    control channel itself is unreliable and the worker must be
    replaced, not retried."""


class PayloadTooLarge(ValueError):
    """The payload exceeds the slab cap.  A ``ValueError`` on purpose:
    it is the REQUEST's fault (the 400 family at HTTP) and resubmitting
    it unchanged will fail again — it must not charge replica breakers
    or trip the supervisor."""


def pack_frame(msg: dict) -> bytes:
    """Serialize one control frame: ``MAGIC + version byte + JSON``.
    Frames carry only JSON-native scalars/lists/dicts (slab references,
    never arrays)."""
    if not isinstance(msg, dict):
        raise WireError(f"frame body must be a dict, got {type(msg).__name__}")
    try:
        body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    except (TypeError, ValueError) as e:
        raise WireError(f"unserializable frame body: {e}") from e
    return MAGIC + bytes([VERSION]) + body


def unpack_frame(data: bytes) -> dict:
    """Parse one control frame; raises :class:`WireError` on anything
    that is not a well-formed frame of THIS protocol version."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise WireError(f"frame must be bytes, got {type(data).__name__}")
    data = bytes(data)
    if len(data) < len(MAGIC) + 1:
        raise WireError(f"truncated frame ({len(data)} bytes)")
    if data[: len(MAGIC)] != MAGIC:
        raise WireError("bad frame magic (foreign or torn message)")
    ver = data[len(MAGIC)]
    if ver != VERSION:
        raise WireError(f"frame version {ver} != {VERSION} (worker skew)")
    try:
        msg = json.loads(data[len(MAGIC) + 1 :].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparseable frame body: {e}") from e
    if not isinstance(msg, dict):
        raise WireError(f"frame body must be a dict, got {type(msg).__name__}")
    return msg


def slab_class(nbytes: int) -> int:
    """The size class a payload of ``nbytes`` rides: the smallest power
    of two >= max(nbytes, MIN_SLAB_BYTES) — mirroring the padding-bucket
    discipline so slab shapes are as finite as program shapes."""
    n = max(int(nbytes), MIN_SLAB_BYTES)
    return 1 << (n - 1).bit_length()


class Slab:
    """One owned shared-memory segment (created by a :class:`SlabPool`;
    the remote side attaches by :attr:`name`)."""

    __slots__ = ("shm", "capacity")

    def __init__(self, shm, capacity: int):
        self.shm = shm
        self.capacity = int(capacity)

    @property
    def name(self) -> str:
        return self.shm.name

    @property
    def buf(self):
        return self.shm.buf


class SlabPool:
    """Creator-side slab manager: acquire/release with reuse across
    size classes (a released 1 MiB slab serves a later 256 KiB payload
    — ``acquire`` hands out the smallest free slab that fits before
    creating a new one).  Owned slabs are unlinked at :meth:`close`.
    Thread-safe; creation is rare after warm-up (the working set is one
    request slab + one response slab per worker)."""

    def __init__(
        self,
        prefix: str = "ksw",
        max_slab_bytes: int = DEFAULT_MAX_SLAB_BYTES,
    ):
        import re

        # the prefix lands in the POSIX shm name (debuggability: ls
        # /dev/shm attributes every segment to its pool/worker); keep
        # it name-safe and short
        self.prefix = re.sub(r"[^A-Za-z0-9_]", "_", str(prefix))[:48]
        self.max_slab_bytes = int(max_slab_bytes)
        self._lock = threading.Lock()
        self._free: List[Slab] = []
        self._all: List[Slab] = []
        self._closed = False
        self._seq = 0
        self.created = 0
        self.reused = 0

    def acquire(self, nbytes: int) -> Slab:
        nbytes = int(nbytes)
        if nbytes > self.max_slab_bytes:
            raise PayloadTooLarge(
                f"payload of {nbytes} bytes exceeds the slab cap "
                f"({self.max_slab_bytes}); refused at dispatch"
            )
        cls = slab_class(nbytes)
        with self._lock:
            if self._closed:
                raise WireError("slab pool is closed")
            fits = [s for s in self._free if s.capacity >= cls]
            if fits:
                slab = min(fits, key=lambda s: s.capacity)
                self._free.remove(slab)
                self.reused += 1
                return slab
        import os

        from multiprocessing import shared_memory

        shm = None
        while shm is None:
            with self._lock:
                self._seq += 1
                name = f"{self.prefix}_{os.getpid()}_{self._seq}"
            try:
                shm = shared_memory.SharedMemory(
                    name=name, create=True, size=cls
                )
            except FileExistsError:
                continue  # a stale segment from a crashed prior run
        slab = Slab(shm, cls)
        with self._lock:
            self._all.append(slab)
            self.created += 1
        return slab

    def release(self, slab: Slab) -> None:
        with self._lock:
            if self._closed:
                self._destroy(slab)
                return
            if slab not in self._free:
                self._free.append(slab)

    @staticmethod
    def _destroy(slab: Slab) -> None:
        try:
            slab.shm.close()
            slab.shm.unlink()
        except (FileNotFoundError, OSError):
            pass
        except BufferError:
            # a NumPy view over the segment is still alive (a late
            # rider reference, a recorder-held row): unlink the NAME so
            # the segment dies with the last mapping instead of leaking
            # past process exit, and leave the mapping to the GC
            try:
                slab.shm.unlink()
            except (FileNotFoundError, OSError):
                pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "created": self.created,
                "reused": self.reused,
                "free": len(self._free),
                "total": len(self._all),
                "bytes": sum(s.capacity for s in self._all),
            }

    def close(self) -> None:
        """Unlink every owned slab (idempotent).  The owner outlives
        every reader by protocol (a worker's response slabs die with
        the worker AFTER the parent read its last response)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            slabs, self._all, self._free = self._all, [], []
        for s in slabs:
            self._destroy(s)


class SlabAttacher:
    """Reader-side cache of attached segments (attach = a syscall +
    mmap; the steady state re-reads the same one or two slab names
    per worker)."""

    def __init__(self):
        self._attached: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _segment(self, name: str):
        with self._lock:
            seg = self._attached.get(name)
            if seg is None:
                from multiprocessing import shared_memory

                seg = shared_memory.SharedMemory(name=name)
                self._attached[name] = seg
            return seg

    def view(self, ref: dict) -> np.ndarray:
        """A zero-copy NumPy view over the referenced payload.  The
        view is valid only until the protocol allows the writer to
        reuse the slab — copy before crossing that boundary.  An
        optional ``offset`` field (bytes, default 0) lets one slab
        carry a payload past its head — the admission-block hand-off."""
        seg = self._segment(ref["slab"])
        dtype = np.dtype(ref["dtype"])
        shape = tuple(ref["shape"])
        nbytes = int(ref["nbytes"])
        offset = int(ref.get("offset", 0))
        if offset < 0 or offset + nbytes > seg.size:
            raise WireError(
                f"slab reference claims bytes [{offset}, {offset + nbytes}) "
                f"but segment {ref['slab']!r} holds {seg.size}"
            )
        return np.ndarray(
            shape, dtype=dtype, buffer=seg.buf[offset : offset + nbytes]
        )

    def read(self, ref: dict) -> np.ndarray:
        """An owning copy of the referenced payload (safe past slab
        reuse)."""
        return np.array(self.view(ref))

    def close(self) -> None:
        with self._lock:
            segs, self._attached = list(self._attached.values()), {}
        for seg in segs:
            try:
                seg.close()
            except OSError:
                pass

    def unlink_all(self) -> None:
        """Reap segments whose OWNER died without unlinking (a
        SIGKILLed worker's response slabs): close, unlink, and clear
        the dead owner's resource-tracker registration.  A segment the
        owner already unlinked is skipped silently."""
        with self._lock:
            segs, self._attached = list(self._attached.values()), {}
        for seg in segs:
            try:
                seg.close()
            except OSError:
                pass
            try:
                seg.unlink()
            except (FileNotFoundError, OSError):
                pass


def write_array(pool: SlabPool, arr: np.ndarray) -> Tuple[Slab, dict]:
    """Copy ``arr`` into a pool slab; returns ``(slab, reference)`` —
    the reference is what rides the control frame.  Non-contiguous
    inputs are made contiguous first (one copy either way)."""
    arr = np.ascontiguousarray(arr)
    slab = pool.acquire(arr.nbytes)
    dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=slab.buf[: arr.nbytes])
    np.copyto(dst, arr)
    del dst  # release the exported buffer view before any slab close
    ref = {
        "slab": slab.name,
        "shape": list(arr.shape),
        "dtype": arr.dtype.str,
        "nbytes": int(arr.nbytes),
    }
    return slab, ref


class SlabBlock:
    """An admission-owned padded batch living in ONE pool slab — the
    zero-copy hand-off between the ingress front end and the dispatch
    path.

    Rows ``[0, count)`` are request rows (the ingress reads client
    payload bytes straight off the socket into them); rows
    ``[count, padded_rows)`` are zero pad, pre-sized to the service's
    padding bucket so a flush of the whole block needs NO re-pad copy.
    :attr:`ref` is the slab reference a process worker can attach by
    name — the router ships it on the control frame instead of
    memcpy'ing the batch into a dispatch slab.

    Lifetime is refcounted in request rows: the admitting caller
    :meth:`retain`\\ s once per submitted future and each future's done
    callback :meth:`release_one`\\ s; the slab rejoins its pool only
    after the LAST future resolves, which by the strict
    request/response dispatch protocol is after any worker has read
    the payload (and after bisection's re-runs, which slice the same
    rows).  ``admission_block`` is the duck-typed marker
    ``PipelineService.submit_batch`` keys on — no wire import needed
    at the admission layer."""

    admission_block = True

    __slots__ = ("pool", "slab", "array", "count", "_refs", "_lock")

    def __init__(self, pool: SlabPool, slab: Slab, array: np.ndarray, count: int):
        self.pool = pool
        self.slab = slab
        self.array = array
        self.count = int(count)
        self._refs = 0
        self._lock = threading.Lock()

    @property
    def padded_rows(self) -> int:
        return int(self.array.shape[0])

    @property
    def ref(self) -> dict:
        """The dispatch slab reference for the WHOLE padded block."""
        return {
            "slab": self.slab.name,
            "shape": list(self.array.shape),
            "dtype": self.array.dtype.str,
            "nbytes": int(self.array.nbytes),
            "offset": 0,
        }

    def rows(self) -> list:
        """Per-request row views (zero-copy slices of the block)."""
        return [self.array[i] for i in range(self.count)]

    def retain(self, n: int = 1) -> None:
        with self._lock:
            self._refs += int(n)

    def release_one(self, _fut=None) -> None:
        """Drop one reference (signature-compatible with
        ``Future.add_done_callback``); the last one frees the slab."""
        with self._lock:
            self._refs -= 1
            if self._refs > 0:
                return
        self.close()

    def close(self) -> None:
        """Return the slab to the pool (idempotent).  The ndarray view
        is dropped first so a later ``pool.close()`` can unmap the
        segment."""
        with self._lock:
            slab, self.slab, self.array = self.slab, None, None
        if slab is not None:
            self.pool.release(slab)


def alloc_block(
    pool: SlabPool,
    count: int,
    item_shape: Tuple[int, ...],
    dtype,
    padded_rows: Optional[int] = None,
) -> SlabBlock:
    """Acquire a slab sized for ``padded_rows`` (default ``count``)
    items of ``item_shape``/``dtype`` and return the
    :class:`SlabBlock` over it, pad rows zeroed.  The caller fills
    rows ``[0, count)`` — typically by ``recv_into`` straight off a
    socket.  Raises :class:`PayloadTooLarge` past the pool cap."""
    count = int(count)
    padded = count if padded_rows is None else max(int(padded_rows), count)
    dtype = np.dtype(dtype)
    shape = (padded,) + tuple(int(d) for d in item_shape)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    slab = pool.acquire(nbytes)
    arr = np.ndarray(shape, dtype=dtype, buffer=slab.buf[:nbytes])
    if padded > count:
        arr[count:] = 0
    return SlabBlock(pool, slab, arr, count)


def send_frame(conn, msg: dict) -> None:
    conn.send_bytes(pack_frame(msg))


def recv_frame(conn, timeout: Optional[float] = None) -> dict:
    """Receive one frame; ``timeout`` (seconds) raises ``TimeoutError``
    instead of blocking forever — the ready-handshake path."""
    if timeout is not None and not conn.poll(timeout):
        raise TimeoutError(f"no frame within {timeout:.1f}s")
    return unpack_frame(conn.recv_bytes())


# ------------------------------------------------- cross-host framing (v2)


def pack_stream_frame(msg: dict, payload: bytes = b"") -> bytes:
    """Serialize one socket frame: prefix + JSON body + inline payload.
    The body carries the control message (op, flush id, array meta);
    ``payload`` is the raw array bytes for remote peers that cannot
    attach a slab."""
    if not isinstance(msg, dict):
        raise WireError(f"frame body must be a dict, got {type(msg).__name__}")
    try:
        body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    except (TypeError, ValueError) as e:
        raise WireError(f"unserializable frame body: {e}") from e
    payload = bytes(payload)
    crc = zlib.crc32(payload, zlib.crc32(body)) & 0xFFFFFFFF
    return (
        MAGIC
        + bytes([SOCKET_VERSION])
        + _STREAM_HEADER.pack(len(body), len(payload), crc)
        + body
        + payload
    )


def _wait_readable(sock, timeout: Optional[float]) -> bool:
    """``select``-based wait for readability; ``None`` blocks forever.

    Readers MUST wait this way rather than via ``settimeout``: the
    socket-object timeout is shared with the send direction (it caps the
    total duration of ``sendall``), and reader threads share the socket
    with sender threads — a reader that narrowed the timeout to its
    0.25s idle poll would abort any concurrent ``sendall`` that cannot
    flush within one poll interval, condemning a healthy channel the
    moment a sizeable payload meets a full kernel send buffer.  A socket
    closed out from under the wait surfaces as ``OSError``."""
    try:
        ready, _, _ = select.select([sock], [], [], timeout)
    except ValueError:
        # a concurrent close() already set fileno() to -1
        raise OSError("socket closed while waiting for a frame") from None
    return bool(ready)


def _recv_exact(sock, n: int, idle_timeout: Optional[float]) -> bytes:
    """Read exactly ``n`` bytes from ``sock``.

    ``idle_timeout`` bounds the wait for the FIRST byte only (an idle
    channel raises ``TimeoutError`` — the caller's poll loop); once any
    byte of a frame has arrived the rest must land within
    :data:`MID_FRAME_TIMEOUT_S` or the frame is declared torn
    (:class:`WireError`).  A peer that closes cleanly between frames
    raises ``EOFError``; a close MID-read is a truncated frame and
    raises :class:`WireError`.  All waiting rides
    :func:`_wait_readable`, so the socket's own timeout — the
    concurrent-send budget — is never disturbed.
    """
    chunks: List[bytes] = []
    got = 0
    while got < n:
        budget = idle_timeout if got == 0 else MID_FRAME_TIMEOUT_S
        if not _wait_readable(sock, budget):
            if got == 0:
                raise TimeoutError(f"no frame within {idle_timeout}s")
            raise WireError(
                f"stream frame stalled mid-read ({got}/{n} bytes)"
            )
        try:
            chunk = sock.recv(n - got)
        except (BlockingIOError, InterruptedError):
            continue  # spurious readability; re-arm the wait
        if not chunk:
            if got == 0:
                raise EOFError("peer closed the connection")
            raise WireError(
                f"truncated stream frame (peer closed at {got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_stream_frame(sock, msg: dict, payload: bytes = b"") -> None:
    """Write one socket frame (blocking ``sendall``)."""
    sock.sendall(pack_stream_frame(msg, payload))


def recv_stream_frame(
    sock,
    timeout: Optional[float] = None,
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
) -> Tuple[dict, bytes]:
    """Receive one socket frame; returns ``(msg, payload_bytes)``.

    ``timeout`` is the IDLE timeout (seconds until the first byte);
    raises ``TimeoutError`` when no frame starts in time, ``EOFError``
    on a clean close between frames, and :class:`WireError` on
    anything torn: truncation mid-frame, foreign magic, version skew,
    oversized length fields, or a CRC mismatch.
    """
    prefix = _recv_exact(sock, _STREAM_PREFIX_LEN, timeout)
    if prefix[: len(MAGIC)] != MAGIC:
        raise WireError("bad stream-frame magic (foreign or torn stream)")
    ver = prefix[len(MAGIC)]
    if ver != SOCKET_VERSION:
        raise WireError(
            f"stream-frame version {ver} != {SOCKET_VERSION} (peer skew)"
        )
    body_len, payload_len, crc = _STREAM_HEADER.unpack(
        prefix[len(MAGIC) + 1 :]
    )
    if body_len + payload_len > max_frame_bytes:
        raise WireError(
            f"stream frame claims {body_len + payload_len} bytes "
            f"(cap {max_frame_bytes}); refusing before allocation"
        )
    try:
        body = (
            _recv_exact(sock, body_len, MID_FRAME_TIMEOUT_S)
            if body_len
            else b""
        )
        payload = (
            _recv_exact(sock, payload_len, MID_FRAME_TIMEOUT_S)
            if payload_len
            else b""
        )
    except (TimeoutError, EOFError) as e:
        # the header already landed: any stall or close past it is a
        # torn frame, never an idle channel
        raise WireError(f"truncated stream frame: {e}") from None
    got_crc = zlib.crc32(payload, zlib.crc32(body)) & 0xFFFFFFFF
    if got_crc != crc:
        raise WireError(
            f"stream-frame CRC mismatch (got {got_crc:#010x}, "
            f"header {crc:#010x}) — bytes damaged in flight"
        )
    try:
        parsed = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparseable stream-frame body: {e}") from e
    if not isinstance(parsed, dict):
        raise WireError(
            f"stream-frame body must be a dict, got {type(parsed).__name__}"
        )
    return parsed, payload


def array_payload(arr: np.ndarray) -> Tuple[dict, bytes]:
    """``(meta, bytes)`` for shipping an array inline in a stream
    frame — the cross-host analogue of :func:`write_array`'s slab
    reference."""
    arr = np.ascontiguousarray(arr)
    meta = {
        "shape": list(arr.shape),
        "dtype": arr.dtype.str,
        "nbytes": int(arr.nbytes),
    }
    return meta, arr.tobytes()


def payload_array(meta: dict, payload: bytes) -> np.ndarray:
    """Rehydrate an inline array payload; raises :class:`WireError`
    when the meta and the byte count disagree (a mismatch that survived
    the CRC means the SENDER was confused — fail loudly)."""
    dtype = np.dtype(meta["dtype"])
    shape = tuple(int(d) for d in meta["shape"])
    expect = int(meta["nbytes"])
    if len(payload) != expect:
        raise WireError(
            f"array payload carries {len(payload)} bytes but meta "
            f"claims {expect}"
        )
    return np.frombuffer(payload, dtype=dtype).reshape(shape).copy()
