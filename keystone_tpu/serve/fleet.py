"""Replica fleet: N frozen appliers behind a least-outstanding router.

One `PipelineService` batcher thread draining onto one `FrozenApplier`
(PR 5) saturates exactly one device; the "millions of users" direction
(ROADMAP item 1) needs every local device serving and a live model-swap
story.  This module is that layer:

- **Replica** — one :class:`~keystone_tpu.workflow.pipeline.FrozenApplier`
  pinned to one device.  Multi-replica pools clone the fitted pipeline
  per replica (pickle round-trip) and re-place every fitted device array
  with an explicit ``jax.device_put`` onto the replica's device, so each
  flush's computation lands where its parameters live (committed inputs
  pin XLA placement).  Each replica owns a worker thread with a private
  flush queue — while replica 0 computes, the batcher is already
  dispatching the next flush to replica 1 — and a per-replica
  :class:`~keystone_tpu.utils.guard.CircuitBreaker` (key
  ``<service>.replica.<i>``) charged by flush outcomes.
- **ReplicaPool** — the router.  ``dispatch`` picks the replica with the
  fewest outstanding flushes whose breaker admits work (a tripped
  replica is routed *around* until its half-open probe); when every
  breaker refuses, the least-loaded replica serves anyway (degraded
  service beats refusing the whole fleet — counted as
  ``serve.router_forced``).
- **Blue/green swap** — ``stage()`` builds a full staged generation of
  replicas for a new model version on the same devices (the caller
  primes their padding-bucket programs while the old generation keeps
  serving); ``commit()`` swaps the routing list under the router lock —
  the swap pause IS that lock-held window, microseconds — and retires
  the old generation: each old worker drains its already-queued flushes
  before exiting, so queued requests never drop and in-flight requests
  resolve from the version that admitted them.

Observability: per-replica series share the label key ``replica``
(``serve.replica_flushes{replica=i}`` counter,
``serve.replica_outstanding{replica=i}`` / queue-share gauges) — one
metric name per quantity, fan-out via labels, which is the convention
``tools/lint.py`` now enforces.  Fault site ``serve.replica`` fires on
every live flush's replica apply (chaos: fail/stall one flush, trip a
breaker, exercise failover).

The single-replica default (``replicas=1``, no devices) wraps the given
pipeline's applier directly — no clone, no placement — so the PR-5
service behavior, program counts, and byte-identity pins are exactly
unchanged.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from typing import Callable, List, Optional, Sequence

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

#: replica breakers default to a short reset so a swapped-in healthy
#: model is probed within seconds, not the 30 s stage-retry default
DEFAULT_REPLICA_BREAKER_RESET = 5.0


def _place_on_device(obj, device, _seen=None, _depth=0):
    """Recursively ``jax.device_put`` every device array reachable from
    ``obj`` onto ``device``; containers/attributes are updated in place
    where possible (the mirror of ``executor.block_on_arrays``'s walk —
    same depth cap, same "has block_until_ready" leaf test).  Returns
    the — possibly replaced — object.  ``_seen`` maps ``id(original)``
    to the placed result so an array referenced from two sites gets ONE
    placed copy at both — a set-based guard would re-place the first
    reference and leave the alias on the default device, and XLA
    rejects the resulting mixed placement on every flush."""
    import jax

    if _depth > 8 or obj is None or isinstance(obj, (str, bytes, int, float, bool)):
        return obj
    if _seen is None:
        _seen = {}
    if id(obj) in _seen:
        return _seen[id(obj)]
    if hasattr(obj, "block_until_ready"):
        placed = jax.device_put(obj, device)
        _seen[id(obj)] = placed
        return placed
    _seen[id(obj)] = obj  # containers: in-place update, cycle-safe
    if isinstance(obj, dict):
        for k in list(obj):
            obj[k] = _place_on_device(obj[k], device, _seen, _depth + 1)
        return obj
    if isinstance(obj, list):
        for i in range(len(obj)):
            obj[i] = _place_on_device(obj[i], device, _seen, _depth + 1)
        return obj
    if isinstance(obj, tuple):
        new = type(obj)(
            _place_on_device(v, device, _seen, _depth + 1) for v in obj
        )
        _seen[id(obj)] = new  # aliases of the tuple get the rebuilt one
        return new
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        for k, v in list(vars(obj).items()):
            nv = _place_on_device(v, device, _seen, _depth + 1)
            if nv is not v:
                setattr(obj, k, nv)
        return obj
    return obj


def _clone_and_place(pipeline, device):
    """An independent copy of a fitted pipeline with its fitted state
    committed to ``device`` (None = leave placement alone).  The clone
    is a pickle round-trip — the same serialization contract
    ``FittedPipeline.save``/``load`` already pin — so replicas share no
    transformer instances and therefore no per-instance jit caches:
    each replica compiles (and keeps hot) its own bucket programs
    against its own device."""
    clone = pickle.loads(pickle.dumps(pipeline))
    if device is not None:
        for op in clone.graph.operators.values():
            t = getattr(op, "transformer", None)
            if t is not None:
                _place_on_device(t, device)
    return clone


def _as_applier(pipeline):
    from keystone_tpu.workflow.pipeline import FrozenApplier

    return (
        pipeline
        if isinstance(pipeline, FrozenApplier)
        else FrozenApplier(pipeline)
    )


_SENTINEL = object()


class Replica:
    """One frozen applier pinned to one device, plus its flush worker,
    queue, breaker, and counters.  Constructed by :class:`ReplicaPool`."""

    def __init__(
        self,
        index: int,
        applier,
        device=None,
        version: str = "v0",
        breaker: Optional[guard.CircuitBreaker] = None,
        pool_name: str = "serve",
    ):
        self.index = int(index)
        self.applier = applier
        self.device = device
        self.version = version
        self.pool_name = pool_name
        self.breaker = breaker or guard.CircuitBreaker(
            f"{pool_name}.replica.{index}",
            reset_timeout=DEFAULT_REPLICA_BREAKER_RESET,
        )
        #: dispatched-but-unfinished flushes (queued + in flight);
        #: guarded by the owning pool's lock — the router reads it
        self.outstanding = 0
        self.flushes = 0
        self.errors = 0
        self._q: list = []
        self._cond = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._retired = False

    # ------------------------------------------------------------ apply
    def apply(self, ds, deadline=None, prime: bool = False):
        """Run the frozen graph over one padded batch on THIS replica.
        Live flushes pass through the ``serve.replica`` fault site;
        priming warm-ups (``prime=True``) do not — chaos plans target
        traffic, not warm-up."""
        if not prime:
            fault_point("serve.replica", replica=self.index)
        return self.applier(ds, deadline=deadline)

    # ----------------------------------------------------------- worker
    def start(self, runner: Callable, obs_context=None) -> None:
        """Spawn the flush worker: pops queued items and hands them to
        ``runner(replica, batch)`` until the retire sentinel.
        ``obs_context``: a ``ledger.capture_context`` token restored at
        worker start, so the runner's ledger spans (``serve.batch`` and
        the executor stages under it) parent where the service was
        constructed instead of floating rootless on this thread."""

        def loop():
            ledger.restore_context(obs_context)
            while True:
                with self._cond:
                    while not self._q:
                        self._cond.wait()
                    item = self._q.pop(0)
                if item is _SENTINEL:
                    return
                try:
                    runner(self, item)
                except BaseException:  # runner owns failure delivery
                    logger.exception(
                        "replica %d flush runner raised", self.index
                    )

        self._worker = threading.Thread(
            target=loop,
            daemon=True,
            name=f"{self.pool_name}-replica{self.index}",
        )
        self._worker.start()

    def enqueue(self, batch) -> None:
        with self._cond:
            self._q.append(batch)
            self._cond.notify()

    def retire(self) -> None:
        """Queue the stop sentinel BEHIND any already-dispatched flushes
        — the worker drains them first, so a swap never drops work."""
        with self._cond:
            if not self._retired:
                self._retired = True
                self._q.append(_SENTINEL)
                self._cond.notify()

    def join(self, timeout: float) -> List:
        """Wait for the worker to exit; returns any batches left in the
        queue so the caller can fail their futures — a wedged worker's
        abandoned flushes, or flushes enqueued after retirement (the
        worker exits at the sentinel and never sees what lands behind
        it)."""
        if self._worker is not None:
            self._worker.join(timeout)
        with self._cond:
            left = [b for b in self._q if b is not _SENTINEL]
            self._q.clear()
        return left

    def status(self) -> dict:
        return {
            "replica": self.index,
            "device": str(self.device) if self.device is not None else None,
            "version": self.version,
            "breaker": self.breaker.state(),
            "outstanding": self.outstanding,
            "flushes": self.flushes,
            "errors": self.errors,
        }


class ReplicaPool:
    """N replicas + the least-outstanding router + blue/green swap.

    ``pipeline``: a fitted pipeline (or ``FrozenApplier``).  With
    ``replicas=1`` and no explicit devices the pool wraps the given
    applier directly (the PR-5 single-device behavior, bit-for-bit);
    with more, each replica gets an independent clone of the fitted
    state ``jax.device_put`` onto its device (``devices=None`` cycles
    ``jax.local_devices()``)."""

    def __init__(
        self,
        pipeline,
        replicas: int = 1,
        devices: Optional[Sequence] = None,
        version: str = "v0",
        name: str = "serve",
        dispatch_window: int = 2,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if dispatch_window < 1:
            raise ValueError(
                f"dispatch_window must be >= 1, got {dispatch_window}"
            )
        self.name = name
        self._lock = threading.Lock()
        #: flow control between the batcher and the replica queues:
        #: ``dispatch`` blocks while EVERY replica already holds
        #: ``dispatch_window`` outstanding flushes (one computing + one
        #: queued behind it, by default).  Without this bound the
        #: batcher drains the admission queue into the replicas' private
        #: queues at line rate, the admission queue never fills, and
        #: overload bypasses ``Overloaded`` backpressure entirely —
        #: excess work queues invisibly and completes past its deadline
        #: instead of being rejected at submit.
        self._window = int(dispatch_window)
        self._cond = threading.Condition(self._lock)
        self._draining = False
        self._runner: Optional[Callable] = None
        self._obs_ctx = None
        self.version = version
        self.replicas: List[Replica] = self._build(
            pipeline, int(replicas), devices, version
        )

    # ------------------------------------------------------------ build
    def _devices_for(self, n: int, devices) -> list:
        if devices is not None:
            devices = list(devices)
            if not devices:
                raise ValueError("devices must be non-empty when given")
            return [devices[i % len(devices)] for i in range(n)]
        if n == 1:
            return [None]  # single replica: no placement, no clone
        import jax

        local = jax.local_devices()
        return [local[i % len(local)] for i in range(n)]

    def _build(self, pipeline, n: int, devices, version) -> List[Replica]:
        devs = self._devices_for(n, devices)
        out = []
        for i, dev in enumerate(devs):
            if dev is None and n == 1:
                applier = _as_applier(pipeline)
            else:
                applier = _as_applier(_clone_and_place(pipeline, dev))
            out.append(
                Replica(
                    i,
                    applier,
                    device=dev,
                    version=version,
                    pool_name=self.name,
                )
            )
        return out

    @property
    def size(self) -> int:
        return len(self.replicas)

    # ----------------------------------------------------------- router
    def start(self, runner: Callable, obs_context=None) -> None:
        """Start every replica worker; ``runner(replica, batch)`` is the
        service's flush body (shed + pad + apply + resolve futures).
        ``obs_context`` (a ``ledger.capture_context`` token) is restored
        in every worker — including staged generations built later — so
        span parenting survives the replica threads."""
        self._runner = runner
        self._obs_ctx = obs_context
        for r in self.replicas:
            r.start(runner, obs_context)

    def dispatch(self, batch) -> Replica:
        """Route one batch: least outstanding work first, skipping
        replicas whose breaker refuses (``allow()`` on the chosen
        replica doubles as the half-open probe admission).  All-open
        falls back to the least-loaded replica — refusing the entire
        fleet would turn one bad model generation into a total outage,
        and the probe path needs traffic to ever close a breaker.

        Blocks while every replica is at the dispatch window — the
        backpressure that makes submit-side admission control real (the
        bound is per-replica occupancy, so it is soft in the degraded
        all-breakers-open case where routing ignores load)."""
        with self._cond:
            while (
                not self._draining
                and self.replicas
                and min(r.outstanding for r in self.replicas) >= self._window
            ):
                # timed: a commit/complete notify can land between the
                # predicate and the wait on another generation's list
                self._cond.wait(0.05)
            order = sorted(self.replicas, key=lambda r: (r.outstanding, r.index))
            chosen = None
            for r in order:
                if r.breaker.allow():
                    chosen = r
                    break
            if chosen is None:
                chosen = order[0]
                metrics.inc("serve.router_forced")
            chosen.outstanding += 1
            metrics.set_gauge(
                "serve.replica_outstanding",
                chosen.outstanding,
                replica=chosen.index,
            )
            # enqueue UNDER the router lock: commit() retires the old
            # generation only after taking this lock, so a batch routed
            # to an old replica is queued ahead of the retire sentinel
            # and the draining worker still serves it.  Enqueued outside
            # the lock, a concurrent swap could slot the sentinel first
            # and the batch's futures would hang forever (swap-retired
            # replicas are never join()ed).
            chosen.enqueue(batch)
        return chosen

    def complete(self, replica: Replica, ok: Optional[bool]) -> None:
        """Account one finished flush: outstanding/queue-share updates
        plus the breaker charge.  ``ok=True`` records a success (closes
        a half-open breaker), ``ok=False`` a failure (accumulates toward
        open), ``ok=None`` is NEUTRAL — nothing ran on the device
        (shed/cancelled-only flush), so it must neither pass a half-open
        probe nor reset the consecutive-failure streak: a sick replica
        shedding 100% of its riders would otherwise keep its breaker
        closed exactly when failover matters most."""
        with self._cond:
            replica.outstanding = max(0, replica.outstanding - 1)
            self._cond.notify_all()
            replica.flushes += 1
            if ok is False:
                replica.errors += 1
            metrics.set_gauge(
                "serve.replica_outstanding",
                replica.outstanding,
                replica=replica.index,
            )
            metrics.inc("serve.replica_flushes", replica=replica.index)
            if ok is False:
                metrics.inc("serve.replica_errors", replica=replica.index)
            total = sum(r.flushes for r in self.replicas) or 1
            for r in self.replicas:
                metrics.set_gauge(
                    "serve.replica_queue_share",
                    r.flushes / total,
                    replica=r.index,
                )
        if ok is True:
            replica.breaker.record_success()
        elif ok is False:
            replica.breaker.record_failure()

    # ------------------------------------------------------------- swap
    def stage(self, pipeline, version: str) -> List[Replica]:
        """Build (and start) a full staged generation for ``version`` on
        the same devices as the current one.  Staged replicas accept
        priming applies but receive no routed traffic until
        :meth:`commit` — the old generation keeps serving."""
        devices = [r.device for r in self.replicas]
        n = len(devices)
        if n == 1 and devices[0] is None:
            staged = [
                Replica(
                    0,
                    _as_applier(_clone_and_place(pipeline, None)),
                    device=None,
                    version=version,
                    pool_name=self.name,
                )
            ]
        else:
            staged = [
                Replica(
                    i,
                    _as_applier(_clone_and_place(pipeline, dev)),
                    device=dev,
                    version=version,
                    pool_name=self.name,
                )
                for i, dev in enumerate(devices)
            ]
        if self._runner is not None:
            for r in staged:
                r.start(self._runner, self._obs_ctx)
        return staged

    def commit(self, staged: List[Replica], version: str) -> float:
        """Atomically install a staged generation; returns the swap
        pause in seconds — the router-lock-held window during which no
        flush could be dispatched.  Old workers retire AFTER the lock is
        released: they drain their queued flushes, then exit."""
        t0 = time.perf_counter()
        with self._cond:
            refused = self._draining
            if not refused:
                old, self.replicas = self.replicas, staged
                self.version = version
                pause = time.perf_counter() - t0
                # the fresh generation has zero outstanding work: wake a
                # batcher blocked on the old generation's window
                self._cond.notify_all()
        if refused:
            # the pool is closing: installing a fresh generation now
            # would leak its worker threads (close() has already
            # snapshotted the replicas it will retire).  Retire the
            # staged workers instead and refuse the swap.
            for r in staged:
                r.retire()
            raise RuntimeError(
                f"replica pool {self.name!r} is closing; swap commit refused"
            )
        for r in old:
            r.retire()
        return pause

    # ------------------------------------------------------------ close
    def begin_drain(self) -> None:
        """Release a ``dispatch`` blocked at the dispatch window: with
        draining set it dispatches regardless, so the batch lands in a
        replica queue where :meth:`close` can collect and hand it back
        instead of the batcher holding it in-hand forever.  The service
        calls this BEFORE joining its batcher thread — otherwise a
        batcher blocked on a wedged fleet burns the whole join timeout
        and its in-hand batch's futures never resolve."""
        with self._lock:
            self._draining = True
            self._cond.notify_all()

    def close(self, timeout: float = 30.0) -> List:
        """Retire and join every replica; returns batches abandoned by
        wedged workers (the service fails their futures)."""
        self.begin_drain()
        with self._lock:
            replicas = list(self.replicas)
        abandoned: List = []
        for r in replicas:
            r.retire()
        deadline = time.monotonic() + timeout
        for r in replicas:
            abandoned.extend(r.join(max(0.1, deadline - time.monotonic())))
        return abandoned

    def statuses(self) -> List[dict]:
        with self._lock:
            replicas = list(self.replicas)
        return [r.status() for r in replicas]
